// Lock-free host event recorder for the profiler.
//
// TPU-native analog of the reference's HostEventRecorder
// (paddle/fluid/platform/profiler/host_event_recorder.h: thread-local
// event buffers drained by the HostTracer) — here a single fixed-capacity
// ring written with one atomic fetch_add per event, so instrumented op
// dispatch never takes a lock and never allocates on the hot path.
// Python drains it after Profiler.stop() via ht_read.
//
// Concurrency contract:
//   * writers reserve a slot with fetch_add, fill it, then publish it via
//     a per-slot ready flag (release); readers check the flag (acquire),
//     so a torn/in-progress slot is never observed;
//   * ht_stop spins until in-flight writers have left before freeing, so
//     a writer that raced past the enabled check cannot touch freed
//     memory.
//
// C ABI (ctypes-consumed by paddle_tpu/profiler):
//   ht_start(capacity)            allocate + reset the ring
//   ht_record(name,start,end,tid) append one span (lock-free, truncates
//                                 name to 63 chars)
//   ht_count()                    events recorded (may exceed capacity;
//                                 ring keeps the first `capacity`)
//   ht_read(i, ...)               copy out event i (fails on unpublished)
//   ht_stop()                     quiesce writers + free the ring
#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>

namespace {

struct Event {
  char name[64];
  uint64_t start_ns;
  uint64_t end_ns;
  uint64_t tid;
};

Event* g_ring = nullptr;
std::atomic<uint8_t>* g_ready = nullptr;
uint64_t g_capacity = 0;
std::atomic<uint64_t> g_count{0};
std::atomic<bool> g_enabled{false};
std::atomic<uint64_t> g_writers{0};

}  // namespace

extern "C" {

int ht_start(uint64_t capacity) {
  if (g_enabled.load(std::memory_order_acquire)) return -1;
  delete[] g_ring;
  delete[] g_ready;
  g_ring = new (std::nothrow) Event[capacity];
  g_ready = new (std::nothrow) std::atomic<uint8_t>[capacity];
  if (!g_ring || !g_ready) {
    delete[] g_ring;
    delete[] g_ready;
    g_ring = nullptr;
    g_ready = nullptr;
    return -1;
  }
  for (uint64_t i = 0; i < capacity; ++i)
    g_ready[i].store(0, std::memory_order_relaxed);
  g_capacity = capacity;
  g_count.store(0, std::memory_order_relaxed);
  g_enabled.store(true, std::memory_order_release);
  return 0;
}

void ht_record(const char* name, uint64_t start_ns, uint64_t end_ns,
               uint64_t tid) {
  g_writers.fetch_add(1, std::memory_order_seq_cst);
  // seq_cst pairing with ht_stop's (enabled store, writers load): either
  // this thread sees enabled==false and skips, or ht_stop's writers load
  // sees our increment and waits — store-load reordering is excluded
  if (g_enabled.load(std::memory_order_seq_cst)) {
    uint64_t idx = g_count.fetch_add(1, std::memory_order_relaxed);
    if (idx < g_capacity) {
      Event& e = g_ring[idx];
      std::strncpy(e.name, name ? name : "", sizeof(e.name) - 1);
      e.name[sizeof(e.name) - 1] = '\0';
      e.start_ns = start_ns;
      e.end_ns = end_ns;
      e.tid = tid;
      g_ready[idx].store(1, std::memory_order_release);  // publish
    }
  }
  g_writers.fetch_sub(1, std::memory_order_release);
}

uint64_t ht_count() { return g_count.load(std::memory_order_relaxed); }

uint64_t ht_capacity() { return g_capacity; }

int ht_read(uint64_t i, char* name_out, uint64_t name_cap,
            uint64_t* start_ns, uint64_t* end_ns, uint64_t* tid) {
  if (!g_ring || i >= g_capacity) return -1;
  if (g_ready[i].load(std::memory_order_acquire) == 0) return -1;
  const Event& e = g_ring[i];
  std::strncpy(name_out, e.name, name_cap - 1);
  name_out[name_cap - 1] = '\0';
  *start_ns = e.start_ns;
  *end_ns = e.end_ns;
  *tid = e.tid;
  return 0;
}

void ht_stop() {
  g_enabled.store(false, std::memory_order_seq_cst);
  // quiesce: wait for racing writers to drain before freeing (seq_cst —
  // see the pairing note in ht_record)
  while (g_writers.load(std::memory_order_seq_cst) != 0) {
  }
  delete[] g_ring;
  delete[] g_ready;
  g_ring = nullptr;
  g_ready = nullptr;
  g_capacity = 0;
  g_count.store(0, std::memory_order_relaxed);
}

}  // extern "C"
