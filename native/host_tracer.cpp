// Lock-free host event recorder for the profiler.
//
// TPU-native analog of the reference's HostEventRecorder
// (paddle/fluid/platform/profiler/host_event_recorder.h: thread-local
// event buffers drained by the HostTracer) — here a single fixed-capacity
// ring written with one atomic fetch_add per event, so instrumented op
// dispatch never takes a lock and never allocates on the hot path.
// Python drains it after Profiler.stop() via ht_read.
//
// Concurrency contract:
//   * writers reserve a slot with fetch_add, fill it, then publish it via
//     a per-slot ready flag (release); readers check the flag (acquire),
//     so a torn/in-progress slot is never observed;
//   * ht_stop spins until in-flight writers have left before freeing, so
//     a writer that raced past the enabled check cannot touch freed
//     memory.
//
// C ABI (ctypes-consumed by paddle_tpu/profiler):
//   ht_start(capacity)            allocate + reset the ring
//   ht_record(name,start,end,tid) append one span (lock-free, truncates
//                                 name to 63 chars)
//   ht_count()                    events recorded (may exceed capacity;
//                                 ring keeps the first `capacity`)
//   ht_read(i, ...)               copy out event i (fails on unpublished)
//   ht_stop()                     quiesce writers + free the ring
//
// A second, independent ring backs the crash flight recorder
// (paddle_tpu/observability/flight_recorder.py): unlike the profiler ring
// it WRAPS — it always holds the most recent `capacity` events — and each
// slot carries a seqlock so a postmortem reader racing a writer skips the
// torn slot instead of reporting garbage:
//   fr_start(capacity)                       allocate + reset
//   fr_record(kind,name,start,end,tid,aux)   append, overwriting oldest
//   fr_count()                               total events ever recorded
//   fr_read(i, ...)                          event i of the retained
//                                            window, oldest first
//   fr_stop()                                quiesce + free
#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>

namespace {

struct Event {
  char name[64];
  uint64_t start_ns;
  uint64_t end_ns;
  uint64_t tid;
};

Event* g_ring = nullptr;
std::atomic<uint8_t>* g_ready = nullptr;
uint64_t g_capacity = 0;
std::atomic<uint64_t> g_count{0};
std::atomic<bool> g_enabled{false};
std::atomic<uint64_t> g_writers{0};

}  // namespace

extern "C" {

int ht_start(uint64_t capacity) {
  if (g_enabled.load(std::memory_order_acquire)) return -1;
  delete[] g_ring;
  delete[] g_ready;
  g_ring = new (std::nothrow) Event[capacity];
  g_ready = new (std::nothrow) std::atomic<uint8_t>[capacity];
  if (!g_ring || !g_ready) {
    delete[] g_ring;
    delete[] g_ready;
    g_ring = nullptr;
    g_ready = nullptr;
    return -1;
  }
  for (uint64_t i = 0; i < capacity; ++i)
    g_ready[i].store(0, std::memory_order_relaxed);
  g_capacity = capacity;
  g_count.store(0, std::memory_order_relaxed);
  g_enabled.store(true, std::memory_order_release);
  return 0;
}

void ht_record(const char* name, uint64_t start_ns, uint64_t end_ns,
               uint64_t tid) {
  g_writers.fetch_add(1, std::memory_order_seq_cst);
  // seq_cst pairing with ht_stop's (enabled store, writers load): either
  // this thread sees enabled==false and skips, or ht_stop's writers load
  // sees our increment and waits — store-load reordering is excluded
  if (g_enabled.load(std::memory_order_seq_cst)) {
    uint64_t idx = g_count.fetch_add(1, std::memory_order_relaxed);
    if (idx < g_capacity) {
      Event& e = g_ring[idx];
      std::strncpy(e.name, name ? name : "", sizeof(e.name) - 1);
      e.name[sizeof(e.name) - 1] = '\0';
      e.start_ns = start_ns;
      e.end_ns = end_ns;
      e.tid = tid;
      g_ready[idx].store(1, std::memory_order_release);  // publish
    }
  }
  g_writers.fetch_sub(1, std::memory_order_release);
}

uint64_t ht_count() { return g_count.load(std::memory_order_relaxed); }

uint64_t ht_capacity() { return g_capacity; }

int ht_read(uint64_t i, char* name_out, uint64_t name_cap,
            uint64_t* start_ns, uint64_t* end_ns, uint64_t* tid) {
  if (!g_ring || i >= g_capacity) return -1;
  if (g_ready[i].load(std::memory_order_acquire) == 0) return -1;
  const Event& e = g_ring[i];
  std::strncpy(name_out, e.name, name_cap - 1);
  name_out[name_cap - 1] = '\0';
  *start_ns = e.start_ns;
  *end_ns = e.end_ns;
  *tid = e.tid;
  return 0;
}

void ht_stop() {
  g_enabled.store(false, std::memory_order_seq_cst);
  // quiesce: wait for racing writers to drain before freeing (seq_cst —
  // see the pairing note in ht_record)
  while (g_writers.load(std::memory_order_seq_cst) != 0) {
  }
  delete[] g_ring;
  delete[] g_ready;
  g_ring = nullptr;
  g_ready = nullptr;
  g_capacity = 0;
  g_count.store(0, std::memory_order_relaxed);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Flight-recorder ring: wrapping, per-slot seqlock.
// ---------------------------------------------------------------------------

namespace {

struct FrEvent {
  char name[64];
  uint64_t start_ns;
  uint64_t end_ns;
  uint64_t tid;
  uint64_t aux;  // payload bytes for collectives, samples for steps
  uint32_t kind;  // 0=op 1=comm 2=step 3=user
};

FrEvent* g_fr_ring = nullptr;
std::atomic<uint64_t>* g_fr_seq = nullptr;  // odd while a write is in flight
uint64_t g_fr_capacity = 0;
std::atomic<uint64_t> g_fr_count{0};
std::atomic<bool> g_fr_enabled{false};
std::atomic<uint64_t> g_fr_writers{0};

}  // namespace

extern "C" {

int fr_start(uint64_t capacity) {
  if (capacity == 0 || g_fr_enabled.load(std::memory_order_acquire))
    return -1;
  delete[] g_fr_ring;
  delete[] g_fr_seq;
  g_fr_ring = new (std::nothrow) FrEvent[capacity];
  g_fr_seq = new (std::nothrow) std::atomic<uint64_t>[capacity];
  if (!g_fr_ring || !g_fr_seq) {
    delete[] g_fr_ring;
    delete[] g_fr_seq;
    g_fr_ring = nullptr;
    g_fr_seq = nullptr;
    return -1;
  }
  for (uint64_t i = 0; i < capacity; ++i)
    g_fr_seq[i].store(0, std::memory_order_relaxed);
  g_fr_capacity = capacity;
  g_fr_count.store(0, std::memory_order_relaxed);
  g_fr_enabled.store(true, std::memory_order_release);
  return 0;
}

void fr_record(uint32_t kind, const char* name, uint64_t start_ns,
               uint64_t end_ns, uint64_t tid, uint64_t aux) {
  g_fr_writers.fetch_add(1, std::memory_order_seq_cst);
  // same seq_cst pairing as ht_record/ht_stop: either we see
  // enabled==false and skip, or fr_stop sees our increment and waits
  if (g_fr_enabled.load(std::memory_order_seq_cst)) {
    uint64_t idx = g_fr_count.fetch_add(1, std::memory_order_relaxed);
    uint64_t slot = idx % g_fr_capacity;
    // seqlock write: CAS even->odd acquires the slot, so seq is NEVER
    // even while any writer is mid-write — a reader seeing an even,
    // unchanged seq is guaranteed an untorn copy. A writer that finds
    // the slot odd has been lapped by a full ring wrap mid-write; it
    // drops its (older) event rather than corrupt the newer one.
    uint64_t s = g_fr_seq[slot].load(std::memory_order_relaxed);
    bool acquired = false;
    while (!(s & 1)) {
      if (g_fr_seq[slot].compare_exchange_weak(
              s, s + 1, std::memory_order_acq_rel,
              std::memory_order_relaxed)) {
        acquired = true;
        break;
      }
    }
    if (acquired) {
      FrEvent& e = g_fr_ring[slot];
      std::strncpy(e.name, name ? name : "", sizeof(e.name) - 1);
      e.name[sizeof(e.name) - 1] = '\0';
      e.start_ns = start_ns;
      e.end_ns = end_ns;
      e.tid = tid;
      e.aux = aux;
      e.kind = kind;
      g_fr_seq[slot].store(s + 2, std::memory_order_release);
    }
  }
  g_fr_writers.fetch_sub(1, std::memory_order_release);
}

uint64_t fr_count() { return g_fr_count.load(std::memory_order_relaxed); }

uint64_t fr_capacity() { return g_fr_capacity; }

// Read event i of the retained window (i in [0, min(count, capacity)),
// oldest first). Returns -1 for out-of-range, torn, or mid-rewrite slots.
// Known benign imprecision: a slot whose index was claimed but whose write
// has not yet landed (or was dropped by a lapped writer) still holds the
// previous lap's event, which is returned as-is — a crash dump may show
// one capacity-old event where the newest would be. A strict lap check on
// seq cannot distinguish this from the drop case (drops leave seq behind
// forever), so postmortem readers tolerate it instead.
int fr_read(uint64_t i, uint32_t* kind, char* name_out, uint64_t name_cap,
            uint64_t* start_ns, uint64_t* end_ns, uint64_t* tid,
            uint64_t* aux) {
  // readers ride the same in-flight counter as writers so fr_stop cannot
  // free the ring under a concurrent read (SIGUSR1 dump vs. disable())
  struct Guard {
    Guard() { g_fr_writers.fetch_add(1, std::memory_order_seq_cst); }
    ~Guard() { g_fr_writers.fetch_sub(1, std::memory_order_release); }
  } guard;
  if (!g_fr_enabled.load(std::memory_order_seq_cst)) return -1;
  if (!g_fr_ring || g_fr_capacity == 0 || name_cap == 0) return -1;
  uint64_t total = g_fr_count.load(std::memory_order_acquire);
  uint64_t n = total < g_fr_capacity ? total : g_fr_capacity;
  if (i >= n) return -1;
  uint64_t slot = (total - n + i) % g_fr_capacity;
  uint64_t s0 = g_fr_seq[slot].load(std::memory_order_acquire);
  if (s0 == 0 || (s0 & 1)) return -1;  // unwritten or write in flight
  const FrEvent e = g_fr_ring[slot];   // copy out, then validate
  // order the (non-atomic) field loads before the revalidating seq load —
  // without the fence a weakly-ordered CPU may satisfy them afterwards
  // and a torn copy would pass the unchanged-seq check
  std::atomic_thread_fence(std::memory_order_acquire);
  if (g_fr_seq[slot].load(std::memory_order_relaxed) != s0) return -1;
  std::strncpy(name_out, e.name, name_cap - 1);
  name_out[name_cap - 1] = '\0';
  *kind = e.kind;
  *start_ns = e.start_ns;
  *end_ns = e.end_ns;
  *tid = e.tid;
  *aux = e.aux;
  return 0;
}

void fr_stop() {
  g_fr_enabled.store(false, std::memory_order_seq_cst);
  while (g_fr_writers.load(std::memory_order_seq_cst) != 0) {
  }
  delete[] g_fr_ring;
  delete[] g_fr_seq;
  g_fr_ring = nullptr;
  g_fr_seq = nullptr;
  g_fr_capacity = 0;
  g_fr_count.store(0, std::memory_order_relaxed);
}

}  // extern "C"
