// TCPStore — key-value rendezvous over raw TCP.
//
// Native counterpart of the reference's C++ store
// (paddle/phi/core/distributed/store/tcp_store.cc + tcp_utils.cc): the
// master host listens, every participant connects, and the store answers
// SET/GET/ADD/WAIT/DELETE — the primitive under comm-id exchange, barriers,
// and elastic membership (SURVEY.md §2.4). Python binds via ctypes
// (paddle_tpu/distributed/tcp_store.py); no pybind11 dependency.
//
// Protocol (all integers little-endian):
//   request : u8 cmd | u32 klen | key bytes | u32 vlen | value bytes
//   response: i64 status_or_int | u32 vlen | value bytes
// Commands: 0=SET 1=GET 2=ADD(value = i64 delta) 3=WAIT(value = i64
// timeout_ms, -1 = forever) 4=DELETE 5=PING 6=DELETE_PREFIX
// GET on a missing key returns status -1; WAIT blocks until the key exists,
// returning -3 on timeout and -4 if the server is shutting down.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <list>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  bool stopping = false;
};

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_response(int fd, int64_t status, const std::string& value) {
  uint32_t vlen = static_cast<uint32_t>(value.size());
  if (!write_exact(fd, &status, sizeof(status))) return false;
  if (!write_exact(fd, &vlen, sizeof(vlen))) return false;
  if (vlen && !write_exact(fd, value.data(), vlen)) return false;
  return true;
}

// One accepted connection. The server (accept-loop reap or stop) owns the
// fd's close and the thread's join; serve_client only flags completion —
// closing here would let the kernel reuse the descriptor number while it is
// still in the server's list, so stop() could shutdown an unrelated socket.
struct ClientSlot {
  int fd = -1;
  std::thread th;
  std::atomic<bool> done{false};
};

void serve_client(Store* store, ClientSlot* slot) {
  const int fd = slot->fd;
  for (;;) {
    uint8_t cmd;
    uint32_t klen = 0, vlen = 0;
    if (!read_exact(fd, &cmd, 1)) break;
    if (!read_exact(fd, &klen, 4)) break;
    std::string key(klen, '\0');
    if (klen && !read_exact(fd, key.data(), klen)) break;
    if (!read_exact(fd, &vlen, 4)) break;
    std::string value(vlen, '\0');
    if (vlen && !read_exact(fd, value.data(), vlen)) break;

    bool ok = true;
    switch (cmd) {
      case 0: {  // SET
        {
          std::lock_guard<std::mutex> g(store->mu);
          store->kv[key] = value;
        }
        store->cv.notify_all();
        ok = send_response(fd, 0, "");
        break;
      }
      case 1: {  // GET — copy out under the lock, send after releasing it
        // (a stalled reader must not block the store for everyone else)
        bool found;
        std::string out;
        {
          std::lock_guard<std::mutex> g(store->mu);
          auto it = store->kv.find(key);
          found = it != store->kv.end();
          if (found) out = it->second;
        }
        ok = found ? send_response(fd, 0, out) : send_response(fd, -1, "");
        break;
      }
      case 2: {  // ADD: value holds an i64 delta; missing key starts at 0
        int64_t delta = 0;
        if (value.size() == sizeof(delta))
          std::memcpy(&delta, value.data(), sizeof(delta));
        int64_t result;
        {
          std::lock_guard<std::mutex> g(store->mu);
          int64_t cur = 0;
          auto it = store->kv.find(key);
          if (it != store->kv.end() && it->second.size() == sizeof(cur))
            std::memcpy(&cur, it->second.data(), sizeof(cur));
          result = cur + delta;
          std::string stored(sizeof(result), '\0');
          std::memcpy(stored.data(), &result, sizeof(result));
          store->kv[key] = stored;
        }
        store->cv.notify_all();
        ok = send_response(fd, result, "");
        break;
      }
      case 3: {  // WAIT (value = i64 timeout_ms; -1 blocks forever)
        int64_t timeout_ms = -1;
        if (value.size() == sizeof(timeout_ms))
          std::memcpy(&timeout_ms, value.data(), sizeof(timeout_ms));
        bool found, stopping;
        std::string out;
        {
          std::unique_lock<std::mutex> g(store->mu);
          auto pred = [&] {
            return store->stopping || store->kv.count(key) > 0;
          };
          if (timeout_ms < 0) {
            store->cv.wait(g, pred);
            found = store->kv.count(key) > 0;
          } else {
            found = store->cv.wait_for(
                        g, std::chrono::milliseconds(timeout_ms), pred) &&
                    store->kv.count(key) > 0;
          }
          if (found) out = store->kv[key];
          stopping = store->stopping;
        }
        ok = found ? send_response(fd, 0, out)
                   : send_response(fd, stopping ? -4 : -3, "");
        break;
      }
      case 4: {  // DELETE
        int64_t erased;
        {
          std::lock_guard<std::mutex> g(store->mu);
          erased = static_cast<int64_t>(store->kv.erase(key));
        }
        ok = send_response(fd, erased, "");
        break;
      }
      case 5:  // PING
        ok = send_response(fd, 0, "pong");
        break;
      case 6: {  // DELETE_PREFIX: erase every key starting with `key`
        int64_t erased = 0;
        {
          std::lock_guard<std::mutex> g(store->mu);
          auto it = store->kv.lower_bound(key);
          while (it != store->kv.end() &&
                 it->first.compare(0, key.size(), key) == 0) {
            it = store->kv.erase(it);
            ++erased;
          }
        }
        ok = send_response(fd, erased, "");
        break;
      }
      default:
        ok = send_response(fd, -2, "");
    }
    if (!ok) break;
  }
  slot->done.store(true);
}

struct Server {
  int listen_fd = -1;
  uint16_t port = 0;
  Store store;
  std::thread accept_thread;
  std::mutex clients_mu;
  std::list<ClientSlot> clients;  // list: stable addresses for the threads
};

}  // namespace

extern "C" {

// Start the master store. port 0 picks an ephemeral port; the bound port is
// returned via *out_port. Returns an opaque handle or null on failure.
void* tcp_store_server_start(uint16_t port, uint16_t* out_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);

  auto* srv = new Server();
  srv->listen_fd = fd;
  srv->port = ntohs(addr.sin_port);
  if (out_port) *out_port = srv->port;
  srv->accept_thread = std::thread([srv] {
    for (;;) {
      int cfd = ::accept(srv->listen_fd, nullptr, nullptr);
      if (cfd < 0) break;  // listen socket closed -> shut down
      int one = 1;
      ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(srv->clients_mu);
      // reap finished connections so a long-lived master does not retain
      // one joinable thread (and its stack mapping) per connection ever made
      for (auto it = srv->clients.begin(); it != srv->clients.end();) {
        if (it->done.load()) {
          if (it->th.joinable()) it->th.join();
          ::close(it->fd);
          it = srv->clients.erase(it);
        } else {
          ++it;
        }
      }
      srv->clients.emplace_back();
      ClientSlot& slot = srv->clients.back();
      slot.fd = cfd;
      slot.th = std::thread(serve_client, &srv->store, &slot);
    }
  });
  return srv;
}

void tcp_store_server_stop(void* handle) {
  auto* srv = static_cast<Server*>(handle);
  if (!srv) return;
  // shutdown unblocks accept(); close only AFTER the join so the kernel
  // cannot recycle the descriptor number into an unrelated socket the
  // accept loop would then operate on
  ::shutdown(srv->listen_fd, SHUT_RDWR);
  if (srv->accept_thread.joinable()) srv->accept_thread.join();
  ::close(srv->listen_fd);
  // wake WAITers, unblock reads, and join every client thread before the
  // Store (mutex/condvar) is destroyed — detached threads would race the
  // delete below (use-after-free)
  {
    std::lock_guard<std::mutex> g(srv->store.mu);
    srv->store.stopping = true;
  }
  srv->store.cv.notify_all();
  {
    std::lock_guard<std::mutex> g(srv->clients_mu);
    for (ClientSlot& c : srv->clients)
      if (!c.done.load()) ::shutdown(c.fd, SHUT_RDWR);
  }
  for (ClientSlot& c : srv->clients) {
    if (c.th.joinable()) c.th.join();
    ::close(c.fd);
  }
  delete srv;
}

// ---- client ----
int tcp_store_connect(const char* host, uint16_t port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

void tcp_store_close(int fd) {
  if (fd >= 0) ::close(fd);
}

static int64_t request(int fd, uint8_t cmd, const char* key, uint32_t klen,
                       const char* val, uint32_t vlen, char* out,
                       uint32_t out_cap, uint32_t* out_len) {
  if (!write_exact(fd, &cmd, 1)) return -1000;
  if (!write_exact(fd, &klen, 4)) return -1000;
  if (klen && !write_exact(fd, key, klen)) return -1000;
  if (!write_exact(fd, &vlen, 4)) return -1000;
  if (vlen && !write_exact(fd, val, vlen)) return -1000;
  int64_t status;
  uint32_t rlen;
  if (!read_exact(fd, &status, 8)) return -1000;
  if (!read_exact(fd, &rlen, 4)) return -1000;
  if (rlen > 0) {
    std::vector<char> buf(rlen);
    if (!read_exact(fd, buf.data(), rlen)) return -1000;
    uint32_t n = rlen < out_cap ? rlen : out_cap;
    if (out && n) std::memcpy(out, buf.data(), n);
    if (out_len) *out_len = rlen;
  } else if (out_len) {
    *out_len = 0;
  }
  return status;
}

int64_t tcp_store_set(int fd, const char* key, uint32_t klen,
                      const char* val, uint32_t vlen) {
  return request(fd, 0, key, klen, val, vlen, nullptr, 0, nullptr);
}

int64_t tcp_store_get(int fd, const char* key, uint32_t klen, char* out,
                      uint32_t out_cap, uint32_t* out_len) {
  return request(fd, 1, key, klen, nullptr, 0, out, out_cap, out_len);
}

int64_t tcp_store_add(int fd, const char* key, uint32_t klen,
                      int64_t delta) {
  return request(fd, 2, key, klen, reinterpret_cast<char*>(&delta),
                 sizeof(delta), nullptr, 0, nullptr);
}

int64_t tcp_store_wait(int fd, const char* key, uint32_t klen,
                       int64_t timeout_ms, char* out, uint32_t out_cap,
                       uint32_t* out_len) {
  return request(fd, 3, key, klen, reinterpret_cast<char*>(&timeout_ms),
                 sizeof(timeout_ms), out, out_cap, out_len);
}

int64_t tcp_store_delete(int fd, const char* key, uint32_t klen) {
  return request(fd, 4, key, klen, nullptr, 0, nullptr, 0, nullptr);
}

int64_t tcp_store_delete_prefix(int fd, const char* key, uint32_t klen) {
  return request(fd, 6, key, klen, nullptr, 0, nullptr, 0, nullptr);
}

int64_t tcp_store_ping(int fd) {
  char buf[8];
  uint32_t n = 0;
  return request(fd, 5, nullptr, 0, nullptr, 0, buf, sizeof(buf), &n);
}

}  // extern "C"
