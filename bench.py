"""Benchmark: Llama-3-8B transformer layer, forward+backward, bf16.

Measures tokens/sec and MFU on the available accelerator and prints ONE
JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config mirrors the BASELINE.md north star (Llama-3-8B: d_model=4096,
n_heads=32, ffn=14336 SwiGLU, seq 2048); vs_baseline is measured MFU over
the >=40% target. FLOP accounting: 6*N*tokens-style analytic count per
block (2 MAC flops; backward = 2x forward).
"""
import json
import os
import sys
import time

import numpy as np


def peak_flops(device) -> float:
    """bf16 peak per chip by device kind (public TPU specs)."""
    kind = getattr(device, "device_kind", "").lower()
    table = [
        ("v6e", 918e12), ("trillium", 918e12),
        ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
        ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
    ]
    for key, val in table:
        if key in kind:
            return val
    if "tpu" in kind:
        return 275e12  # conservative default for unknown TPU
    return 0.0  # CPU: MFU not meaningful


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.functional import functional_state, swap_state

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        D, H, DFF, S, B = 4096, 32, 14336, 2048, 8
        steps, warmup = 20, 3
    else:  # smoke config so the bench is runnable anywhere
        D, H, DFF, S, B = 256, 4, 896, 256, 4
        steps, warmup = 5, 2

    pt.seed(0)

    class Block(nn.Layer):
        """One pre-norm Llama block: RMSNorm -> attn -> RMSNorm -> SwiGLU."""

        def __init__(self):
            super().__init__()
            self.norm1 = nn.RMSNorm(D)
            self.attn = nn.MultiHeadAttention(D, H)
            self.norm2 = nn.RMSNorm(D)
            self.gate = nn.Linear(D, DFF, bias_attr=False)
            self.up = nn.Linear(D, DFF, bias_attr=False)
            self.down = nn.Linear(DFF, D, bias_attr=False)

        def forward(self, x, mask):
            h = x + self.attn(self.norm1(x), attn_mask=mask)
            z = self.norm2(h)
            return h + self.down(
                nn.functional.silu(self.gate(z)) * self.up(z))

    model = Block()
    model.eval()
    model.bfloat16()

    train, frozen, buffers = functional_state(model)
    state = {**train, **frozen, **buffers}
    mask = nn.Transformer.generate_square_subsequent_mask(S)
    mask_arr = mask.data.astype(jnp.bfloat16)

    def fwd(params, x):
        with swap_state(model, params, collect_buffers=False):
            out = model(pt.Tensor(x), pt.Tensor(mask_arr))
        return jnp.sum(out.data.astype(jnp.float32))

    grad_fn = jax.jit(jax.value_and_grad(fwd))

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, S, D), dtype=jnp.bfloat16)

    for _ in range(warmup):
        val, grads = grad_fn(state, x)
    jax.block_until_ready((val, grads))

    t0 = time.perf_counter()
    for _ in range(steps):
        val, grads = grad_fn(state, x)
    jax.block_until_ready((val, grads))
    dt = (time.perf_counter() - t0) / steps

    tokens = B * S
    # analytic FLOPs per forward: projections 8*D^2/token (QKVO) +
    # SwiGLU 6*D*DFF/token + attention 4*S*D/token (QK^T + AV)
    fwd_flops = tokens * (8 * D * D + 6 * D * DFF) + 4 * B * S * S * D
    train_flops = 3 * fwd_flops  # backward = 2x forward
    achieved = train_flops / dt
    tok_per_sec = tokens / dt

    dev = jax.devices()[0]
    peak = peak_flops(dev)
    mfu = achieved / peak if peak else 0.0

    if on_tpu and peak:
        result = {"metric": "llama3_8b_layer_mfu_bf16",
                  "value": round(mfu * 100, 2), "unit": "percent_mfu",
                  "vs_baseline": round(mfu / 0.40, 3)}
    else:
        result = {"metric": "llama3_8b_layer_tokens_per_sec_cpu_smoke",
                  "value": round(tok_per_sec, 1), "unit": "tokens/sec",
                  "vs_baseline": 0.0}
    extra = {"tokens_per_sec": round(tok_per_sec, 1),
             "step_ms": round(dt * 1e3, 2),
             "achieved_tflops": round(achieved / 1e12, 2),
             "device": getattr(dev, "device_kind", str(dev)),
             "config": {"d": D, "heads": H, "dff": DFF, "seq": S,
                        "batch": B}}
    print(json.dumps(result))
    print(json.dumps(extra), file=sys.stderr)


if __name__ == "__main__":
    main()
