"""Benchmark: full-model Llama causal-LM pretraining step, bf16, one chip.

Headline metric (the BASELINE.md north star, measured end to end): one
complete compiled ``jit.TrainStep`` — token embedding, L transformer blocks
with Pallas flash attention (causal, GQA, no materialized mask), RMSNorm,
SwiGLU, tied vocab projection (the 128K-vocab matmul), cross-entropy loss,
gradient clip, and AdamW (multi-precision: f32 master weights + moments) —
on a Llama-3-recipe-shaped model sized to a single chip (~0.7B params,
d=2048, 16 heads / 4 KV heads, ffn=7168, vocab=128256, seq 2048).

The bench ASSERTS the Pallas flash kernel is on the hot path by counting
kernel routings during trace (one per layer). A single-block bench (the
round-2 metric) runs alongside as the layer-vs-model breakdown.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}; extra
detail goes to stderr. FLOP accounting is analytic (2 flops/MAC, causal
attention at half, backward = 2x forward, optimizer not counted).
"""
import gc
import json
import os
import sys
import time

if os.environ.get("BENCH_FORCE_CPU"):
    # the sandbox's sitecustomize imports jax at interpreter startup, so
    # env vars are too late — override the platform through the config
    # (same mechanism as tests/conftest.py)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np


def peak_flops(device) -> float:
    """bf16 peak per chip (shared with the telemetry layer's MFU gauge)."""
    from paddle_tpu.observability.step_timer import peak_flops as pf
    return pf(device)


def emit_metrics(payload: dict, path: str):
    """Write ``payload``'s numeric leaves through the observability
    metrics registry as labeled ``bench_result`` gauges and dump the
    registry's JSON exposition to ``path`` — so BENCH_*.json rounds,
    ad-hoc runs, and live training scrapes all share one schema. The
    DEFAULT registry's families ride along too (comm_* incl. the
    exposure counters, serving_*, ckpt_* — whatever the benched code
    recorded), so one file holds both the headline numbers and the
    telemetry behind them."""
    from paddle_tpu.observability.metrics import (MetricsRegistry,
                                                  get_registry)

    reg = MetricsRegistry()
    g = reg.gauge("bench_result", "benchmark scalar results by key path")

    def walk(prefix, obj):
        if isinstance(obj, dict):
            for k, v in obj.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
            g.set(float(obj), key=prefix)

    walk("", payload)
    doc = get_registry().to_json()
    doc.update(reg.to_json())  # bench_result wins on (impossible) clash
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"metrics written to {path}", file=sys.stderr)


def _metrics_out_path():
    """--emit-metrics PATH (or BENCH_EMIT_METRICS env)."""
    if "--emit-metrics" in sys.argv:
        i = sys.argv.index("--emit-metrics")
        if i + 1 >= len(sys.argv):
            raise SystemExit("--emit-metrics requires an output path")
        return sys.argv[i + 1]
    return os.environ.get("BENCH_EMIT_METRICS")


def _time_steps(fn, steps, warmup, ready, reps=3):
    """Per-step seconds by SLOPE: time a short and a long dispatch window
    and divide the difference by the extra steps. A plain total/steps
    folds one constant host<->device round-trip (~tens of ms through the
    sandbox tunnel) into the window, inflating short steps by RTT/steps —
    the MoE suite entry read 8ms/step (~20%) high before this. The slope
    cancels every per-window constant; per-CALL dispatch overhead stays
    in, as it should (a real training loop pays it too). Returns the
    minimum of ``reps`` slopes (least-interference estimate).
    """
    mean, _ = _time_steps_stats(fn, steps, warmup, ready, reps=reps,
                                reduce="min")
    return mean


def _time_steps_stats(fn, steps, warmup, ready, reps=3, reduce="min"):
    """(per_step_seconds, spread_seconds) over ``reps`` slope measurements
    (spread = max-min). ``reduce``: "min" (noise floor) or "mean"."""
    for _ in range(warmup):
        out = fn()
    ready(out)

    def window(n):
        t0 = time.perf_counter()
        o = None
        for _ in range(n):
            o = fn()
        ready(o)
        return time.perf_counter() - t0

    n1, n2 = steps, 3 * steps
    vals = []
    for _ in range(reps):
        t1 = window(n1)
        t2 = window(n2)
        vals.append((t2 - t1) / (n2 - n1))
    agg = min(vals) if reduce == "min" else sum(vals) / len(vals)
    return agg, (max(vals) - min(vals))


def bench_full_model(on_tpu):
    """Complete TrainStep on a Llama-recipe model; returns
    (flops_per_sec, extras)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    import paddle_tpu.ops.pallas.flash_attention as fa_mod

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=7168,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=4096,
            tie_word_embeddings=True)
        # B=4 fits (and beats B=2 by ~6 MFU points) since the fused
        # chunked CE removed the [T, V] logits from HBM; B=8 measured
        # slightly worse (59.8%)
        B, S = 4, 2048
        steps, warmup = 10, 2
    else:  # smoke config so the bench is runnable anywhere
        cfg = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=448,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=512,
            tie_word_embeddings=True)
        B, S = 2, 256
        steps, warmup = 3, 1

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    model.bfloat16()
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True,
                             grad_clip=pt.nn.ClipGradByGlobalNorm(1.0))

    def loss_fn(m, x):
        return m(x, labels=x)[1]

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int64))

    # trace happens on the first call; count flash-kernel routings so the
    # "72% MFU but naive attention" failure mode of round 2 cannot recur
    n_flash = [0]
    real_bshd = fa_mod.flash_attention_bshd

    def counting_bshd(*a, **kw):
        n_flash[0] += 1
        return real_bshd(*a, **kw)
    fa_mod.flash_attention_bshd = counting_bshd
    try:
        first_loss = float(step(x).numpy())
    finally:
        fa_mod.flash_attention_bshd = real_bshd
    if on_tpu and n_flash[0] != cfg.num_hidden_layers:
        raise RuntimeError(
            f"flash kernel routed {n_flash[0]} times during trace, expected "
            f"{cfg.num_hidden_layers} (one per layer) — the bench must "
            "exercise the Pallas hot path")

    # 5 independent slope measurements: mean is the headline, spread is
    # published so driver snapshots and docs stop drifting against each
    # other on tunnel noise (one canonical number +- variance)
    dt, dt_spread = _time_steps_stats(lambda: step(x), steps, warmup,
                                      lambda loss: loss.numpy(), reps=5,
                                      reduce="mean")

    d, ffn, V, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                    cfg.num_hidden_layers)
    d_kv = cfg.num_key_value_heads * (d // cfg.num_attention_heads)
    T = B * S
    per_tok = L * (4 * d * d + 4 * d * d_kv + 6 * d * ffn) + 2 * d * V
    attn = L * 2 * B * S * S * d  # QK^T + AV at causal half
    fwd = T * per_tok + attn
    train_flops = 3 * fwd
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    extras = {
        "loss_first_step": round(first_loss, 3),
        "flash_routings": n_flash[0],
        "params_millions": round(n_params / 1e6, 1),
        "tokens_per_sec": round(T / dt, 1),
        "step_ms": round(dt * 1e3, 2),
        "step_ms_spread": round(dt_spread * 1e3, 2),
        "spread_pct_of_mean": round(dt_spread / dt * 100, 2),
        "achieved_tflops": round(train_flops / dt / 1e12, 2),
        "config": {"d": d, "ffn": ffn, "vocab": V, "layers": L,
                   "heads": cfg.num_attention_heads,
                   "kv_heads": cfg.num_key_value_heads, "batch": B,
                   "seq": S},
    }
    return train_flops / dt, extras


def bench_layer(on_tpu):
    """Single Llama block fwd+bwd (the round-2 metric, kept as the
    layer-vs-model breakdown) — now routed through the flash kernel via the
    tagged causal mask."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.functional import functional_state, swap_state

    if on_tpu:
        D, H, DFF, S, B = 4096, 32, 14336, 2048, 8
        steps, warmup = 20, 3
    else:
        D, H, DFF, S, B = 256, 4, 896, 256, 4
        steps, warmup = 5, 2

    pt.seed(0)

    class Block(nn.Layer):
        """One pre-norm Llama block: RMSNorm -> attn -> RMSNorm -> SwiGLU."""

        def __init__(self):
            super().__init__()
            self.norm1 = nn.RMSNorm(D)
            self.attn = nn.MultiHeadAttention(D, H)
            self.norm2 = nn.RMSNorm(D)
            self.gate = nn.Linear(D, DFF, bias_attr=False)
            self.up = nn.Linear(D, DFF, bias_attr=False)
            self.down = nn.Linear(DFF, D, bias_attr=False)

        def forward(self, x, mask):
            h = x + self.attn(self.norm1(x), attn_mask=mask)
            z = self.norm2(h)
            return h + self.down(
                nn.functional.silu(self.gate(z)) * self.up(z))

    model = Block()
    model.eval()
    model.bfloat16()

    train, frozen, buffers = functional_state(model)
    state = {**train, **frozen, **buffers}
    # the tagged causal mask routes MultiHeadAttention onto the flash
    # kernel's block-skip path (round 2 fed a raw additive mask here and
    # silently benched naive attention)
    mask = nn.Transformer.generate_square_subsequent_mask(S)

    def fwd(params, x):
        with swap_state(model, params, collect_buffers=False):
            out = model(pt.Tensor(x), mask)
        return jnp.sum(out.data.astype(jnp.float32))

    grad_fn = jax.jit(jax.value_and_grad(fwd))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, S, D), dtype=jnp.bfloat16)

    # sync by transferring the scalar loss: through the sandbox's TPU
    # tunnel, block_until_ready does NOT reliably block (measured) — a
    # host transfer of a value that depends on the whole step does
    dt = _time_steps(lambda: grad_fn(state, x), steps, warmup,
                     lambda out: np.asarray(out[0]))

    tokens = B * S
    # projections 8*D^2/token (QKVO) + SwiGLU 6*D*DFF/token + causal
    # attention 2*S*D/token (QK^T + AV at half)
    fwd_flops = tokens * (8 * D * D + 6 * D * DFF) + 2 * B * S * S * D
    train_flops = 3 * fwd_flops
    return train_flops / dt, {"layer_step_ms": round(dt * 1e3, 2),
                              "layer_tokens_per_sec": round(tokens / dt, 1)}


def bench_decode():
    """Serving numbers for the zoo Llama (headline 0.7B config, bf16):
    prefill tokens/sec and decode tokens/sec at B=1 and B=8, via the
    whole-loop compiled generator. Separation by budget slope: one full
    generate call costs prefill + mnt * per_token (+ window RTT, cancelled
    by the call-count slope inside _time_steps); timing two budgets
    isolates the decode slope, and the intercept is the prefill."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=128256, hidden_size=2048, intermediate_size=7168,
        num_hidden_layers=8, num_attention_heads=16,
        num_key_value_heads=4, max_position_embeddings=4096,
        tie_word_embeddings=True)
    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    model.bfloat16()
    S1, S2 = 512, 1024
    m1, m2 = 8, 72
    rng = np.random.RandomState(0)
    out = {}
    for B in (1, 8):
        def t_of(S, mnt):
            ids = pt.to_tensor(rng.randint(0, cfg.vocab_size,
                                           (B, S)).astype(np.int32))
            call = lambda: model.generate_compiled(  # noqa: E731
                ids, max_new_tokens=mnt, temperature=0.0)
            return _time_steps(call, 2, 1, lambda r: r.numpy())

        # decode rate: budget slope at fixed prompt; prefill rate: prompt
        # slope at the MINIMUM budget (mnt=1) so the longer prompt's extra
        # decode-attention cost contaminates the slope by at most one step
        # (an intercept estimate drowns in call noise at B=1 where the
        # whole prefill is a few ms)
        t1, t2 = t_of(S1, m1), t_of(S1, m2)
        per_tok = (t2 - t1) / (m2 - m1)
        prefill_per_tok = max(
            (t_of(S2, 1) - t_of(S1, 1)) / (S2 - S1), 1e-9)
        out[f"B{B}"] = {
            "prefill_tok_per_s": round(B / prefill_per_tok, 1),
            "prefill_ms_at_512": round(prefill_per_tok * S1 * 1e3, 2),
            "decode_ms_per_tok": round(per_tok * 1e3, 3),
            "decode_tok_per_s": round(B / per_tok, 1),
        }
        print(json.dumps({f"B{B}": out[f"B{B}"]}), file=sys.stderr,
              flush=True)
        gc.collect()
    # ragged batch: 8 unequal prompts (256..512) LEFT-padded to 512 —
    # the standard serving shape, one compiled program, mask as input
    B = 8
    lens = np.linspace(256, S1, B).astype(int)
    ids = np.zeros((B, S1), np.int32)
    mask = np.zeros((B, S1), np.int32)
    for b, n in enumerate(lens):
        ids[b, S1 - n:] = rng.randint(0, cfg.vocab_size, n)
        mask[b, S1 - n:] = 1
    ids_t, mask_t = pt.to_tensor(ids), pt.to_tensor(mask)

    def t_ragged(mnt):
        call = lambda: model.generate_compiled(  # noqa: E731
            ids_t, max_new_tokens=mnt, temperature=0.0,
            attention_mask=mask_t)
        return _time_steps(call, 2, 1, lambda r: r.numpy())

    t1, t2 = t_ragged(m1), t_ragged(m2)
    per_tok = (t2 - t1) / (m2 - m1)
    out["B8_ragged"] = {
        "prompt_lens": f"{lens[0]}..{lens[-1]}",
        "decode_ms_per_tok": round(per_tok * 1e3, 3),
        "decode_tok_per_s": round(B / per_tok, 1),
    }
    print(json.dumps({"B8_ragged": out["B8_ragged"]}), file=sys.stderr,
          flush=True)
    out["config"] = {"prompt": S1, "d": cfg.hidden_size,
                     "layers": cfg.num_hidden_layers,
                     "vocab": cfg.vocab_size, "dtype": "bf16"}
    return out


def bench_serve():
    """Continuous-batching serving bench (--serve): drive the
    ``serving.ServingEngine`` with a synthetic Poisson arrival trace and
    report p50/p99 TTFT and aggregate generated tokens/sec. Runs the
    trace under BOTH paged-attention read paths on TPU — ``rpa`` (the
    Ragged-Paged-Attention Pallas kernel, the engine's TPU default) and
    ``gather`` (the XLA fallback it replaced) — so the kernel's win is
    measured in-tree; off-TPU only the gather path runs (interpret-mode
    kernels don't produce meaningful timings). The primary impl's p99
    TTFT and decode tokens/sec are emitted as report-gate headlines
    (``serving_p99_ttft_seconds`` LOWER_BETTER /
    ``serving_decode_tokens_per_sec`` HIGHER_BETTER, ``_cpu_smoke``
    suffix off-TPU), so ``--report`` holds the RPA win against
    regression. A second, shared-prefix Poisson trace (every request =
    one long common prefix + a short unique tail) runs cache-off then
    cache-on and emits the prefix-cache headlines
    (``serving_prefix_cache_hit_rate`` / ``serving_shared_prefix_speedup``
    HIGHER_BETTER, ``serving_cached_p99_ttft_seconds`` /
    ``serving_cold_p99_ttft_seconds`` LOWER_BETTER), gating the 2x
    effective-throughput claim. On TPU the model is the headline 0.7B
    bf16 Llama config;
    elsewhere a smoke config keeps the bench runnable anywhere. Results
    ride the ``--emit-metrics`` JSON schema.
    """
    import time as _time

    import jax
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import ServingEngine

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=7168,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=4096,
            tie_word_embeddings=True)
        n_req, mean_gap = 32, 0.05
        p_lo, p_hi, g_lo, g_hi = 64, 512, 16, 96
        eng_kw = dict(max_batch=8, max_blocks=512, block_size=16,
                      prefill_chunk=128)
        impls = ("rpa", "gather")
    else:
        cfg = LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=256,
            tie_word_embeddings=True)
        n_req, mean_gap = 12, 0.02
        p_lo, p_hi, g_lo, g_hi = 8, 32, 8, 24
        eng_kw = dict(max_batch=4, max_blocks=64, block_size=8,
                      prefill_chunk=16)
        impls = ("gather",)

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    if on_tpu:
        model.bfloat16()

    def run_trace(impl, ledger=True, **extra_kw):
        # ledger=False builds a disarmed engine (the hot path pays only
        # attribute reads on None) — the pair prices the request ledger
        # for the serving_request_ledger_overhead_frac headline;
        # extra_kw rides through to the engine (quantize=, kv_dtype=)
        env_prev = os.environ.get("PADDLE_TPU_REQUEST_LEDGER")
        if not ledger:
            os.environ["PADDLE_TPU_REQUEST_LEDGER"] = "0"
        try:
            engine = ServingEngine(model, attn_impl=impl, **eng_kw,
                                   **extra_kw)
        finally:
            if not ledger:
                if env_prev is None:
                    os.environ.pop("PADDLE_TPU_REQUEST_LEDGER", None)
                else:
                    os.environ["PADDLE_TPU_REQUEST_LEDGER"] = env_prev
        engine.start()
        rng = np.random.RandomState(0)
        # warmup request compiles the unified step outside the timed
        # trace (and proves chunked prefill re-uses it: step_compiles
        # stays 1 through the whole trace)
        engine.submit(rng.randint(1, cfg.vocab_size, 8),
                      max_new_tokens=4).result(timeout=600)

        gaps = rng.exponential(mean_gap, n_req)  # Poisson arrivals
        plens = rng.randint(p_lo, p_hi + 1, n_req)
        gens = rng.randint(g_lo, g_hi + 1, n_req)
        handles = []
        t0 = _time.perf_counter()
        for gap, pl, gn in zip(gaps, plens, gens):
            _time.sleep(gap)
            handles.append(engine.submit(
                rng.randint(1, cfg.vocab_size, pl),
                max_new_tokens=int(gn)))
        engine.drain(timeout=600)
        elapsed = _time.perf_counter() - t0
        engine.shutdown()

        results = [h.result(timeout=1) for h in handles]
        ttfts = np.array([r["ttft_s"] for r in results])
        lats = np.array([r["latency_s"] for r in results])
        gen_tokens = int(sum(r["num_generated"] for r in results))
        stats = engine.stats()
        return {
            "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 2),
            "ttft_p99_ms": round(float(np.percentile(ttfts, 99)) * 1e3, 2),
            "latency_p50_ms": round(
                float(np.percentile(lats, 50)) * 1e3, 2),
            "latency_p99_ms": round(
                float(np.percentile(lats, 99)) * 1e3, 2),
            "generated_tokens": gen_tokens,
            "tokens_per_sec": round(gen_tokens / elapsed, 1),
            "elapsed_s": round(elapsed, 2),
            "preemptions": stats["preemptions"],
            "step_compiles": stats["step_compiles"],
        }

    def run_shared_prefix(prefix_cache):
        """Shared-prefix Poisson trace (ISSUE 15): every request opens
        with the same long system prefix and diverges in a short unique
        tail — the traffic shape the block-granular prefix cache exists
        for. Same workload cache-on vs cache-off, so the effective-
        throughput ratio (generated tokens over wall-clock INCLUDING
        queue/prefill time) is the cache's end-to-end win."""
        if on_tpu:
            pfx_len, tail_lo, tail_hi, gen_n, n, gap = 256, 8, 24, 24, 24, 0.02
        else:
            pfx_len, tail_lo, tail_hi, gen_n, n, gap = 96, 2, 6, 2, 10, 0.002
        engine = ServingEngine(model, attn_impl=impls[0],
                               prefix_cache=prefix_cache, **eng_kw)
        engine.start()
        rng = np.random.RandomState(1)
        prefix = list(rng.randint(1, cfg.vocab_size, pfx_len))
        # warmup: compiles the step and (cache-on) registers the prefix
        engine.submit(prefix, max_new_tokens=2).result(timeout=600)
        gaps = rng.exponential(gap, n)
        tails = [list(rng.randint(1, cfg.vocab_size,
                                  rng.randint(tail_lo, tail_hi + 1)))
                 for _ in range(n)]
        handles = []
        t0 = _time.perf_counter()
        for g, tail in zip(gaps, tails):
            _time.sleep(g)
            handles.append(engine.submit(prefix + tail,
                                         max_new_tokens=gen_n))
        engine.drain(timeout=600)
        elapsed = _time.perf_counter() - t0
        results = [h.result(timeout=1) for h in handles]
        stats = engine.stats()
        engine.shutdown()
        ttfts = np.array([r["ttft_s"] for r in results])
        gen_tokens = int(sum(r["num_generated"] for r in results))
        pc = stats.get("prefix_cache") or {}
        return {
            "prefix_cache": bool(prefix_cache),
            "prefix_len": pfx_len,
            "requests": n,
            "ttft_p50_ms": round(float(np.percentile(ttfts, 50)) * 1e3, 2),
            "ttft_p99_ms": round(float(np.percentile(ttfts, 99)) * 1e3, 2),
            "effective_tokens_per_sec": round(gen_tokens / elapsed, 1),
            "elapsed_s": round(elapsed, 3),
            "hit_rate": pc.get("hit_rate", 0.0),
            "hit_tokens": pc.get("hit_tokens", 0),
            "evictions": pc.get("evictions", 0),
        }

    out = {}
    for impl in impls:
        out[impl] = run_trace(impl)
        print(json.dumps({impl: out[impl]}), file=sys.stderr, flush=True)
        gc.collect()
    # per-request cost summary (ISSUE 16): the exemplar ring after the
    # primary trace — errors/preempted/slow-tail always kept, the rest
    # sampled (PADDLE_TPU_REQUEST_LOG_SAMPLE)
    from paddle_tpu.observability import requests as obs_requests
    led = obs_requests.active()
    if led is not None:
        ex = led.exemplars()
        if ex:
            cols = ("req_id", "kept", "queue_wait_s", "ttft_s",
                    "latency_s", "itl_p99_s", "prefilled_tokens",
                    "cached_tokens", "decode_tokens", "preemptions",
                    "kv_block_seconds")
            print("request cost exemplars (kept=%d of %d completed):"
                  % (len(ex), led.completed_total), file=sys.stderr)
            print(" | ".join(cols), file=sys.stderr)
            for r in ex:
                print(" | ".join(str(r.get(c)) for c in cols),
                      file=sys.stderr)
            sys.stderr.flush()
    # disarmed twin of the primary trace prices the ledger: the headline
    # is the throughput it costs (≤1% gate — LOWER_BETTER in --report)
    ledger_off = run_trace(impls[0], ledger=False)
    out["ledger_off"] = ledger_off
    tps_on = out[impls[0]]["tokens_per_sec"]
    tps_off = ledger_off["tokens_per_sec"]
    ledger_overhead = round(1.0 - tps_on / max(tps_off, 1e-9), 4)
    out["ledger_overhead_frac"] = ledger_overhead
    print(json.dumps({"ledger_off": ledger_off,
                      "ledger_overhead_frac": ledger_overhead}),
          file=sys.stderr, flush=True)
    gc.collect()
    shared = {"cold": run_shared_prefix(False),
              "cached": run_shared_prefix(True)}
    shared["speedup"] = round(
        shared["cached"]["effective_tokens_per_sec"]
        / max(shared["cold"]["effective_tokens_per_sec"], 1e-9), 2)
    out["shared_prefix"] = shared
    print(json.dumps({"shared_prefix": shared}), file=sys.stderr,
          flush=True)
    gc.collect()

    # quantized + multi-tenant serving (ISSUE 20): the int8 weight-only
    # twin of the primary trace prices quantization in tokens/sec, a
    # greedy-parity probe prices it in quality, the doubled-batch int8
    # KV engine must fit the full-precision engine's pool bytes, and an
    # 8-slot LoRA engine serves one request per tenant from ONE
    # compiled step.
    int8_trace = run_trace(impls[0], quantize="int8_wo")
    out["int8_wo"] = int8_trace
    gc.collect()

    def greedy_probe(**kw):
        engine = ServingEngine(model, attn_impl=impls[0], **eng_kw, **kw)
        engine.start()
        prng = np.random.RandomState(3)
        prompts = [list(prng.randint(1, cfg.vocab_size, 12))
                   for _ in range(4)]
        hs = [engine.submit(p, max_new_tokens=8) for p in prompts]
        engine.drain(timeout=600)
        outs = [tuple(h.result(timeout=5)["token_ids"]) for h in hs]
        engine.shutdown()
        return outs

    base_greedy = greedy_probe()
    int8_greedy = greedy_probe(quantize="int8_wo")
    int8_match = float(np.mean([a == b for a, b
                                in zip(base_greedy, int8_greedy)]))
    out["int8_wo"]["greedy_match_frac"] = int8_match
    gc.collect()

    def pool_bytes(engine):
        c = engine.cache
        leaves = (list(c.k_pools) + list(c.v_pools)
                  + list(c.k_scales) + list(c.v_scales))
        return int(sum(x.nbytes for x in leaves))

    ref_engine = ServingEngine(model, attn_impl=impls[0], **eng_kw)
    ref_bytes = pool_bytes(ref_engine)
    del ref_engine
    kv_kw = dict(eng_kw)
    kv_kw["max_batch"] = eng_kw["max_batch"] * 2
    kv_kw["max_blocks"] = eng_kw["max_blocks"] * 2
    kv_engine = ServingEngine(model, attn_impl=impls[0],
                              kv_dtype="int8", **kv_kw)
    kv_bytes = pool_bytes(kv_engine)
    kv_engine.start()
    prng = np.random.RandomState(4)
    hs = [kv_engine.submit(list(prng.randint(1, cfg.vocab_size, 8)),
                           max_new_tokens=4)
          for _ in range(kv_kw["max_batch"])]
    kv_engine.drain(timeout=600)
    kv_served = int(sum(h.result(timeout=5)["num_generated"] > 0
                        for h in hs))
    kv_engine.shutdown()
    kv_quant_max_batch = kv_kw["max_batch"] if kv_bytes <= ref_bytes \
        else eng_kw["max_batch"]
    out["kv_int8"] = {
        "max_batch": kv_quant_max_batch, "served": kv_served,
        "pool_bytes": kv_bytes, "full_precision_pool_bytes": ref_bytes}
    print(json.dumps({"kv_int8": out["kv_int8"]}), file=sys.stderr,
          flush=True)
    gc.collect()

    from paddle_tpu import tuning
    lora_model = LlamaForCausalLM(cfg)
    lora_model.eval()
    if on_tpu:
        lora_model.bfloat16()
    tuning.apply_lora(lora_model, tuning.LoRAConfig(rank=4), n_slots=8)
    lora_engine = ServingEngine(lora_model, attn_impl=impls[0],
                                quantize="int8_wo", **eng_kw)
    prng = np.random.RandomState(5)
    for s in range(1, 9):
        state = {k: (prng.randn(*v.shape[1:]) * 0.01).astype(np.float32)
                 for k, v in lora_engine._st.items()
                 if k.rsplit(".", 1)[-1].startswith("lora_")}
        lora_engine.load_adapter(s, state, name=f"tenant-{s}")
    lora_engine.start()
    hs = [lora_engine.submit(list(prng.randint(1, cfg.vocab_size, 8)),
                             max_new_tokens=4, adapter_id=s)
          for s in range(1, 9)]
    lora_engine.drain(timeout=600)
    adapters_served = int(sum(h.result(timeout=5)["num_generated"] > 0
                              for h in hs))
    lora_stats = lora_engine.stats()
    lora_engine.shutdown()
    out["lora"] = {"slots": lora_stats["adapters"]["slots"],
                   "loaded": lora_stats["adapters"]["loaded"],
                   "served": adapters_served,
                   "step_compiles": lora_stats["step_compiles"]}
    print(json.dumps({"int8_wo": out["int8_wo"], "lora": out["lora"]}),
          file=sys.stderr, flush=True)
    gc.collect()

    primary = out[impls[0]]
    # flatten the primary impl's numbers at the top level (the committed
    # BENCH_r0*.json "parsed" shape earlier rounds gated on)
    out.update(primary)
    out["impl"] = impls[0]
    out["requests"] = n_req
    out["mean_arrival_gap_s"] = mean_gap
    out["config"] = {"d": cfg.hidden_size, "layers": cfg.num_hidden_layers,
                     "vocab": cfg.vocab_size, **eng_kw}
    # report-gate headlines (stdout JSON lines — the round's tail parser
    # picks {"metric", "value"} up; see _report_metrics_of)
    sfx = "" if on_tpu else "_cpu_smoke"
    print(json.dumps({"metric": f"serving_p99_ttft_seconds{sfx}",
                      "value": round(primary["ttft_p99_ms"] / 1e3, 4),
                      "unit": "seconds"}))
    print(json.dumps({"metric": f"serving_decode_tokens_per_sec{sfx}",
                      "value": primary["tokens_per_sec"],
                      "unit": "tokens/sec"}))
    print(json.dumps({"metric": f"serving_prefix_cache_hit_rate{sfx}",
                      "value": shared["cached"]["hit_rate"],
                      "unit": "fraction"}))
    print(json.dumps({"metric": f"serving_cached_p99_ttft_seconds{sfx}",
                      "value": round(shared["cached"]["ttft_p99_ms"] / 1e3,
                                     4),
                      "unit": "seconds"}))
    print(json.dumps({"metric": f"serving_cold_p99_ttft_seconds{sfx}",
                      "value": round(shared["cold"]["ttft_p99_ms"] / 1e3, 4),
                      "unit": "seconds"}))
    print(json.dumps({"metric": f"serving_shared_prefix_speedup{sfx}",
                      "value": shared["speedup"],
                      "unit": "x"}))
    print(json.dumps({"metric":
                      f"serving_request_ledger_overhead_frac{sfx}",
                      "value": out["ledger_overhead_frac"],
                      "unit": "fraction"}))
    print(json.dumps({"metric": f"serving_int8_tokens_per_sec{sfx}",
                      "value": int8_trace["tokens_per_sec"],
                      "unit": "tokens/sec"}))
    print(json.dumps({"metric": f"serving_kv_quant_max_batch{sfx}",
                      "value": kv_quant_max_batch,
                      "unit": "sequences"}))
    print(json.dumps({"metric": f"serving_adapters_served{sfx}",
                      "value": adapters_served,
                      "unit": "adapters"}))
    return out


def bench_fleet(n_replicas=None):
    """Multi-replica fleet bench (--serve --replicas N): drive N engine
    replicas behind the cache-aware :class:`FleetRouter` with an
    open-loop Poisson trace of shared-prefix request groups and compare
    against a single replica under the SAME per-replica offered load —
    the throughput ratio over N single-replica throughputs is the
    fleet's scaling efficiency. A second, mixed long-prompt/chat trace
    runs disaggregation ON (prefill/decode-tagged replicas, long
    prompts prefilled off the decode path) vs OFF (all mixed) and
    reports the chat traffic's p99 inter-token latency both ways — the
    long-prompt-isolation number. Headlines:
    ``serving_fleet_tokens_per_sec`` / ``serving_fleet_scaling_efficiency``
    / ``serving_router_affinity_hit_rate`` (all HIGHER_BETTER,
    ``_cpu_smoke`` suffix off-TPU)."""
    import time as _time

    import jax
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.serving import FleetRouter, Replica, ServingEngine

    if n_replicas is None:
        n_replicas = int(os.environ.get("PADDLE_TPU_FLEET_REPLICAS", "4"))
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=7168,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=4096,
            tie_word_embeddings=True)
        eng_kw = dict(max_batch=8, max_blocks=512, block_size=16,
                      prefill_chunk=128)
        n_base, mean_gap, pfx_len, tail_lo, tail_hi, gen_n = \
            16, 0.05, 64, 8, 24, 32
        long_lo, long_hi, chat_gen, long_gen, disagg_thresh = \
            512, 1024, 32, 8, 256
    else:
        cfg = LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=256,
            tie_word_embeddings=True)
        eng_kw = dict(max_batch=4, max_blocks=64, block_size=8,
                      prefill_chunk=16)
        # per-replica offered load sized well under one replica's
        # capacity: the efficiency headline isolates router/contention
        # overhead, not CPU-smoke GIL saturation
        n_base, mean_gap, pfx_len, tail_lo, tail_hi, gen_n = \
            8, 0.1, 16, 4, 8, 8
        long_lo, long_hi, chat_gen, long_gen, disagg_thresh = \
            64, 96, 12, 4, 48

    def model_fn():
        pt.seed(0)
        m = LlamaForCausalLM(cfg)
        m.eval()
        if on_tpu:
            m.bfloat16()
        return m

    def spin_up(n, roles=None, **router_kw):
        roles = list(roles or [])
        roles += ["mixed"] * (n - len(roles))
        reps = [Replica(ServingEngine(model_fn(), **eng_kw), f"r{i}",
                        role=roles[i]) for i in range(n)]
        router = FleetRouter(reps, **router_kw)
        router.start()
        # warmup: compile each replica's unified step outside the
        # timed window (prefill-role replicas too — the disagg path
        # runs through them)
        rng = np.random.RandomState(99)
        for rep in reps:
            rep.engine.submit(rng.randint(1, cfg.vocab_size, 8),
                              max_new_tokens=2).result(timeout=600)
        return router, reps

    def run_trace(router, reqs, itl_sink=None):
        """Open-loop Poisson drive: (gap, prompt, gen, tag) tuples.
        ``itl_sink[tag]`` collects client-observed inter-token gaps."""
        handles = []
        t0 = _time.perf_counter()
        for gap, prompt, gen, tag in reqs:
            _time.sleep(gap)
            on_token = None
            if itl_sink is not None:
                stamps = itl_sink.setdefault(tag, [])
                marker = []

                def on_token(h, tok, _s=stamps, _m=marker):
                    now = _time.perf_counter()
                    if _m:
                        _s.append(now - _m[0])
                    _m[:] = [now]
            handles.append(router.submit(prompt, max_new_tokens=gen,
                                         on_token=on_token))
        results = [h.result(timeout=600) for h in handles]
        elapsed = _time.perf_counter() - t0
        tokens = sum(r["num_generated"] for r in results)
        return tokens / elapsed, elapsed, results

    def shared_prefix_trace(rng, n_req, gap_mean):
        """Shared-prefix request groups (4 system prompts): the traffic
        shape cache-aware placement exists for — after each group's
        first request registers its blocks somewhere, affinity should
        pin the rest of the group to that replica."""
        prefixes = [list(rng.randint(1, cfg.vocab_size, pfx_len))
                    for _ in range(4)]
        gaps = rng.exponential(gap_mean, n_req)
        out = []
        for i in range(n_req):
            p = prefixes[rng.randint(len(prefixes))]
            tail = list(rng.randint(1, cfg.vocab_size,
                                    rng.randint(tail_lo, tail_hi + 1)))
            out.append((gaps[i], p + tail, gen_n, "chat"))
        return out

    out = {"replicas": n_replicas}

    # -- scaling: same per-replica offered load, 1 vs N replicas -----------
    router1, _ = spin_up(1)
    tps1, el1, _ = run_trace(router1,
                             shared_prefix_trace(np.random.RandomState(2),
                                                 n_base, mean_gap))
    router1.shutdown(drain=True)
    gc.collect()

    routerN, _ = spin_up(n_replicas)
    tpsN, elN, _ = run_trace(
        routerN, shared_prefix_trace(np.random.RandomState(2),
                                     n_base * n_replicas,
                                     mean_gap / n_replicas))
    statsN = routerN.stats()
    routerN.shutdown(drain=True)
    gc.collect()

    efficiency = round(tpsN / max(n_replicas * tps1, 1e-9), 4)
    out["single_replica_tokens_per_sec"] = round(tps1, 1)
    out["fleet_tokens_per_sec"] = round(tpsN, 1)
    out["scaling_efficiency"] = efficiency
    out["affinity_hit_rate"] = statsN.get("affinity_hit_rate") or 0.0
    out["routing"] = statsN.get("routing")
    print(json.dumps({"fleet_scaling": {
        "tps_1": out["single_replica_tokens_per_sec"],
        "tps_n": out["fleet_tokens_per_sec"],
        "efficiency": efficiency, "routing": out["routing"]}}),
        file=sys.stderr, flush=True)

    # -- disaggregation: long-prompt/chat mix, disagg on vs off ------------
    def mixed_trace(rng):
        gaps = rng.exponential(mean_gap, n_base * 2)
        reqs = []
        for i in range(n_base * 2):
            if i % 4 == 0:  # every 4th request drags a long prompt in
                plen = rng.randint(long_lo, long_hi + 1)
                reqs.append((gaps[i],
                             list(rng.randint(1, cfg.vocab_size, plen)),
                             long_gen, "long"))
            else:
                plen = rng.randint(tail_lo + 4, tail_lo + 12)
                reqs.append((gaps[i],
                             list(rng.randint(1, cfg.vocab_size, plen)),
                             chat_gen, "chat"))
        return reqs

    def chat_p99_itl(disagg):
        roles = (["prefill"] + ["decode"] * (n_replicas - 1)) if disagg \
            else None
        router, _ = spin_up(max(n_replicas, 2), roles=roles,
                            disagg=disagg,
                            prefill_threshold=disagg_thresh)
        sink = {}
        _, _, _ = run_trace(router, mixed_trace(np.random.RandomState(5)),
                            itl_sink=sink)
        stats = router.stats()
        router.shutdown(drain=True)
        gc.collect()
        itls = sink.get("chat") or [0.0]
        return (round(float(np.percentile(itls, 99)) * 1e3, 3),
                stats.get("routing"))

    disagg_itl, disagg_routing = chat_p99_itl(True)
    mixed_itl, _ = chat_p99_itl(False)
    out["disagg"] = {
        "chat_p99_itl_ms_disagg_on": disagg_itl,
        "chat_p99_itl_ms_disagg_off": mixed_itl,
        "isolation_ratio": round(mixed_itl / max(disagg_itl, 1e-9), 3),
        "routing": disagg_routing,
    }
    print(json.dumps({"fleet_disagg": out["disagg"]}), file=sys.stderr,
          flush=True)

    # report-gate headlines ({"metric","value"} stdout JSON lines)
    sfx = "" if on_tpu else "_cpu_smoke"
    print(json.dumps({"metric": f"serving_fleet_tokens_per_sec{sfx}",
                      "value": out["fleet_tokens_per_sec"],
                      "unit": "tokens/sec"}))
    print(json.dumps({"metric": f"serving_fleet_scaling_efficiency{sfx}",
                      "value": efficiency, "unit": "fraction"}))
    print(json.dumps({"metric": f"serving_router_affinity_hit_rate{sfx}",
                      "value": out["affinity_hit_rate"],
                      "unit": "fraction"}))
    return out


def bench_ckpt():
    """Checkpoint subsystem bench (--ckpt): save/restore GB/s through the
    ``CheckpointManager`` and the step-loop STALL each save mode injects
    (sync = snapshot + shard write + fsync + commit on the caller;
    async = snapshot only, writing overlaps the next steps) — the number
    the async writer exists to shrink. A fake train loop of fixed-work
    steps measures the stall end to end; ``ckpt_blocking_seconds``
    reports the same quantity from the metrics side. Results ride the
    ``--emit-metrics`` JSON schema."""
    import shutil
    import tempfile
    import time as _time

    import paddle_tpu as pt
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.checkpoint.writer import ckpt_metrics

    mb = float(os.environ.get("BENCH_CKPT_MB", "256"))
    n_tensors = 16
    per = max(int(mb * 1e6 / 4 / n_tensors), 1)
    rng = np.random.RandomState(0)
    state = {f"layers.{i}.weight":
             pt.to_tensor(rng.randn(per // 256 + 1, 256).astype(np.float32))
             for i in range(n_tensors)}
    nbytes = sum(int(np.prod(t.shape)) * 4 for t in state.values())

    root = tempfile.mkdtemp(prefix="pt_ckpt_bench_")
    out = {"state_mb": round(nbytes / 1e6, 1)}
    try:
        mgr = CheckpointManager(root, keep_last_k=2)

        # -- raw save / restore bandwidth (sync, timed to commit) ---------
        t0 = _time.perf_counter()
        mgr.save(0, state, async_=False)
        save_s = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        mgr.restore(0)
        restore_s = _time.perf_counter() - t0
        out["save_gbps"] = round(nbytes / save_s / 1e9, 3)
        out["restore_gbps"] = round(nbytes / restore_s / 1e9, 3)

        # -- step-loop stall: fixed-work steps, one save injected ---------
        step_work_s = 0.01

        def loop(step_offset, async_):
            times = []
            for i in range(8):
                t0 = _time.perf_counter()
                _time.sleep(step_work_s)  # the "train step"
                if i == 2:
                    fut = mgr.save(step_offset, state, async_=async_)
                times.append(_time.perf_counter() - t0)
            fut.wait(600)
            return max(times) - step_work_s

        sync_stall = loop(1, async_=False)
        async_stall = loop(2, async_=True)
        out["sync_stall_ms"] = round(sync_stall * 1e3, 2)
        out["async_stall_ms"] = round(async_stall * 1e3, 2)
        out["stall_ratio"] = round(sync_stall / max(async_stall, 1e-9), 1)
        blocked = ckpt_metrics()["blocking_seconds"]
        out["blocking_ms_sync_mean"] = round(
            blocked.stats(mode="sync")["mean"] * 1e3, 2)
        out["blocking_ms_async_mean"] = round(
            blocked.stats(mode="async")["mean"] * 1e3, 2)
        mgr.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_data():
    """Input-pipeline bench (--data): the two numbers the
    ``paddle_tpu.data`` subsystem exists to move (docs/DATA.md).

    1. **packed vs padded tokens/sec** — same variable-length corpus,
       same model, same compiled TrainStep geometry: the padded loader
       places one document per row (padding the tail, the classic
       fine-tuning shape); the packed pipeline first-fit-packs documents
       into the same [B, seq] with segment-id masking. Throughput is
       counted in REAL (non-pad) tokens — the tokens that actually
       train — so the ratio is the utilization the packer recovers.
       Packing efficiency (real-token fraction per batch) is reported
       from the ``data_packing_efficiency`` histogram.
    2. **prefetch on/off step-time delta** — a deliberately slow
       (IO-bound, GIL-releasing) dataset feeds the same fit-shaped loop
       with and without the async device prefetcher; the delta is the
       per-step data wait the prefetcher hides (the
       ``train_step_data_seconds`` component StepTelemetry reports).

    Results ride the ``--emit-metrics`` JSON schema."""
    import time as _time

    import jax
    import paddle_tpu as pt
    from paddle_tpu.data import DataPipeline
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=7168,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=4096,
            tie_word_embeddings=True)
        B, S, n_docs, steps = 4, 2048, 512, 8
        d_lo, d_hi = 128, 1024
    else:
        cfg = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=448,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=512,
            tie_word_embeddings=True)
        B, S, n_docs, steps = 2, 256, 256, 6
        d_lo, d_hi = 24, 128

    class Corpus:
        """Deterministic variable-length documents."""

        def __getitem__(self, i):
            rng = np.random.RandomState(7000 + i)
            return rng.randint(1, cfg.vocab_size,
                               rng.randint(d_lo, d_hi + 1)).astype(np.int32)

        def __len__(self):
            return n_docs

    def build_step():
        pt.seed(0)
        model = LlamaForCausalLM(cfg)
        if on_tpu:
            model.bfloat16()
        opt = pt.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=True)

        def loss_fn(m, **batch):
            out = m(**batch)
            return out[1] if isinstance(out, tuple) else out
        return TrainStep(model, loss_fn, opt)

    def run(batches, step):
        """(elapsed_s, real_tokens) over pre-built batches (data cost
        excluded — this measures the step-time value of density)."""
        real = 0
        loss = None
        for b in batches:  # warmup/compile on the first call
            loss = step(**{k: pt.to_tensor(v) for k, v in b.items()})
            break
        loss.numpy()
        t0 = _time.perf_counter()
        for b in batches:
            real += int((np.asarray(b["attention_mask"]) > 0).sum())
            loss = step(**{k: pt.to_tensor(v) for k, v in b.items()})
        loss.numpy()
        return _time.perf_counter() - t0, real

    corpus = Corpus()
    out = {"config": {"batch": B, "seq": S, "docs": n_docs,
                      "doc_len": f"{d_lo}..{d_hi}"}}

    # -- packed: first-fit pipeline batches ------------------------------
    pipe = DataPipeline(corpus, batch_size=B, seq_len=S, pack=True,
                        base_seed=3, shuffle=True, drop_last=True)
    packed = []
    for b in pipe:
        packed.append(b)
        if len(packed) >= steps:
            break
    # -- padded: one doc per row, padded to S (same label/mask form) -----
    padded = []
    di = 0
    while len(padded) < len(packed):
        ids = np.zeros((B, S), np.int32)
        seg = np.zeros((B, S), np.int32)
        pos = np.zeros((B, S), np.int32)
        lab = np.full((B, S), -100, np.int32)
        for r in range(B):
            d = corpus[di % n_docs][:S]
            di += 1
            ids[r, :len(d)] = d
            seg[r, :len(d)] = 1
            pos[r, :len(d)] = np.arange(len(d))
            lab[r, 1:len(d)] = d[1:]
        padded.append({"input_ids": ids, "attention_mask": seg,
                       "position_ids": pos, "labels": lab})

    step_fn = build_step()
    t_packed, tok_packed = run(packed, step_fn)
    del step_fn
    gc.collect()
    step_fn = build_step()  # fresh params: identical compile state
    t_padded, tok_padded = run(padded, step_fn)
    del step_fn
    gc.collect()

    eff = pipe.packer.efficiency_stats()
    out["packing_efficiency"] = round(eff["mean"], 4)
    out["packed_tokens_per_sec"] = round(tok_packed / t_packed, 1)
    out["padded_tokens_per_sec"] = round(tok_padded / t_padded, 1)
    out["packed_over_padded"] = round(
        (tok_packed / t_packed) / max(tok_padded / t_padded, 1e-9), 2)
    out["packed_step_ms"] = round(t_packed / len(packed) * 1e3, 2)
    out["padded_step_ms"] = round(t_padded / len(padded) * 1e3, 2)

    # -- prefetch on/off: hide a slow host fetch -------------------------
    fetch_s = 0.015

    class SlowDocs:
        """IO-bound corpus: sleep stands in for object-store reads and
        releases the GIL exactly like real IO would."""

        def __getitem__(self, i):
            _time.sleep(fetch_s)
            return corpus[i]

        def __len__(self):
            return n_docs

    def timed_loop(loader, n):
        """Mean per-step wall time of a fit-shaped loop: fetch (the
        measured wait) + a fixed compute phase."""
        it = iter(loader)
        next(it)  # exclude iterator spin-up
        t0 = _time.perf_counter()
        got = 0
        for b in it:
            _time.sleep(0.01)  # the "train step" the chip would run
            got += 1
            if got >= n:
                break
        return (_time.perf_counter() - t0) / max(got, 1)

    def fresh_pipe(prefetch):
        return DataPipeline(SlowDocs(), batch_size=B, seq_len=S,
                            pack=True, base_seed=3, shuffle=True,
                            drop_last=True, device_prefetch=prefetch)

    n_timed = max(len(packed) - 2, 3)
    sync_step = timed_loop(fresh_pipe(0), n_timed)
    pre_step = timed_loop(fresh_pipe(2), n_timed)
    out["sync_step_ms"] = round(sync_step * 1e3, 2)
    out["prefetch_step_ms"] = round(pre_step * 1e3, 2)
    out["prefetch_data_wait_saved_ms"] = round(
        (sync_step - pre_step) * 1e3, 2)
    return out


def _chaos_worker():
    """Trainer side of ``--chaos`` (launched under the elastic launcher):
    a tiny resilient fit — FitResilience checkpointing every step and
    resuming from ``latest_step`` on relaunch — that appends one JSON
    line per completed step, so the parent can reconstruct the kill /
    recovery timeline from the file alone."""
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.resilience import FitResilience

    run_dir = os.environ["BENCH_CHAOS_DIR"]
    target = int(os.environ.get("BENCH_CHAOS_STEPS", "12"))
    steps_path = os.path.join(run_dir, "steps.jsonl")

    model = pt.hapi.Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                        nn.Linear(16, 1)))
    model.prepare(pt.optimizer.SGD(learning_rate=0.01,
                                   parameters=model.parameters()),
                  nn.MSELoss())
    fr = FitResilience(checkpoint_dir=os.path.join(run_dir, "ckpt"),
                       save_every_steps=1, preemption=True)
    resumed = fr.restore(model)

    class Progress(pt.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            with open(steps_path, "a") as f:
                f.write(json.dumps({"gs": fr.global_step,
                                    "pid": os.getpid(),
                                    "t": time.time()}) + "\n")

    remaining = target - (resumed or 0)
    if remaining > 0:
        rng = np.random.RandomState(0)
        data = [(rng.randn(4, 8).astype(np.float32),
                 rng.randn(4, 1).astype(np.float32)) for _ in range(4)]
        # StepTelemetry drives the StepTimer → the goodput ledger, whose
        # per-step snapshots (PADDLE_TPU_GOODPUT_DIR) the parent folds
        # into the job_goodput_fraction headline
        model.fit(data, epochs=(remaining + len(data) - 1) // len(data),
                  num_iters=remaining, verbose=0,
                  callbacks=[fr, pt.callbacks.StepTelemetry(), Progress()])
    fr.exit_if_preempted()


def bench_chaos():
    """Chaos/MTTR bench (--chaos): run the resilient worker under the
    elastic launcher, SIGKILL it mid-run through the chaos harness
    (``PADDLE_TPU_CHAOS_KILL_AT_STEP``), and measure recovery end to
    end: mean time to recovery (gap between the last step before the
    kill and the first step after the relaunch — dominated by process
    start + jax import + restore), steps lost to the async-save window,
    and whether the run still reached its target step count. Results
    ride the ``--emit-metrics`` JSON schema."""
    import shutil
    import subprocess
    import tempfile

    kill_step = int(os.environ.get("BENCH_CHAOS_KILL_STEP", "5"))
    target = int(os.environ.get("BENCH_CHAOS_STEPS", "12"))
    run_dir = tempfile.mkdtemp(prefix="pt_chaos_bench_")
    env = dict(os.environ)
    env.update({
        "BENCH_CHAOS_DIR": run_dir,
        "BENCH_CHAOS_STEPS": str(target),
        "PADDLE_TPU_CHAOS_KILL_AT_STEP": str(kill_step),
        "PADDLE_TPU_CHAOS_MARK_DIR": run_dir,  # kill fires once per job
        # per-step goodput ledger snapshots (one file per incarnation;
        # the launcher stamps PADDLE_TPU_GOODPUT_DOWN_AT on relaunch, so
        # the second file's ledger carries the kill→resume gap as
        # restart badput)
        "PADDLE_TPU_GOODPUT_DIR": run_dir,
    })
    try:
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--max_restarts", "2", os.path.abspath(__file__),
             "--chaos-worker"],
            env=env, timeout=600)
        elapsed = time.perf_counter() - t0
        steps = []
        with open(os.path.join(run_dir, "steps.jsonl")) as f:
            steps = [json.loads(line) for line in f if line.strip()]
        pids = list(dict.fromkeys(s["pid"] for s in steps))
        out = {"target_steps": target, "kill_step": kill_step,
               "elapsed_s": round(elapsed, 2),
               "launcher_rc": proc.returncode,
               "restarts": len(pids) - 1,
               "completed": bool(steps) and steps[-1]["gs"] >= target}
        if len(pids) >= 2:
            boundary = next(i for i, s in enumerate(steps)
                            if s["pid"] == pids[1])
            last_before, first_after = steps[boundary - 1], steps[boundary]
            out["mttr_s"] = round(first_after["t"] - last_before["t"], 2)
            # steps re-run because the kill outran the async commit
            out["steps_lost"] = last_before["gs"] + 1 - first_after["gs"]
        out.update(_chaos_goodput(run_dir))
        # the elastic counterpart: same class of event (2 of 8 hosts
        # lost), handled as an in-place resize instead of the
        # kill→checkpoint→relaunch above — MTTRs land side by side
        out["resize_drill"] = bench_resize_drill(out.get("mttr_s"))
    finally:
        shutil.rmtree(run_dir, ignore_errors=True)
    if "job_goodput_fraction" in out:
        # report-gate headline (stdout JSON line; see _report_metrics_of)
        import jax
        sfx = "" if jax.default_backend() == "tpu" else "_cpu_smoke"
        print(json.dumps({"metric": f"job_goodput_fraction{sfx}",
                          "value": out["job_goodput_fraction"],
                          "unit": "fraction"}))
    return out


def _chaos_goodput(run_dir: str) -> dict:
    """Fold the chaos run's per-incarnation goodput ledger snapshots
    (``goodput_rank0_<pid>.json``, written per step under
    ``PADDLE_TPU_GOODPUT_DIR``) into the job-level accounting: summed
    bins, the SIGKILL relaunch gap as restart badput, and the headline
    ``job_goodput_fraction``. ``wall_coverage`` is the invariant the
    docs promise — the bins sum to measured wall-clock (first ledger
    birth → last classified step) within a few percent; only the
    last-step→SIGKILL slice and the launcher's reap latency escape."""
    import glob as _glob
    snaps = []
    for p in _glob.glob(os.path.join(run_dir, "goodput_rank*.json")):
        try:
            with open(p) as f:
                snaps.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    if not snaps:
        return {}
    snaps.sort(key=lambda s: s.get("start_unix", 0.0))
    bins = {}
    for s in snaps:
        for b, v in s.get("bins", {}).items():
            bins[b] = bins.get(b, 0.0) + v
    binned = sum(bins.values())
    last = snaps[-1]
    end_unix = last["start_unix"] + last["wall_s"] - \
        last.get("bins", {}).get("restart", 0.0)
    measured = end_unix - snaps[0]["start_unix"]
    out = {"goodput_bins": {b: round(v, 3) for b, v in bins.items()},
           "goodput_restart_s": round(bins.get("restart", 0.0), 3),
           "goodput_incarnations": len(snaps)}
    if binned > 0:
        out["job_goodput_fraction"] = round(
            bins.get("productive", 0.0) / binned, 4)
    if measured > 0:
        out["goodput_wall_coverage"] = round(binned / measured, 4)
    return out


def bench_resize_drill(relaunch_mttr_s=None):
    """Elastic resize drill (rides ``--chaos``): 8 simulated hosts in ONE
    process lose 2 mid-epoch and continue on 6 — the live-resharding
    path (resilience.elastic) end to end, with the acceptance checks
    inline: the consensus boundary lands on the same step for every
    lane, the in-memory shard exchange reassembles model+opt
    bit-identically (same offset math as the checkpoint-file reshard),
    the remapped data order stays exactly-once (token-multiset digest
    over pre+post batches equals one full epoch), zero filesystem writes
    happen on the resize path, and the in-place MTTR comes in far under
    the kill→checkpoint→relaunch MTTR measured by the main chaos run
    (passed in as ``relaunch_mttr_s``). Badput lands in the ``reshard``
    goodput bin — ``restart`` stays at 0."""
    import builtins
    from collections import Counter

    from paddle_tpu.checkpoint.layout import flatten_state
    from paddle_tpu.data.pipeline import DataPipeline
    from paddle_tpu.distributed.tcp_store import TCPStore
    from paddle_tpu.observability.goodput import GoodputLedger
    from paddle_tpu.resilience import elastic
    from paddle_tpu.resilience.elastic import ElasticResizeListener

    OLD, NEW = 8, 6
    rng = np.random.RandomState(7)
    # 240 docs = lcm(8, 6) * 10: both worlds cover every doc exactly once
    docs = [rng.randint(1, 1000, size=rng.randint(5, 48)).astype(np.int32)
            for _ in range(240)]

    class Docs:
        def __len__(self):
            return len(docs)

        def __getitem__(self, i):
            return docs[i]

    def pipes(n):
        return [DataPipeline(Docs(), batch_size=2, seq_len=32, pack=True,
                             base_seed=11, shuffle=True, shard_index=k,
                             num_shards=n, drop_last=False)
                for k in range(n)]

    def toks(batch):
        ids, m = batch["input_ids"], batch["attention_mask"]
        return ids[m > 0].tolist()

    want = Counter()
    for d in docs:
        want.update(d.tolist())

    # the replicated model+opt every host holds after allreduce; the
    # deterministic "train step" makes post-resize state divergence
    # detectable through the weights themselves
    state = {"model": {"w": rng.randn(64, 64).astype(np.float32),
                       "b": rng.randn(64).astype(np.float32)},
             "opt": {"m": np.zeros((64, 64), np.float32),
                     "step": np.int64(0)}}

    def train_step(st, n_tok):
        st["model"]["w"] *= np.float32(1.0 - 1e-4)
        st["opt"]["m"] += np.float32(n_tok)
        st["opt"]["step"] = st["opt"]["step"] + 1

    ledger = GoodputLedger()
    store = TCPStore(is_master=True, world_size=1)
    listeners = [ElasticResizeListener(store=store) for _ in range(OLD)]
    have = Counter()
    old = pipes(OLD)
    iters = [iter(p) for p in old]
    kill_at, gs, boundary, t_kill = 3, 0, None, None
    while boundary is None:
        t0 = time.perf_counter()
        batches = [next(it) for it in iters]
        for b in batches:
            have.update(toks(b))
        train_step(state, sum(int(b["attention_mask"].sum())
                              for b in batches))
        gs += 1
        ledger.record("productive", time.perf_counter() - t0)
        if gs == kill_at:
            # 2 of 8 hosts are going away: the doomed host's preemption
            # notice arrives through the elastic seam on ONE lane; the
            # consensus protocol spreads it to all
            t_kill = time.perf_counter()
            listeners[6].request(NEW, "preempt_2_hosts")
        decided = [ln.should_resize(step=gs) for ln in listeners]
        if all(decided):
            boundary = gs
        else:
            assert not any(decided), "consensus boundary diverged"
    agreed = {ln.target_world for ln in listeners}
    assert agreed == {NEW}, f"target world diverged: {agreed}"

    # --- the resize itself: all 8 publish, 6 assemble — NO filesystem ---
    writes = []
    _open = builtins.open

    def spy(f, mode="r", *a, **k):
        if any(c in str(mode) for c in "wxa+"):
            writes.append(str(f))
        return _open(f, mode, *a, **k)

    import threading
    clients = [TCPStore(host="127.0.0.1", port=store.port,
                        is_master=False, world_size=1)
               for _ in range(OLD)]
    results = [None] * OLD

    def one_rank(r):
        results[r] = elastic.perform_resize(
            clients[r], state=state, data_state=old[r].state_dict(),
            world=OLD, rank=r, new_world=NEW, generation=0,
            boundary_step=boundary, timeout=120)

    t0 = time.perf_counter()
    builtins.open = spy
    try:
        # one thread per simulated host — the same concurrent publish →
        # barrier → assemble dance real ranks run
        ths = [threading.Thread(target=one_rank, args=(r,), daemon=True)
               for r in range(OLD)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=180)
    finally:
        builtins.open = _open
    assert all(s is None and d is None for s, d in results[NEW:]), \
        "departing ranks must not assemble"
    new_states = [s for s, _ in results[:NEW]]
    new_datas = [d for _, d in results[:NEW]]

    _, f0 = flatten_state(state)
    bit_identical = True
    for ns in new_states:
        _, f1 = flatten_state(ns)
        bit_identical &= f0.keys() == f1.keys() and all(
            f0[k][0].tobytes() == f1[k][0].tobytes() for k in f0)

    new = pipes(NEW)
    for j, p in enumerate(new):
        p.load_state_dict(new_datas[j])
    t_ready = time.perf_counter()
    resize_s = t_ready - t0
    # MTTR: preemption notice → consensus boundary → in-place reshard →
    # ready to train on the new world
    mttr_s = t_ready - t_kill
    ledger.record("reshard", resize_s)

    # --- continue on 6: drive the epoch to completion on the survivors
    post_steps = 0
    iters = [iter(p) for p in new]
    live = list(range(NEW))
    while live:
        t0 = time.perf_counter()
        done = []
        for j in live:
            try:
                b = next(iters[j])
            except StopIteration:
                done.append(j)
                continue
            have.update(toks(b))
        if len(done) < len(live):
            train_step(new_states[0], 1)
            post_steps += 1
            ledger.record("productive", time.perf_counter() - t0)
        live = [j for j in live if j not in done]
    snap = ledger.snapshot()
    b = snap["bins"]
    binned = b["productive"] + b["reshard"] + b["restart"]
    out = {"old_world": OLD, "new_world": NEW,
           "boundary_step": boundary, "post_steps": post_steps,
           "resize_s": round(resize_s, 4),
           "resize_mttr_s": round(mttr_s, 4),
           "state_bit_identical": bool(bit_identical),
           "exactly_once": have == want,
           "filesystem_writes_on_resize_path": len(writes),
           "goodput_restart_s": b["restart"],
           "goodput_reshard_s": b["reshard"],
           # productive share of (train + downtime) — the apples-to-
           # apples counterpart of the relaunch run's fraction, where
           # the same membership change bins seconds of restart badput
           "job_goodput_fraction": round(
               b["productive"] / binned, 4) if binned > 0 else None}
    if relaunch_mttr_s:
        out["relaunch_mttr_s"] = relaunch_mttr_s
        if mttr_s > 0:
            out["resize_vs_relaunch_speedup"] = round(
                float(relaunch_mttr_s) / mttr_s, 1)
    return out


def bench_eager():
    """Eager-dispatch overhead — SURVEY §7's #1 risk ('per-op eager
    dispatch is untenable'), finally measured (reference ships the
    equivalent microbench: eager/tests/performance_tests/
    benchmark_eager_cuda.cc). Two numbers: µs per small eager op (tape
    node + XLA dispatch, slope-timed so the sync constant cancels), and
    the eager-vs-TrainStep step-time ratio at the headline config — the
    factor a user pays for skipping compilation on the hot loop."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    # --- 1) µs/op on a chain of small adds (dependent: no fusion escape)
    a = pt.to_tensor(np.ones((8, 8), np.float32))
    b = pt.to_tensor(np.ones((8, 8), np.float32))

    def chain(n):
        c = a
        t0 = time.perf_counter()
        for _ in range(n):
            c = pt.ops.add(c, b)
        float(np.asarray(c.numpy()).sum())
        return time.perf_counter() - t0

    chain(20)  # warm
    n1, n2 = 100, 500
    us_per_op = min((chain(n2) - chain(n1)) / (n2 - n1)
                    for _ in range(3)) * 1e6

    # --- 2) eager vs TrainStep, headline model (scaled to keep the eager
    # run tractable: same recipe, 4 layers, B=2)
    on_tpu = jax.default_backend() == "tpu"
    cfg = LlamaConfig(
        vocab_size=128256 if on_tpu else 512,
        hidden_size=2048 if on_tpu else 128,
        intermediate_size=7168 if on_tpu else 448,
        num_hidden_layers=4 if on_tpu else 2,
        num_attention_heads=16 if on_tpu else 4,
        num_key_value_heads=4 if on_tpu else 2,
        max_position_embeddings=4096 if on_tpu else 512,
        tie_word_embeddings=True)
    B, S = (2, 2048) if on_tpu else (2, 128)
    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    model.bfloat16()
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True)
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randint(0, cfg.vocab_size, (B, S))
                     .astype(np.int64))

    def eager_step():
        _, loss = model(x, labels=x)
        loss.backward()
        opt.step()
        opt.clear_grad(set_to_zero=False)
        return loss

    eager_dt = _time_steps(eager_step, 1, 1, lambda l: l.numpy(), reps=2)

    pt.seed(0)
    model2 = LlamaForCausalLM(cfg)
    model2.bfloat16()
    opt2 = pt.optimizer.AdamW(learning_rate=1e-4,
                              parameters=model2.parameters(),
                              multi_precision=True)
    step = TrainStep(model2, lambda m, t: m(t, labels=t)[1], opt2)
    comp_dt = _time_steps(lambda: step(x), 3, 1, lambda l: l.numpy())

    return {
        "eager_us_per_small_op": round(us_per_op, 1),
        "eager_step_ms": round(eager_dt * 1e3, 1),
        "trainstep_step_ms": round(comp_dt * 1e3, 1),
        "eager_over_trainstep": round(eager_dt / comp_dt, 1),
        "config": {"layers": cfg.num_hidden_layers, "d": cfg.hidden_size,
                   "batch": B, "seq": S},
    }


# ===================== regression gate (--report) ===========================
# The committed BENCH_r0*.json / MULTICHIP_r0*.json files ARE the perf
# trajectory; --report compares a current run against the newest usable
# round and exits nonzero past a configurable tolerance, so CI and future
# PRs can't land a silent perf regression. These helpers import neither
# jax nor paddle_tpu — doctored-trajectory tests run them in-process.

#: per-metric comparison direction; metrics not listed are reported
#: informationally but never gate
REPORT_HIGHER_BETTER = {
    "llama_full_train_step_mfu_bf16", "llama3_8b_layer_mfu_bf16",
    "tokens_per_sec", "layer_tokens_per_sec", "achieved_tflops",
    "layer_mfu_pct",
    # serving throughput under the RPA kernel (ISSUE 8): bench.py
    # --serve Poisson-trace aggregate decode rate
    "serving_decode_tokens_per_sec",
    # productive share of chaos-run wall-clock (ISSUE 13): bench.py
    # --chaos goodput ledger headline — restart/rollback badput must
    # not silently grow
    "job_goodput_fraction",
    # multi-replica fleet serving (ISSUE 17): bench.py --serve
    # --replicas N — aggregate fleet decode rate, its ratio over N
    # single-replica runs at the same per-replica offered load, and
    # the cache-aware router's sketch-match placement rate on
    # shared-prefix traffic
    "serving_fleet_tokens_per_sec",
    "serving_fleet_scaling_efficiency",
    "serving_router_affinity_hit_rate",
    # block-granular prefix cache on shared-prefix traffic (ISSUE 15):
    # fraction of admissions that reused cached KV blocks, and the
    # cache-on/cache-off effective-throughput ratio on the same trace
    "serving_prefix_cache_hit_rate",
    "serving_shared_prefix_speedup",
    # quantized + multi-tenant serving (ISSUE 20): int8 weight-only
    # decode rate on the primary Poisson trace, the batch the int8 KV
    # cache sustains inside the full-precision engine's pool bytes,
    # and the tenants served concurrently from one compiled step
    "serving_int8_tokens_per_sec",
    "serving_kv_quant_max_batch",
    "serving_adapters_served",
}
REPORT_LOWER_BETTER = {"step_ms", "layer_step_ms",
                       # step-glue fusion/overlap trajectory (ISSUE 7):
                       # fused multi-tensor optimizer phase and exposed
                       # (non-overlapped) collective share of the step
                       "optimizer_phase_seconds",
                       "train_step_exposed_collective_seconds",
                       # serving tail latency under the RPA kernel
                       # (ISSUE 8): bench.py --serve p99 TTFT
                       "serving_p99_ttft_seconds",
                       # shared-prefix trace tail latency with the
                       # prefix cache on and off (ISSUE 15) — the
                       # cached path must hold its TTFT win and the
                       # cold oracle must not quietly degrade either
                       "serving_cached_p99_ttft_seconds",
                       "serving_cold_p99_ttft_seconds",
                       # throughput cost of the per-request ledger
                       # (ISSUE 16): armed-vs-disarmed decode rate on
                       # the same Poisson trace — must stay ≤ 1%
                       "serving_request_ledger_overhead_frac",
                       # static program-audit headlines (ISSUE 9,
                       # bench.py --audit / paddle_tpu.analysis): dp
                       # collective census, bytes the step keeps
                       # double-buffered (undonated), and the largest
                       # intermediate (the fused-CE before/after metric)
                       "train_step_allreduce_count",
                       "train_step_undonated_bytes",
                       "train_step_largest_intermediate_bytes",
                       # runtime-truth peak HBM of the compiled train
                       # step (ISSUE 11, observability.memory): XLA
                       # buffer-assignment total for the audited step
                       "train_step_peak_hbm_bytes",
                       # instrumented-vs-plain step cost of the numerics
                       # observatory's sampled twin (ISSUE 14, bench.py
                       # --numerics) — the tap seam must stay cheap
                       "numerics_step_overhead_frac"}
#: open-ended LOWER_BETTER families — the static comm budget is one
#: metric per mesh axis (ISSUE 12, bench.py --audit /
#: paddle_tpu.analysis commplan), so membership is by prefix; the
#: ``_cpu_smoke`` suffix rides after the axis name
REPORT_LOWER_BETTER_PREFIXES = ("train_step_comm_bytes_",)
#: absolute ceilings: current must stay under max(baseline, bound) —
#: step-time spread is a stability gate, not a race
REPORT_BOUNDED = {"spread_pct_of_mean": 1.5}


def _lower_better(name: str) -> bool:
    return name in REPORT_LOWER_BETTER or \
        name.startswith(REPORT_LOWER_BETTER_PREFIXES)


def _report_metrics_of(doc: dict) -> dict:
    """Flat {metric: value} from one round document — either a committed
    BENCH_r0*.json ({"tail", "parsed", ...}) or a bare result dict. The
    headline {"metric": name, "value": v} line (stdout JSON) becomes a
    metric under its own name."""
    out = {}
    parsed = doc.get("parsed") if isinstance(doc.get("parsed"), dict) \
        else None
    flat = parsed if parsed is not None else doc
    for k, v in flat.items():
        # rc/unix_time are round bookkeeping, not perf metrics — counting
        # them would let a metric-less round pass for a usable baseline
        if k in ("rc", "unix_time"):
            continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = float(v)
    tail = doc.get("tail", "")
    for line in tail.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj and "value" in obj:
            try:
                out[str(obj["metric"])] = float(obj["value"])
            except (TypeError, ValueError):
                continue  # null / non-numeric headline: not comparable
    if "metric" in doc and "value" in doc:
        try:
            out[str(doc["metric"])] = float(doc["value"])
        except (TypeError, ValueError):
            pass
    return out


def _round_key(path: str) -> int:
    """Numeric round id from BENCH_r12.json — lexicographic sort would
    pin the gate to r09 forever once r10 lands."""
    import re
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def report_baseline(baseline_dir: str, pattern: str = "BENCH_r*.json"):
    """(round_name, metrics) from the newest trajectory round that has
    comparable numbers (rc==0 and at least one numeric metric)."""
    import glob as _glob
    paths = sorted(_glob.glob(os.path.join(baseline_dir, pattern)),
                   key=_round_key)
    for path in reversed(paths):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if doc.get("rc", 0) != 0:
            continue
        metrics = _report_metrics_of(doc)
        if metrics:
            return os.path.basename(path), metrics
    return None, {}


def report_compare(baseline: dict, current: dict,
                   tolerance_pct: float) -> dict:
    """Row-per-metric comparison. A metric regresses when it moves past
    ``tolerance_pct`` in its bad direction (or past its absolute bound);
    baseline metrics missing from the current run are listed as
    ``skipped`` — visible, but only ``--strict`` turns them into a
    failure."""
    tol = tolerance_pct / 100.0
    rows, failures, skipped = [], [], []
    for name in sorted(baseline):
        base = baseline[name]
        if name not in current:
            if name in REPORT_HIGHER_BETTER or _lower_better(name) \
                    or name in REPORT_BOUNDED:
                skipped.append(name)
            continue
        cur = current[name]
        delta_pct = ((cur - base) / abs(base) * 100) if base else 0.0
        status = "info"
        if name in REPORT_HIGHER_BETTER:
            status = "fail" if cur < base * (1 - tol) else "ok"
        elif _lower_better(name):
            status = "fail" if cur > base * (1 + tol) else "ok"
        elif name in REPORT_BOUNDED:
            limit = max(base, REPORT_BOUNDED[name])
            status = "fail" if cur > limit * (1 + tol) else "ok"
        row = {"metric": name, "baseline": base, "current": cur,
               "delta_pct": round(delta_pct, 2), "status": status}
        rows.append(row)
        if status == "fail":
            failures.append(name)
    return {"rows": rows, "failures": failures, "skipped": skipped,
            "compared": sum(1 for r in rows if r["status"] in
                            ("ok", "fail"))}


def _multichip_segments(doc: dict):
    """Dryrun segment labels out of a MULTICHIP_r0*.json tail — the
    coverage set a current run must not shrink."""
    import re
    tail = doc.get("tail", "")
    segs = set()
    for line in tail.splitlines():
        if "dryrun_multichip" not in line:
            continue
        body = line.split(":", 1)[-1]
        # parity fragments like "|5.55671-5.55671|<tol" also split on
        # "|": only letter-led tokens are segment labels
        for part in body.split("|"):
            m = re.match(r"\s*([A-Za-z][A-Za-z0-9_\[\]x-]*)", part)
            if m:
                segs.add(m.group(1))
    return segs


def report_multichip(baseline_path_dir: str, current_doc: dict) -> dict:
    """Gate the multichip dryrun: the current run must be ok (rc 0) and
    cover every segment the newest committed round covered."""
    import glob as _glob
    paths = sorted(_glob.glob(os.path.join(baseline_path_dir,
                                           "MULTICHIP_r*.json")),
                   key=_round_key)
    base_doc = None
    for path in reversed(paths):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if doc.get("rc", 1) == 0 and doc.get("ok"):
            base_doc = doc
            break
    if base_doc is None:
        return {"status": "no-baseline"}
    missing = sorted(_multichip_segments(base_doc) -
                     _multichip_segments(current_doc))
    ok = bool(current_doc.get("ok")) and current_doc.get("rc", 1) == 0 \
        and not missing
    return {"status": "ok" if ok else "fail",
            "current_ok": bool(current_doc.get("ok")),
            "missing_segments": missing}


def _report_argv_value(argv, flag, default=None):
    if flag in argv:
        i = argv.index(flag)
        if i + 1 >= len(argv):
            raise SystemExit(f"{flag} requires a value")
        return argv[i + 1]
    return default


def bench_report(argv=None) -> int:
    """``bench.py --report`` entry point; returns the exit code.

    Flags: ``--current FILE`` (a prior run's JSON: committed-round shape
    or a flat result dict; default: run the bench now), ``--baseline-dir
    DIR`` (default: this file's directory), ``--tolerance PCT`` (default
    3), ``--multichip FILE`` (also gate dryrun coverage), ``--strict``
    (baseline metrics missing from the current run fail the gate).
    """
    argv = sys.argv if argv is None else argv
    baseline_dir = _report_argv_value(
        argv, "--baseline-dir", os.path.dirname(os.path.abspath(__file__)))
    tolerance = float(_report_argv_value(argv, "--tolerance", "3"))
    strict = "--strict" in argv
    current_path = _report_argv_value(argv, "--current")

    round_name, baseline = report_baseline(baseline_dir)
    if not baseline:
        print(json.dumps({"report": {"status": "no-baseline",
                                     "baseline_dir": baseline_dir}}))
        return 2 if strict else 0

    if current_path:
        with open(current_path) as f:
            cur_doc = json.load(f)
        if cur_doc.get("rc", 0) != 0:
            # a crashed bench's partial numbers must not pass the gate —
            # the same rc discipline report_baseline applies to baselines
            print(json.dumps({"report": {
                "status": "current-run-failed",
                "rc": cur_doc.get("rc")}}))
            return 1
        current = _report_metrics_of(cur_doc)
    else:
        import jax
        on_tpu = jax.default_backend() == "tpu"
        dev = jax.devices()[0]
        peak = peak_flops(dev)
        flops_per_s, extras = bench_full_model(on_tpu)
        gc.collect()
        layer_flops_per_s, layer_extras = bench_layer(on_tpu)
        current = _report_metrics_of({**extras, **layer_extras})
        if on_tpu and peak:
            current["llama_full_train_step_mfu_bf16"] = \
                round(flops_per_s / peak * 100, 2)
            current["layer_mfu_pct"] = \
                round(layer_flops_per_s / peak * 100, 2)
        elif not on_tpu:
            # a CPU smoke run must not race the committed TPU round
            # under identical metric names — suffix everything so the
            # gate lists the baseline's metrics as skipped (soft) rather
            # than failing on hardware, not regression
            current = {f"{k}_cpu_smoke": v for k, v in current.items()}

    cmp = report_compare(baseline, current, tolerance)
    report = {"baseline_round": round_name, "tolerance_pct": tolerance,
              **cmp}

    mc_path = _report_argv_value(argv, "--multichip")
    if mc_path:
        with open(mc_path) as f:
            report["multichip"] = report_multichip(baseline_dir,
                                                   json.load(f))
        if report["multichip"].get("status") == "fail":
            report.setdefault("failures", []).append("multichip")

    failed = bool(report["failures"]) or (strict and report["skipped"])
    report["status"] = "fail" if failed else (
        "ok" if report["compared"] else "no-comparable-metrics")
    for r in report["rows"]:
        print(f"  {r['status']:<5} {r['metric']:<40} "
              f"{r['baseline']:>12.3f} -> {r['current']:>12.3f} "
              f"({r['delta_pct']:+.2f}%)", file=sys.stderr)
    if report["skipped"]:
        print(f"  skipped (absent from current run): "
              f"{', '.join(report['skipped'])}", file=sys.stderr)
    if not report["compared"]:
        print("  no comparable metrics — baseline is a TPU round and the "
              "current run carries none of its gated metrics (CPU smoke?)",
              file=sys.stderr)
    print(json.dumps({"report": report}))
    return 1 if failed else 0


def bench_attribution():
    """Phase-level step attribution (--attribution) on the committed
    bench geometry: where the 287.88ms step goes — embedding+layers vs
    loss-head vs optimizer vs exposed collective — with per-phase MFU
    from XLA cost analysis (docs/OBSERVABILITY.md). The table the
    fusion/overlap work must move."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.observability.attribution import attribute_train_step

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=7168,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=4096,
            tie_word_embeddings=True)
        B, S = 4, 2048
        steps, warmup, reps = 8, 2, 3
    else:
        cfg = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=448,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=512,
            tie_word_embeddings=True)
        B, S = 2, 256
        # the optimizer phase is a ~1ms difference of ~60ms measurements
        # on the 1-CPU smoke box: more reps keep the min-over-windows
        # stable enough for the fused-vs-looped comparison row
        steps, warmup, reps = 4, 1, 4

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    if on_tpu:
        model.bfloat16()
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True,
                             grad_clip=pt.nn.ClipGradByGlobalNorm(1.0))
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randint(0, cfg.vocab_size, (B, S))
                     .astype(np.int64))
    config = {"d": cfg.hidden_size, "layers": cfg.num_hidden_layers,
              "vocab": cfg.vocab_size, "batch": B, "seq": S}
    # fused (the shipped default, whose table/gauges this run reports)
    # measured FIRST on the freshest process state, looped second for the
    # before/after comparison row — the phase is a ~1ms difference of
    # ~60ms programs on CPU smoke and allocator growth between attribute
    # calls would otherwise bias whichever run goes last
    report = attribute_train_step(
        model, opt, x, steps=steps, warmup=warmup, reps=reps,
        config=config, fused=True)
    gc.collect()
    looped = attribute_train_step(
        model, opt, x, steps=steps, warmup=warmup, reps=reps,
        config=config, fused=False)

    def _opt_row(r):
        p = r.phases["optimizer"]
        share = p["seconds"] / r.step_time_s * 100 if r.step_time_s else 0.0
        return p["seconds"], share
    looped_s, looped_share = _opt_row(looped)
    fused_s, fused_share = _opt_row(report)
    print(report.table(), file=sys.stderr)
    print(f"optimizer phase: looped {looped_s * 1e3:.3f}ms "
          f"({looped_share:.2f}%) -> fused {fused_s * 1e3:.3f}ms "
          f"({fused_share:.2f}%)", file=sys.stderr)
    out = report.to_json()
    out["sums_within_5pct"] = report.check(0.05)
    out["optimizer_phase_ms_fused"] = round(fused_s * 1e3, 3)
    out["optimizer_phase_ms_looped"] = round(looped_s * 1e3, 3)
    # regression-gate headlines (BENCHMARKS.md#regression-gate); CPU smoke
    # keeps the suffix so it can't race the committed TPU round
    suffix = "" if on_tpu else "_cpu_smoke"
    print(json.dumps({"metric": f"optimizer_phase_seconds{suffix}",
                      "value": round(fused_s, 6)}))
    print(json.dumps({
        "metric": f"train_step_exposed_collective_seconds{suffix}",
        "value": round(report.phases["exposed_collective"]["seconds"], 6)}))
    return out


def bench_audit():
    """Static program audit (--audit): compiled-HLO invariants on the
    committed geometry, as report-gate headlines (docs/ANALYSIS.md).

    Three LOWER_BETTER numbers: ``train_step_allreduce_count`` (the
    dp collective census — buckets+1 when the bucketed path holds, a
    storm when it regresses), ``train_step_undonated_bytes`` (buffers
    the step keeps two copies of), and
    ``train_step_largest_intermediate_bytes`` (the giant-intermediate
    watermark; the ROADMAP fused-CE item must move it). Off-TPU the
    metrics ride the ``_cpu_smoke`` suffix like every other bench mode.
    Nothing executes — programs are lowered and compiled only, so this
    runs in seconds even on the full chip geometry."""
    # the dp census needs a multi-device mesh: arm the 8-virtual-device
    # CPU platform BEFORE the backend initializes (no-op on TPU)
    from paddle_tpu.analysis.driver import ensure_cpu_mesh, \
        run_default_audit
    ensure_cpu_mesh()
    import jax
    on_tpu = jax.default_backend() == "tpu"

    if on_tpu:
        # the committed bench geometry (bench_full_model's shape), bf16
        # with f32 masters — the donation/upcast/intermediate subject
        from paddle_tpu.models.llama import LlamaConfig
        cfg = LlamaConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=7168,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=4096,
            tie_word_embeddings=True)
        result = run_default_audit(include_serving=False, bf16=True,
                                   batch=(4, 2048), llama_cfg=cfg)
    else:
        result = run_default_audit(include_serving=True)

    findings = result.pop("findings", [])
    result["findings"] = [f.to_json() for f in findings]
    for rep in result["reports"]:
        print(f"  {rep['label']:<14} all_reduce={rep['all_reduce_count']} "
              f"donation_coverage={rep['donation_coverage']} "
              f"undonated={rep['undonated_bytes']}B "
              f"largest={rep['largest_intermediate_bytes']}B "
              f"upcasts={rep['upcast_count']}", file=sys.stderr)
    suffix = "" if on_tpu else "_cpu_smoke"
    for name in ("train_step_allreduce_count",
                 "train_step_undonated_bytes",
                 "train_step_largest_intermediate_bytes",
                 "train_step_peak_hbm_bytes"):
        print(json.dumps({"metric": f"{name}{suffix}",
                          "value": result.get(name)}))

    # per-axis static comm budget (ISSUE 12): the bucketed-dp step's
    # comm-plan ledger as LOWER_BETTER headlines — the before/after
    # instrument the overlap/fusion work pairs with the runtime
    # train_step_exposed_collective_seconds counter
    from paddle_tpu.analysis.driver import run_commplan
    plan = run_commplan(only=("dp8",))
    for axis, slot in sorted(plan["ledgers"].get("dp8", {}).items()):
        result[f"train_step_comm_bytes_{axis}"] = slot["bytes"]
        print(json.dumps({"metric": f"train_step_comm_bytes_{axis}{suffix}",
                          "value": slot["bytes"]}))
    return result


def bench_profile():
    """On-demand device profiler smoke (--profile): compile the tiny
    llama step, open a bounded ``observability.profile`` capture around
    a few steps, and report how many trace files landed under
    ``PADDLE_TPU_TRACE_DIR`` (docs/OBSERVABILITY.md#device-profiler).
    Arming the profiler must not retrace — the step's executable cache
    is asserted unchanged across the captured window."""
    from paddle_tpu.analysis.driver import ensure_cpu_mesh, \
        tiny_llama_step
    ensure_cpu_mesh()
    import jax

    from paddle_tpu.observability import profile
    on_tpu = jax.default_backend() == "tpu"

    step, batch = tiny_llama_step()
    jax.block_until_ready(step(*batch))  # compile outside the window
    traces0 = len(step._cache)
    out_dir = profile.start_capture(label="bench")
    try:
        for _ in range(3):
            jax.block_until_ready(step(*batch))
    finally:
        profile.stop_capture()
    assert len(step._cache) == traces0, \
        "profiler capture must not retrace the train step"
    n_files = sum(len(files) for _, _, files in os.walk(out_dir))
    print(f"  profile capture -> {out_dir} ({n_files} files)",
          file=sys.stderr)
    suffix = "" if on_tpu else "_cpu_smoke"
    print(json.dumps({"metric": f"profile_trace_files{suffix}",
                      "value": n_files}))
    return {"trace_dir": out_dir, "trace_files": n_files}


def bench_numerics():
    """Numerics observatory overhead smoke (--numerics): compile the
    tiny llama step twice — plain and with the instrumented numerics
    twin forced on every step — and report the relative step-time cost
    of the in-graph tap/grad-stat telemetry as the
    ``numerics_step_overhead_frac`` LOWER_BETTER report-gate headline
    (``_cpu_smoke`` suffix off-TPU; docs/OBSERVABILITY.md#numerics).
    The sampled production cost is this number divided by
    ``PADDLE_TPU_NUMERICS_EVERY``."""
    from paddle_tpu.analysis.driver import ensure_cpu_mesh, \
        tiny_llama_step
    ensure_cpu_mesh()
    import jax

    from paddle_tpu.observability import numerics
    on_tpu = jax.default_backend() == "tpu"
    steps, warmup = (20, 3) if on_tpu else (8, 2)

    prev = {k: os.environ.get(k)
            for k in ("PADDLE_TPU_NUMERICS", "PADDLE_TPU_NUMERICS_EVERY")}
    try:
        os.environ["PADDLE_TPU_NUMERICS"] = "0"
        step, batch = tiny_llama_step()

        def time_steps():
            for _ in range(warmup):
                jax.block_until_ready(step(*batch))
            t0 = time.perf_counter()
            for _ in range(steps):
                jax.block_until_ready(step(*batch))
            return (time.perf_counter() - t0) / steps

        t_plain = time_steps()
        compiles0 = len(step._cache)
        os.environ["PADDLE_TPU_NUMERICS"] = "1"
        os.environ["PADDLE_TPU_NUMERICS_EVERY"] = "1"
        t_inst = time_steps()
        assert len(step._cache) == compiles0 + 1, \
            "arming numerics must compile exactly ONE instrumented twin"
        sample = step.last_numerics
        assert sample and sample["taps"], "instrumented steps must sample"
    finally:
        for k, v in prev.items():
            os.environ.pop(k, None) if v is None \
                else os.environ.__setitem__(k, v)

    overhead = (t_inst - t_plain) / t_plain if t_plain > 0 else 0.0
    print(f"  plain={t_plain * 1e3:.2f}ms instrumented={t_inst * 1e3:.2f}ms "
          f"overhead={overhead * 100:.1f}% taps={len(sample['taps'])} "
          f"grad_buckets={len(sample['grads'])}", file=sys.stderr)
    suffix = "" if on_tpu else "_cpu_smoke"
    print(json.dumps({"metric": f"numerics_step_overhead_frac{suffix}",
                      "value": round(overhead, 4)}))
    return {"plain_step_s": t_plain, "instrumented_step_s": t_inst,
            "overhead_frac": overhead, "taps": len(sample["taps"]),
            "grad_buckets": len(sample["grads"])}


def main():
    if "--chaos-worker" in sys.argv:
        _chaos_worker()
        return

    if "--report" in sys.argv:
        raise SystemExit(bench_report())

    import jax

    metrics_out = _metrics_out_path()

    if "--suite" in sys.argv or os.environ.get("BENCH_SUITE"):
        suite = bench_suite()
        print(json.dumps({"suite": suite}))
        if metrics_out:
            emit_metrics({"suite": suite}, metrics_out)
        return

    if "--decode" in sys.argv:
        decode = bench_decode()
        print(json.dumps({"decode": decode}))
        if metrics_out:
            emit_metrics({"decode": decode}, metrics_out)
        return

    if "--eager" in sys.argv:
        eager = bench_eager()
        print(json.dumps({"eager": eager}))
        if metrics_out:
            emit_metrics({"eager": eager}, metrics_out)
        return

    if "--attribution" in sys.argv:
        attribution = bench_attribution()
        print(json.dumps({"attribution": attribution}))
        if metrics_out:
            emit_metrics({"attribution": attribution}, metrics_out)
        return

    if "--audit" in sys.argv:
        audit = bench_audit()
        print(json.dumps({"audit": audit}))
        if metrics_out:
            emit_metrics({"audit": audit}, metrics_out)
        return

    if "--profile" in sys.argv:
        prof = bench_profile()
        print(json.dumps({"profile": prof}))
        if metrics_out:
            emit_metrics({"profile": prof}, metrics_out)
        return

    if "--numerics" in sys.argv:
        nums = bench_numerics()
        print(json.dumps({"numerics": nums}))
        if metrics_out:
            emit_metrics({"numerics": nums}, metrics_out)
        return

    if "--serve" in sys.argv:
        if "--replicas" in sys.argv:
            n = int(sys.argv[sys.argv.index("--replicas") + 1])
            fleet = bench_fleet(n)
            print(json.dumps({"fleet": fleet}))
            if metrics_out:
                emit_metrics({"fleet": fleet}, metrics_out)
        else:
            serve = bench_serve()
            print(json.dumps({"serve": serve}))
            if metrics_out:
                emit_metrics({"serve": serve}, metrics_out)
        return

    if "--ckpt" in sys.argv:
        ckpt = bench_ckpt()
        print(json.dumps({"ckpt": ckpt}))
        if metrics_out:
            emit_metrics({"ckpt": ckpt}, metrics_out)
        return

    if "--data" in sys.argv:
        data = bench_data()
        print(json.dumps({"data": data}))
        if metrics_out:
            emit_metrics({"data": data}, metrics_out)
        return

    if "--chaos" in sys.argv:
        chaos = bench_chaos()
        print(json.dumps({"chaos": chaos}))
        if metrics_out:
            emit_metrics({"chaos": chaos}, metrics_out)
        return

    on_tpu = jax.default_backend() == "tpu"
    dev = jax.devices()[0]
    peak = peak_flops(dev)

    model_flops_per_s, extras = bench_full_model(on_tpu)
    gc.collect()  # free the full model's params/optimizer HBM first
    layer_flops_per_s, layer_extras = bench_layer(on_tpu)
    extras.update(layer_extras)
    extras["device"] = getattr(dev, "device_kind", str(dev))

    if on_tpu and peak:
        model_mfu = model_flops_per_s / peak
        layer_mfu = layer_flops_per_s / peak
        extras["layer_mfu_pct"] = round(layer_mfu * 100, 2)
        result = {"metric": "llama_full_train_step_mfu_bf16",
                  "value": round(model_mfu * 100, 2),
                  "unit": "percent_mfu",
                  "vs_baseline": round(model_mfu / 0.40, 3)}
    else:
        result = {"metric": "llama_full_train_step_tokens_per_sec_cpu_smoke",
                  "value": extras["tokens_per_sec"], "unit": "tokens/sec",
                  "vs_baseline": 0.0}
    print(json.dumps(result))
    print(json.dumps(extras), file=sys.stderr)
    if metrics_out:
        emit_metrics({"headline": result, "detail": extras}, metrics_out)




# ===================== BASELINE config suite (--suite) ======================
# Every BASELINE.json family gets a measured number on the real chip:
# ERNIE pretraining, DeepSeekMoE/Qwen2-MoE-style MoE LM (ragged dispatch),
# DiT (SD-3-family diffusion transformer), PP-OCRv4 conv recognizer, and a
# Llama-3-70B-geometry decoder layer (the full 70B cannot fit one chip —
# BENCHMARKS.md records the reasoning). Shapes are scaled to a single
# v5e's HBM; FLOPs come from XLA's own cost analysis of the compiled
# fwd+bwd program (no hand formulas), so MFU is consistent across
# matmul- and conv-dominated models.

def _measure_pure(build, steps=10, warmup=2):
    import jax
    import jax.numpy as jnp

    fn, state, batch, per_step = build()
    # commit the batch to the device ONCE: numpy args would re-transfer
    # host->device on every timed call (through the sandbox tunnel that
    # costs seconds per call and silently dominated conv benches)
    batch = tuple(jnp.asarray(b) for b in batch)
    # AOT-compile once; the same executable serves cost analysis AND the
    # timing loop (jit would re-trace/re-compile a second copy)
    compiled = jax.jit(jax.value_and_grad(fn)).lower(
        state, *batch).compile()
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost["flops"])
    except Exception:
        pass
    dt = _time_steps(lambda: compiled(state, *batch), steps, warmup,
                     lambda out: np.asarray(out[0]))
    return {"step_ms": round(dt * 1e3, 2),
            "throughput": round(per_step / dt, 1),
            "measured_gflops_per_step": (round(flops / 1e9, 1)
                                         if flops else None),
            "achieved_tflops": (round(flops / dt / 1e12, 2)
                                if flops else None),
            "_flops_per_sec": (flops / dt) if flops else None}


def _functional(model, loss):
    """(pure_fn, state) for a Layer: loss(model_out...) as a jax scalar."""
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit.functional import functional_state, swap_state

    model.bfloat16()
    train, frozen, buffers = functional_state(model)
    state = {**train, **frozen, **buffers}

    def fn(st, *batch):
        wrapped = [pt.Tensor(b.astype(jnp.bfloat16)
                             if jnp.issubdtype(b.dtype, jnp.floating)
                             else b) for b in batch]
        with swap_state(model, st, collect_buffers=False):
            out = loss(*wrapped)
        return out.data.astype(jnp.float32)
    return fn, state


def _suite_ernie():
    import paddle_tpu as pt
    from paddle_tpu.models.ernie import ErnieConfig, ErnieForPretraining

    pt.seed(0)
    cfg = ErnieConfig(hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)
    model = ErnieForPretraining(cfg)
    B, S = 16, 512
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S))
    mlm = rng.randint(0, cfg.vocab_size, (B, S))
    sop = rng.randint(0, 2, (B,))

    def loss(ids_t, mlm_t, sop_t):
        return model(ids_t, masked_lm_labels=mlm_t, sop_labels=sop_t)[-1]

    fn, state = _functional(model, loss)
    return fn, state, (ids, mlm, sop.astype(np.int64)), B * S


def _suite_moe_lm():
    import paddle_tpu as pt
    from paddle_tpu.models.moe import MoeConfig, MoeForCausalLM

    pt.seed(0)
    cfg = MoeConfig(vocab_size=32000, hidden_size=1024,
                    intermediate_size=2816, moe_intermediate_size=704,
                    num_hidden_layers=6, num_attention_heads=8,
                    num_key_value_heads=8, num_experts=16,
                    num_experts_per_tok=4)
    model = MoeForCausalLM(cfg)
    B, S = 4, 1024
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (B, S))

    def loss(ids_t):
        out = model(ids_t, labels=ids_t)
        return out[1] if isinstance(out, tuple) else out

    fn, state = _functional(model, loss)
    return fn, state, (ids,), B * S


def _suite_dit():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.models.dit import DiT, DiTConfig

    pt.seed(0)
    cfg = DiTConfig(depth=8)  # DiT-XL/2 width (1152/16 heads), depth/3.5
    model = DiT(cfg)
    B = 64
    rng = np.random.RandomState(0)
    x = rng.randn(B, cfg.in_channels, cfg.input_size,
                  cfg.input_size).astype(np.float32)
    t = rng.randint(0, 1000, (B,)).astype(np.int64)
    y = rng.randint(0, cfg.num_classes, (B,)).astype(np.int64)
    target = rng.randn(B, cfg.in_channels * 2, cfg.input_size,
                       cfg.input_size).astype(np.float32)
    mse = nn.MSELoss()

    def loss(x_t, t_t, y_t, tgt):
        return mse(model(x_t, t_t, y_t), tgt)

    fn, state = _functional(model, loss)
    return fn, state, (x, t, y, target), B


def _suite_ppocr():
    import paddle_tpu as pt
    from paddle_tpu.models.ppocr import PPOCRRecConfig, PPOCRRecModel

    pt.seed(0)
    cfg = PPOCRRecConfig()
    model = PPOCRRecModel(cfg)
    B, W = 64, 320
    rng = np.random.RandomState(0)
    imgs = rng.randn(B, 3, cfg.img_height, W).astype(np.float32)
    labels = rng.randint(1, cfg.num_classes, (B, 16)).astype(np.int64)
    lens = np.full((B,), 16, np.int64)

    def loss(im, lab, ln):
        return model.loss(model(im), lab, ln)

    fn, state = _functional(model, loss)
    return fn, state, (imgs, labels, lens), B


def _suite_llama70b_layer():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    # one decoder layer at exact 70B geometry (full model: 140GB of bf16
    # weights alone — cannot fit a 16GB chip; see BENCHMARKS.md)
    cfg = LlamaConfig(vocab_size=512, hidden_size=8192,
                      intermediate_size=28672, num_hidden_layers=1,
                      num_attention_heads=64, num_key_value_heads=8,
                      max_position_embeddings=4096,
                      tie_word_embeddings=True)
    model = LlamaForCausalLM(cfg)
    B, S = 1, 2048
    ids = np.random.RandomState(0).randint(0, cfg.vocab_size, (B, S))

    def loss(ids_t):
        out = model(ids_t, labels=ids_t)
        return out[1] if isinstance(out, tuple) else out

    fn, state = _functional(model, loss)
    return fn, state, (ids,), B * S


_SUITE = {
    "ernie_base_pretrain": (_suite_ernie, "tokens/sec"),
    "moe_lm_deepseek_style": (_suite_moe_lm, "tokens/sec"),
    "dit_xl_width_d8": (_suite_dit, "images/sec"),
    "ppocr_v4_rec_conv": (_suite_ppocr, "images/sec"),
    "llama3_70b_geometry_layer": (_suite_llama70b_layer, "tokens/sec"),
}


def bench_suite():
    import jax

    dev = jax.devices()[0]
    peak = peak_flops(dev)
    results = {}
    for name, (builder, unit) in _SUITE.items():
        r = _measure_pure(lambda b=builder: b())
        fps = r.pop("_flops_per_sec")
        r["throughput_unit"] = unit
        r["mfu_pct"] = round(fps / peak * 100, 2) if fps and peak else None
        results[name] = r
        print(json.dumps({name: r}), file=sys.stderr, flush=True)
        gc.collect()
    return results

if __name__ == "__main__":
    main()
