"""Benchmark: full-model Llama causal-LM pretraining step, bf16, one chip.

Headline metric (the BASELINE.md north star, measured end to end): one
complete compiled ``jit.TrainStep`` — token embedding, L transformer blocks
with Pallas flash attention (causal, GQA, no materialized mask), RMSNorm,
SwiGLU, tied vocab projection (the 128K-vocab matmul), cross-entropy loss,
gradient clip, and AdamW (multi-precision: f32 master weights + moments) —
on a Llama-3-recipe-shaped model sized to a single chip (~0.7B params,
d=2048, 16 heads / 4 KV heads, ffn=7168, vocab=128256, seq 2048).

The bench ASSERTS the Pallas flash kernel is on the hot path by counting
kernel routings during trace (one per layer). A single-block bench (the
round-2 metric) runs alongside as the layer-vs-model breakdown.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}; extra
detail goes to stderr. FLOP accounting is analytic (2 flops/MAC, causal
attention at half, backward = 2x forward, optimizer not counted).
"""
import gc
import json
import os
import sys
import time

if os.environ.get("BENCH_FORCE_CPU"):
    # the sandbox's sitecustomize imports jax at interpreter startup, so
    # env vars are too late — override the platform through the config
    # (same mechanism as tests/conftest.py)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np


def peak_flops(device) -> float:
    """bf16 peak per chip by device kind (public TPU specs)."""
    kind = getattr(device, "device_kind", "").lower()
    table = [
        ("v6e", 918e12), ("trillium", 918e12),
        ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
        ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
    ]
    for key, val in table:
        if key in kind:
            return val
    if "tpu" in kind:
        return 275e12  # conservative default for unknown TPU
    return 0.0  # CPU: MFU not meaningful


def _time_steps(fn, steps, warmup, ready):
    for _ in range(warmup):
        out = fn()
    ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn()
    ready(out)
    return (time.perf_counter() - t0) / steps


def bench_full_model(on_tpu):
    """Complete TrainStep on a Llama-recipe model; returns
    (flops_per_sec, extras)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    import paddle_tpu.ops.pallas.flash_attention as fa_mod

    if on_tpu:
        cfg = LlamaConfig(
            vocab_size=128256, hidden_size=2048, intermediate_size=7168,
            num_hidden_layers=8, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=4096,
            tie_word_embeddings=True)
        B, S = 2, 2048
        steps, warmup = 10, 2
    else:  # smoke config so the bench is runnable anywhere
        cfg = LlamaConfig(
            vocab_size=512, hidden_size=128, intermediate_size=448,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=512,
            tie_word_embeddings=True)
        B, S = 2, 256
        steps, warmup = 3, 1

    pt.seed(0)
    model = LlamaForCausalLM(cfg)
    model.bfloat16()
    opt = pt.optimizer.AdamW(learning_rate=1e-4,
                             parameters=model.parameters(),
                             multi_precision=True,
                             grad_clip=pt.nn.ClipGradByGlobalNorm(1.0))

    def loss_fn(m, x):
        return m(x, labels=x)[1]

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int64))

    # trace happens on the first call; count flash-kernel routings so the
    # "72% MFU but naive attention" failure mode of round 2 cannot recur
    n_flash = [0]
    real_bshd = fa_mod.flash_attention_bshd

    def counting_bshd(*a, **kw):
        n_flash[0] += 1
        return real_bshd(*a, **kw)
    fa_mod.flash_attention_bshd = counting_bshd
    try:
        first_loss = float(step(x).numpy())
    finally:
        fa_mod.flash_attention_bshd = real_bshd
    if on_tpu and n_flash[0] != cfg.num_hidden_layers:
        raise RuntimeError(
            f"flash kernel routed {n_flash[0]} times during trace, expected "
            f"{cfg.num_hidden_layers} (one per layer) — the bench must "
            "exercise the Pallas hot path")

    dt = _time_steps(lambda: step(x), steps, warmup,
                     lambda loss: loss.numpy())

    d, ffn, V, L = (cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size,
                    cfg.num_hidden_layers)
    d_kv = cfg.num_key_value_heads * (d // cfg.num_attention_heads)
    T = B * S
    per_tok = L * (4 * d * d + 4 * d * d_kv + 6 * d * ffn) + 2 * d * V
    attn = L * 2 * B * S * S * d  # QK^T + AV at causal half
    fwd = T * per_tok + attn
    train_flops = 3 * fwd
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    extras = {
        "loss_first_step": round(first_loss, 3),
        "flash_routings": n_flash[0],
        "params_millions": round(n_params / 1e6, 1),
        "tokens_per_sec": round(T / dt, 1),
        "step_ms": round(dt * 1e3, 2),
        "achieved_tflops": round(train_flops / dt / 1e12, 2),
        "config": {"d": d, "ffn": ffn, "vocab": V, "layers": L,
                   "heads": cfg.num_attention_heads,
                   "kv_heads": cfg.num_key_value_heads, "batch": B,
                   "seq": S},
    }
    return train_flops / dt, extras


def bench_layer(on_tpu):
    """Single Llama block fwd+bwd (the round-2 metric, kept as the
    layer-vs-model breakdown) — now routed through the flash kernel via the
    tagged causal mask."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.functional import functional_state, swap_state

    if on_tpu:
        D, H, DFF, S, B = 4096, 32, 14336, 2048, 8
        steps, warmup = 20, 3
    else:
        D, H, DFF, S, B = 256, 4, 896, 256, 4
        steps, warmup = 5, 2

    pt.seed(0)

    class Block(nn.Layer):
        """One pre-norm Llama block: RMSNorm -> attn -> RMSNorm -> SwiGLU."""

        def __init__(self):
            super().__init__()
            self.norm1 = nn.RMSNorm(D)
            self.attn = nn.MultiHeadAttention(D, H)
            self.norm2 = nn.RMSNorm(D)
            self.gate = nn.Linear(D, DFF, bias_attr=False)
            self.up = nn.Linear(D, DFF, bias_attr=False)
            self.down = nn.Linear(DFF, D, bias_attr=False)

        def forward(self, x, mask):
            h = x + self.attn(self.norm1(x), attn_mask=mask)
            z = self.norm2(h)
            return h + self.down(
                nn.functional.silu(self.gate(z)) * self.up(z))

    model = Block()
    model.eval()
    model.bfloat16()

    train, frozen, buffers = functional_state(model)
    state = {**train, **frozen, **buffers}
    # the tagged causal mask routes MultiHeadAttention onto the flash
    # kernel's block-skip path (round 2 fed a raw additive mask here and
    # silently benched naive attention)
    mask = nn.Transformer.generate_square_subsequent_mask(S)

    def fwd(params, x):
        with swap_state(model, params, collect_buffers=False):
            out = model(pt.Tensor(x), mask)
        return jnp.sum(out.data.astype(jnp.float32))

    grad_fn = jax.jit(jax.value_and_grad(fwd))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(B, S, D), dtype=jnp.bfloat16)

    # sync by transferring the scalar loss: through the sandbox's TPU
    # tunnel, block_until_ready does NOT reliably block (measured) — a
    # host transfer of a value that depends on the whole step does
    dt = _time_steps(lambda: grad_fn(state, x), steps, warmup,
                     lambda out: np.asarray(out[0]))

    tokens = B * S
    # projections 8*D^2/token (QKVO) + SwiGLU 6*D*DFF/token + causal
    # attention 2*S*D/token (QK^T + AV at half)
    fwd_flops = tokens * (8 * D * D + 6 * D * DFF) + 2 * B * S * S * D
    train_flops = 3 * fwd_flops
    return train_flops / dt, {"layer_step_ms": round(dt * 1e3, 2),
                              "layer_tokens_per_sec": round(tokens / dt, 1)}


def main():
    import jax

    on_tpu = jax.default_backend() == "tpu"
    dev = jax.devices()[0]
    peak = peak_flops(dev)

    model_flops_per_s, extras = bench_full_model(on_tpu)
    gc.collect()  # free the full model's params/optimizer HBM first
    layer_flops_per_s, layer_extras = bench_layer(on_tpu)
    extras.update(layer_extras)
    extras["device"] = getattr(dev, "device_kind", str(dev))

    if on_tpu and peak:
        model_mfu = model_flops_per_s / peak
        layer_mfu = layer_flops_per_s / peak
        extras["layer_mfu_pct"] = round(layer_mfu * 100, 2)
        result = {"metric": "llama_full_train_step_mfu_bf16",
                  "value": round(model_mfu * 100, 2),
                  "unit": "percent_mfu",
                  "vs_baseline": round(model_mfu / 0.40, 3)}
    else:
        result = {"metric": "llama_full_train_step_tokens_per_sec_cpu_smoke",
                  "value": extras["tokens_per_sec"], "unit": "tokens/sec",
                  "vs_baseline": 0.0}
    print(json.dumps(result))
    print(json.dumps(extras), file=sys.stderr)


if __name__ == "__main__":
    main()
