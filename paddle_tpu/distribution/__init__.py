"""paddle.distribution parity (reference: ``python/paddle/distribution/``
— Distribution base, the v2.4 family set, transforms, and the
``register_kl`` multiple-dispatch divergence registry).

TPU-native: every density/entropy is a differentiable tape node (one jnp
body per method), sampling draws keys from the framework RNG
(:mod:`paddle_tpu.core.generator`) so ``paddle.seed`` reproduces draws,
and reparameterized families implement ``rsample`` so pathwise gradients
flow (the reference only exposes rsample on a few; here every location-
scale family has it).
"""
from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import generator as G
from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor

__all__ = [
    "Distribution", "Normal", "Uniform", "Laplace", "Gumbel", "LogNormal",
    "Beta", "Dirichlet", "Categorical", "Multinomial", "Bernoulli",
    "Independent", "TransformedDistribution", "Transform",
    "AffineTransform", "ExpTransform", "SigmoidTransform", "ChainTransform",
    "kl_divergence", "register_kl",
]


def _arr(x):
    if isinstance(x, Tensor):
        return x.data
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jnp.ndarray) \
        else x


def _op(name, fn, *tensors):
    return apply_op(fn, *tensors, op_name=name)


def _shape(sample_shape, base_shape) -> Tuple[int, ...]:
    return tuple(sample_shape) + tuple(base_shape)


class Distribution:
    """Reference: distribution.py:33."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from paddle_tpu import ops
        return ops.exp(self.log_prob(value))

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """Reference: normal.py Normal(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(_arr(loc))
        self.scale = scale if isinstance(scale, Tensor) \
            else Tensor(_arr(scale))
        super().__init__(jnp.broadcast_shapes(self.loc.data.shape,
                                              self.scale.data.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _op("normal_var", lambda s: s * s, self.scale)

    def rsample(self, shape=()):
        eps = jax.random.normal(G.next_key(),
                                _shape(shape, self.batch_shape))
        return _op("normal_rsample",
                   lambda l, s: l + s * eps, self.loc, self.scale)

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        def f(l, s, v):
            var = s * s
            return -((v - l) ** 2) / (2 * var) - jnp.log(s) \
                - 0.5 * math.log(2 * math.pi)
        return _op("normal_log_prob", f, self.loc, self.scale, value)

    def entropy(self):
        return _op("normal_entropy",
                   lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s)
                   + jnp.zeros(self.batch_shape),
                   self.scale)


class LogNormal(Normal):
    """Reference: lognormal.py — exp of a Normal."""

    @property
    def mean(self):
        return _op("lognormal_mean",
                   lambda l, s: jnp.exp(l + s * s / 2), self.loc, self.scale)

    @property
    def variance(self):
        return _op("lognormal_var",
                   lambda l, s: (jnp.exp(s * s) - 1)
                   * jnp.exp(2 * l + s * s), self.loc, self.scale)

    def rsample(self, shape=()):
        base = super().rsample(shape)
        return _op("lognormal_rsample", jnp.exp, base)

    def log_prob(self, value):
        def f(l, s, v):
            logv = jnp.log(v)
            var = s * s
            return -((logv - l) ** 2) / (2 * var) - jnp.log(s) - logv \
                - 0.5 * math.log(2 * math.pi)
        return _op("lognormal_log_prob", f, self.loc, self.scale, value)

    def entropy(self):
        return _op("lognormal_entropy",
                   lambda l, s: 0.5 + 0.5 * math.log(2 * math.pi)
                   + jnp.log(s) + l + jnp.zeros(self.batch_shape),
                   self.loc, self.scale)


class Uniform(Distribution):
    """Reference: uniform.py Uniform(low, high)."""

    def __init__(self, low, high, name=None):
        self.low = low if isinstance(low, Tensor) else Tensor(_arr(low))
        self.high = high if isinstance(high, Tensor) else Tensor(_arr(high))
        super().__init__(jnp.broadcast_shapes(self.low.data.shape,
                                              self.high.data.shape))

    @property
    def mean(self):
        return _op("uniform_mean", lambda l, h: (l + h) / 2,
                   self.low, self.high)

    @property
    def variance(self):
        return _op("uniform_var", lambda l, h: (h - l) ** 2 / 12,
                   self.low, self.high)

    def rsample(self, shape=()):
        u = jax.random.uniform(G.next_key(),
                               _shape(shape, self.batch_shape))
        return _op("uniform_rsample", lambda l, h: l + (h - l) * u,
                   self.low, self.high)

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True  # sample() is detached; rsample is pathwise
        return out

    def log_prob(self, value):
        def f(l, h, v):
            inside = (v >= l) & (v < h)
            return jnp.where(inside, -jnp.log(h - l), -jnp.inf)
        return _op("uniform_log_prob", f, self.low, self.high, value)

    def entropy(self):
        return _op("uniform_entropy", lambda l, h: jnp.log(h - l),
                   self.low, self.high)


class Laplace(Distribution):
    """Reference: laplace.py Laplace(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(_arr(loc))
        self.scale = scale if isinstance(scale, Tensor) \
            else Tensor(_arr(scale))
        super().__init__(jnp.broadcast_shapes(self.loc.data.shape,
                                              self.scale.data.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _op("laplace_var", lambda s: 2 * s * s, self.scale)

    def rsample(self, shape=()):
        u = jax.random.uniform(G.next_key(),
                               _shape(shape, self.batch_shape),
                               minval=-0.5, maxval=0.5)
        return _op("laplace_rsample",
                   lambda l, s: l - s * jnp.sign(u)
                   * jnp.log1p(-2 * jnp.abs(u)), self.loc, self.scale)

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        return _op("laplace_log_prob",
                   lambda l, s, v: -jnp.abs(v - l) / s - jnp.log(2 * s),
                   self.loc, self.scale, value)

    def entropy(self):
        return _op("laplace_entropy",
                   lambda s: 1 + jnp.log(2 * s), self.scale)


class Gumbel(Distribution):
    """Reference: gumbel.py Gumbel(loc, scale)."""

    EULER = 0.57721566490153286060

    def __init__(self, loc, scale, name=None):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(_arr(loc))
        self.scale = scale if isinstance(scale, Tensor) \
            else Tensor(_arr(scale))
        super().__init__(jnp.broadcast_shapes(self.loc.data.shape,
                                              self.scale.data.shape))

    @property
    def mean(self):
        return _op("gumbel_mean", lambda l, s: l + self.EULER * s,
                   self.loc, self.scale)

    @property
    def variance(self):
        return _op("gumbel_var",
                   lambda s: (math.pi ** 2 / 6) * s * s, self.scale)

    def rsample(self, shape=()):
        g = jax.random.gumbel(G.next_key(),
                              _shape(shape, self.batch_shape))
        return _op("gumbel_rsample", lambda l, s: l + s * g,
                   self.loc, self.scale)

    def sample(self, shape=()):
        out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def log_prob(self, value):
        def f(l, s, v):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return _op("gumbel_log_prob", f, self.loc, self.scale, value)

    def entropy(self):
        return _op("gumbel_entropy",
                   lambda s: jnp.log(s) + 1 + self.EULER, self.scale)


class Beta(Distribution):
    """Reference: beta.py Beta(alpha, beta)."""

    def __init__(self, alpha, beta, name=None):
        self.alpha = alpha if isinstance(alpha, Tensor) \
            else Tensor(_arr(alpha))
        self.beta = beta if isinstance(beta, Tensor) else Tensor(_arr(beta))
        super().__init__(jnp.broadcast_shapes(self.alpha.data.shape,
                                              self.beta.data.shape))

    @property
    def mean(self):
        return _op("beta_mean", lambda a, b: a / (a + b),
                   self.alpha, self.beta)

    @property
    def variance(self):
        return _op("beta_var",
                   lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                   self.alpha, self.beta)

    def sample(self, shape=()):
        a = np.broadcast_to(np.asarray(self.alpha.data),
                            _shape(shape, self.batch_shape))
        b = np.broadcast_to(np.asarray(self.beta.data),
                            _shape(shape, self.batch_shape))
        out = jax.random.beta(G.next_key(), a, b)
        return Tensor(out)

    def log_prob(self, value):
        def f(a, b, v):
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) \
                - (jax.scipy.special.gammaln(a)
                   + jax.scipy.special.gammaln(b)
                   - jax.scipy.special.gammaln(a + b))
        return _op("beta_log_prob", f, self.alpha, self.beta, value)

    def entropy(self):
        def f(a, b):
            dg = jax.scipy.special.digamma
            lbeta = (jax.scipy.special.gammaln(a)
                     + jax.scipy.special.gammaln(b)
                     - jax.scipy.special.gammaln(a + b))
            return lbeta - (a - 1) * dg(a) - (b - 1) * dg(b) \
                + (a + b - 2) * dg(a + b)
        return _op("beta_entropy", f, self.alpha, self.beta)


class Dirichlet(Distribution):
    """Reference: dirichlet.py Dirichlet(concentration)."""

    def __init__(self, concentration, name=None):
        self.concentration = concentration \
            if isinstance(concentration, Tensor) \
            else Tensor(_arr(concentration))
        shape = self.concentration.data.shape
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return _op("dirichlet_mean",
                   lambda c: c / jnp.sum(c, -1, keepdims=True),
                   self.concentration)

    @property
    def variance(self):
        def f(c):
            c0 = jnp.sum(c, -1, keepdims=True)
            m = c / c0
            return m * (1 - m) / (c0 + 1)
        return _op("dirichlet_var", f, self.concentration)

    def sample(self, shape=()):
        out = jax.random.dirichlet(
            G.next_key(), np.asarray(self.concentration.data),
            shape=_shape(shape, self.batch_shape) if shape else None)
        return Tensor(out)

    def log_prob(self, value):
        def f(c, v):
            return jnp.sum((c - 1) * jnp.log(v), -1) \
                + jax.scipy.special.gammaln(jnp.sum(c, -1)) \
                - jnp.sum(jax.scipy.special.gammaln(c), -1)
        return _op("dirichlet_log_prob", f, self.concentration, value)

    def entropy(self):
        def f(c):
            dg = jax.scipy.special.digamma
            k = c.shape[-1]
            c0 = jnp.sum(c, -1)
            lB = jnp.sum(jax.scipy.special.gammaln(c), -1) \
                - jax.scipy.special.gammaln(c0)
            return lB + (c0 - k) * dg(c0) - jnp.sum((c - 1) * dg(c), -1)
        return _op("dirichlet_entropy", f, self.concentration)


class Categorical(Distribution):
    """Reference: categorical.py Categorical(logits) — note paddle's
    ``logits`` are unnormalized probabilities (not log-space) when
    positive; we follow the torch/log-space convention of the reference's
    ``probs_to_logits`` path: pass log-probabilities or unnormalized
    logits."""

    def __init__(self, logits, name=None):
        self.logits = logits if isinstance(logits, Tensor) \
            else Tensor(_arr(logits))
        shape = self.logits.data.shape
        super().__init__(shape[:-1])

    @property
    def _log_probs(self):
        return _op("categorical_log_probs",
                   lambda lg: jax.nn.log_softmax(lg, -1), self.logits)

    def sample(self, shape=()):
        out = jax.random.categorical(
            G.next_key(), self.logits.data,
            shape=_shape(shape, self.batch_shape))
        return Tensor(out)

    def log_prob(self, value):
        def f(lg, v):
            lp = jax.nn.log_softmax(lg, -1)
            # value may carry extra sample dims ahead of the batch dims
            lp = jnp.broadcast_to(lp, v.shape + lp.shape[-1:])
            return jnp.take_along_axis(
                lp, v.astype(jnp.int32)[..., None], -1)[..., 0]
        return _op("categorical_log_prob", f, self.logits, value)

    def probs(self, value=None):
        p = _op("categorical_probs",
                lambda lg: jax.nn.softmax(lg, -1), self.logits)
        if value is None:
            return p
        def g(pp, v):
            pp = jnp.broadcast_to(pp, v.shape + pp.shape[-1:])
            return jnp.take_along_axis(
                pp, v.astype(jnp.int32)[..., None], -1)[..., 0]
        return _op("categorical_probs_at", g, p, value)

    def entropy(self):
        def f(lg):
            lp = jax.nn.log_softmax(lg, -1)
            return -jnp.sum(jnp.exp(lp) * lp, -1)
        return _op("categorical_entropy", f, self.logits)


class Bernoulli(Distribution):
    """Reference: the exponential-family Bernoulli (probs parameter)."""

    def __init__(self, probs, name=None):
        self.probs_param = probs if isinstance(probs, Tensor) \
            else Tensor(_arr(probs))
        super().__init__(self.probs_param.data.shape)

    @property
    def mean(self):
        return self.probs_param

    @property
    def variance(self):
        return _op("bernoulli_var", lambda p: p * (1 - p), self.probs_param)

    def sample(self, shape=()):
        u = jax.random.uniform(G.next_key(),
                               _shape(shape, self.batch_shape))
        return Tensor((u < self.probs_param.data).astype(jnp.float32))

    def log_prob(self, value):
        def f(p, v):
            eps = 1e-7
            p_ = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p_) + (1 - v) * jnp.log1p(-p_)
        return _op("bernoulli_log_prob", f, self.probs_param, value)

    def entropy(self):
        def f(p):
            eps = 1e-7
            p_ = jnp.clip(p, eps, 1 - eps)
            return -(p_ * jnp.log(p_) + (1 - p_) * jnp.log1p(-p_))
        return _op("bernoulli_entropy", f, self.probs_param)


class Multinomial(Distribution):
    """Reference: multinomial.py Multinomial(total_count, probs)."""

    def __init__(self, total_count: int, probs, name=None):
        self.total_count = int(total_count)
        self.probs_param = probs if isinstance(probs, Tensor) \
            else Tensor(_arr(probs))
        shape = self.probs_param.data.shape
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return _op("multinomial_mean",
                   lambda p: self.total_count * p, self.probs_param)

    @property
    def variance(self):
        return _op("multinomial_var",
                   lambda p: self.total_count * p * (1 - p),
                   self.probs_param)

    def sample(self, shape=()):
        logits = jnp.log(jnp.maximum(self.probs_param.data, 1e-30))
        draws = jax.random.categorical(
            G.next_key(), logits,
            shape=(self.total_count,) + _shape(shape, self.batch_shape))
        k = self.probs_param.data.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(0)
        return Tensor(counts)

    def log_prob(self, value):
        def f(p, v):
            return (jax.scipy.special.gammaln(jnp.sum(v, -1) + 1)
                    - jnp.sum(jax.scipy.special.gammaln(v + 1), -1)
                    + jnp.sum(v * jnp.log(jnp.maximum(p, 1e-30)), -1))
        return _op("multinomial_log_prob", f, self.probs_param, value)


class Independent(Distribution):
    """Reference: independent.py — reinterprets batch dims as event
    dims (log_prob sums over them)."""

    def __init__(self, base: Distribution,
                 reinterpreted_batch_rank: int = 1):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bs = base.batch_shape
        super().__init__(bs[:len(bs) - self.rank],
                         bs[len(bs) - self.rank:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        from paddle_tpu import ops
        return ops.sum(lp, axis=list(range(lp.ndim - self.rank, lp.ndim)))

    def entropy(self):
        e = self.base.entropy()
        from paddle_tpu import ops
        return ops.sum(e, axis=list(range(e.ndim - self.rank, e.ndim)))


# --------------------------------------------------------------- transforms
class Transform:
    """Reference: transform.py Transform base."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = loc if isinstance(loc, Tensor) else Tensor(_arr(loc))
        self.scale = scale if isinstance(scale, Tensor) \
            else Tensor(_arr(scale))

    def forward(self, x):
        return _op("affine_fwd", lambda l, s, v: l + s * v,
                   self.loc, self.scale, x)

    def inverse(self, y):
        return _op("affine_inv", lambda l, s, v: (v - l) / s,
                   self.loc, self.scale, y)

    def forward_log_det_jacobian(self, x):
        return _op("affine_ldj",
                   lambda s, v: jnp.broadcast_to(jnp.log(jnp.abs(s)),
                                                 v.shape),
                   self.scale, x)


class ExpTransform(Transform):
    def forward(self, x):
        return _op("exp_fwd", jnp.exp, x)

    def inverse(self, y):
        return _op("exp_inv", jnp.log, y)

    def forward_log_det_jacobian(self, x):
        return _op("exp_ldj", lambda v: v, x)


class SigmoidTransform(Transform):
    def forward(self, x):
        return _op("sigmoid_fwd", jax.nn.sigmoid, x)

    def inverse(self, y):
        return _op("sigmoid_inv", lambda v: jnp.log(v) - jnp.log1p(-v), y)

    def forward_log_det_jacobian(self, x):
        return _op("sigmoid_ldj",
                   lambda v: -jax.nn.softplus(-v) - jax.nn.softplus(v), x)


class ChainTransform(Transform):
    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        from paddle_tpu import ops
        total = None
        for t in self.transforms:
            ldj = t.forward_log_det_jacobian(x)
            total = ldj if total is None else ops.add(total, ldj)
            x = t.forward(x)
        return total


class TransformedDistribution(Distribution):
    """Reference: transformed_distribution.py — pushforward of ``base``
    through ``transforms`` (change of variables)."""

    def __init__(self, base: Distribution, transforms):
        self.base = base
        self.transform = transforms if isinstance(transforms, Transform) \
            else ChainTransform(list(transforms))
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        return self.transform.forward(self.base.sample(shape))

    def rsample(self, shape=()):
        return self.transform.forward(self.base.rsample(shape))

    def log_prob(self, value):
        from paddle_tpu import ops
        x = self.transform.inverse(value)
        ldj = self.transform.forward_log_det_jacobian(x)
        return ops.subtract(self.base.log_prob(x), ldj)


# ---------------------------------------------------------------- kl registry
_KL_REGISTRY: Dict[tuple, callable] = {}


def register_kl(cls_p, cls_q):
    """Reference: kl.py:66 — decorator registering a pairwise KL rule."""
    def decorator(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return decorator


def kl_divergence(p: Distribution, q: Distribution):
    """Reference: kl.py:34 — most-derived-match dispatch."""
    best, best_score = None, None
    for (cp, cq), fn in _KL_REGISTRY.items():
        if isinstance(p, cp) and isinstance(q, cq):
            score = (len(type(p).__mro__) - len(cp.__mro__),
                     len(type(q).__mro__) - len(cq.__mro__))
            if best_score is None or score < best_score:
                best, best_score = fn, score
    if best is None:
        raise NotImplementedError(
            f"no KL rule registered for ({type(p).__name__}, "
            f"{type(q).__name__}); use register_kl")
    return best(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def f(lp, sp, lq, sq):
        var_ratio = (sp / sq) ** 2
        t1 = ((lp - lq) / sq) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return _op("kl_normal", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def f(pl, ph, ql, qh):
        kl = jnp.log((qh - ql) / (ph - pl))
        return jnp.where((ql <= pl) & (ph <= qh), kl, jnp.inf)
    return _op("kl_uniform", f, p.low, p.high, q.low, q.high)


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    def f(lp, lq):
        a = jax.nn.log_softmax(lp, -1)
        b = jax.nn.log_softmax(lq, -1)
        return jnp.sum(jnp.exp(a) * (a - b), -1)
    return _op("kl_categorical", f, p.logits, q.logits)


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def f(pa, pb, qa, qb):
        dg = jax.scipy.special.digamma
        lbeta = lambda a, b: (jax.scipy.special.gammaln(a)
                              + jax.scipy.special.gammaln(b)
                              - jax.scipy.special.gammaln(a + b))
        return (lbeta(qa, qb) - lbeta(pa, pb)
                + (pa - qa) * dg(pa) + (pb - qb) * dg(pb)
                + (qa - pa + qb - pb) * dg(pa + pb))
    return _op("kl_beta", f, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def f(pc, qc):
        dg = jax.scipy.special.digamma
        gl = jax.scipy.special.gammaln
        p0 = jnp.sum(pc, -1)
        return (gl(p0) - jnp.sum(gl(pc), -1)
                - gl(jnp.sum(qc, -1)) + jnp.sum(gl(qc), -1)
                + jnp.sum((pc - qc) * (dg(pc) - dg(p0)[..., None]), -1))
    return _op("kl_dirichlet", f, p.concentration, q.concentration)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    def f(lp, sp, lq, sq):
        d = jnp.abs(lp - lq)
        return (jnp.log(sq / sp) + sp / sq * jnp.exp(-d / sp)
                + d / sq - 1)
    return _op("kl_laplace", f, p.loc, p.scale, q.loc, q.scale)
