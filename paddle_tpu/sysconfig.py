"""paddle.sysconfig parity (reference: ``python/paddle/sysconfig.py``)."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]


def get_include() -> str:
    """Directory with the C extension headers (the custom-op seam,
    reference sysconfig.get_include)."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "include")


def get_lib() -> str:
    """Directory with the framework's native libraries (the compiled
    runtime pieces under native/build)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "native", "build")
