"""Error-enforcement machinery (reference: ``paddle/phi/core/enforce.h`` /
``paddle/fluid/platform/enforce.h`` — the PADDLE_ENFORCE_* macro family
raising EnforceNotMet with a formatted error summary + call-stack).

Python-native rebuild: ``enforce*`` helpers raise :class:`EnforceNotMet`
carrying the failed condition, a user message, and the captured Python
stack (the C++ version captures the C++ stack; here the Python frames ARE
the useful context). Ops and user code use these for precondition checks
with reference-style error text.
"""
from __future__ import annotations

import traceback
from typing import Any, Optional

__all__ = ["EnforceNotMet", "enforce", "enforce_eq", "enforce_ne",
           "enforce_gt", "enforce_ge", "enforce_lt", "enforce_le",
           "enforce_not_none", "enforce_shape_match"]


class EnforceNotMet(RuntimeError):
    """Reference: ``platform::EnforceNotMet`` — carries the error summary
    and the captured stack."""

    def __init__(self, message: str, stack: Optional[str] = None):
        self.error_str = message
        self.stack = stack or "".join(traceback.format_stack()[:-2])
        super().__init__(
            f"\n\n--------------------------------------\n"
            f"C++ Traceback (most recent call last):\n"
            f"--------------------------------------\n"
            f"(python-native build: python stack below)\n\n"
            f"----------------------\nError Message Summary:\n"
            f"----------------------\n{message}\n\n{self.stack}")


def _fail(cond_str: str, message: str):
    raise EnforceNotMet(
        f"InvalidArgumentError: Expected {cond_str}, but received the "
        f"opposite. {message}")


def enforce(condition: Any, message: str = ""):
    """PADDLE_ENFORCE: the condition must be truthy."""
    if not condition:
        _fail("condition to be true", message)


def enforce_eq(a, b, message: str = ""):
    if not (a == b):
        _fail(f"{a!r} == {b!r}", message)


def enforce_ne(a, b, message: str = ""):
    if not (a != b):
        _fail(f"{a!r} != {b!r}", message)


def enforce_gt(a, b, message: str = ""):
    if not (a > b):
        _fail(f"{a!r} > {b!r}", message)


def enforce_ge(a, b, message: str = ""):
    if not (a >= b):
        _fail(f"{a!r} >= {b!r}", message)


def enforce_lt(a, b, message: str = ""):
    if not (a < b):
        _fail(f"{a!r} < {b!r}", message)


def enforce_le(a, b, message: str = ""):
    if not (a <= b):
        _fail(f"{a!r} <= {b!r}", message)


def enforce_not_none(value, message: str = ""):
    if value is None:
        _fail("value to be not None", message)
    return value


def enforce_shape_match(shape_a, shape_b, message: str = ""):
    """Shape compatibility with -1/None wildcards (the InferMeta-style
    check ops use at the Python boundary)."""
    a, b = list(shape_a), list(shape_b)
    if len(a) != len(b):
        _fail(f"rank {len(a)} == rank {len(b)}",
              f"shapes {a} vs {b}. {message}")
    for i, (x, y) in enumerate(zip(a, b)):
        wild = (x in (-1, None)) or (y in (-1, None))
        if not wild and x != y:
            _fail(f"shape[{i}] {x} == {y}", f"shapes {a} vs {b}. {message}")
