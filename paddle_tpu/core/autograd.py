"""Eager (dygraph) autograd tape.

TPU-native redesign of the reference's eager autograd engine
(``paddle/fluid/eager/``: ``GradNodeBase``/``Edge`` in ``grad_node_info.h:168``,
``RunBackward`` BFS with in-degree counting in ``backward.cc:104``,
``GradTensorHolder`` accumulation; SURVEY.md §2.3, §3.2).

Where the reference generates one C++ GradNode class per op from YAML
(eager_gen.py), we need no codegen at all: every op is a pure JAX function, so its
GradNode is simply the ``jax.vjp`` closure captured at forward time. The backward
walk is identical in shape to the reference's: seed the output node, BFS with
in-degree bookkeeping, accumulate cotangents per node-slot, and write leaf grads
into ``Tensor.grad`` (the analog of GradNodeAccumulation).

The hot training path does not use this tape — it uses the functional/jit path
(paddle_tpu/jit) where the whole step is one compiled XLA program. The tape is the
debugging/eager UX layer, matching Paddle's dygraph ergonomics.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from . import flags

__all__ = [
    "GradNode", "no_grad", "enable_grad", "is_grad_enabled", "set_grad_enabled",
    "backward", "grad", "apply_op",
]

_tls = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_tls, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _tls.grad_enabled = bool(mode)


class _GradGuard:
    """Context manager *and* decorator, like paddle.no_grad."""

    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(self._mode)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        def wrapper(*a, **k):
            with self.__class__(self._mode):
                return fn(*a, **k)
        wrapper.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapper


def no_grad(fn=None):
    g = _GradGuard(False)
    return g(fn) if callable(fn) else g


def enable_grad(fn=None):
    g = _GradGuard(True)
    return g(fn) if callable(fn) else g


class GradNode:
    """One recorded op on the tape.

    ``vjp_fn`` maps output cotangents -> input cotangents (the jax.vjp closure).
    ``edges`` has one entry per differentiable tensor input:
      ('node', parent_node, slot)  — input produced by another recorded op
      ('leaf', tensor)            — input is a trainable leaf (param)
      None                        — cotangent discarded (stop_gradient input)
    """

    __slots__ = ("name", "vjp_fn", "edges", "n_outputs", "out_avals", "multi",
                 "hooks", "fwd", "input_tensors", "input_vals", "__weakref__")

    def __init__(self, name, vjp_fn, edges, n_outputs, out_avals, multi=False,
                 fwd=None, input_tensors=None, input_vals=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.edges = edges
        self.n_outputs = n_outputs
        self.out_avals = out_avals  # (shape, dtype) per output slot
        self.multi = multi  # forward returned a tuple (vjp expects tuple cotangent)
        self.hooks: List[Callable] = []
        # replay metadata for create_graph (higher-order) differentiation:
        # the pure forward fn + input tensor refs + their recorded values
        # (the reference keeps the static graph for GeneralGrad; we keep the
        # pure functions and rebuild a jax-differentiable composition)
        self.fwd = fwd
        self.input_tensors = input_tensors
        self.input_vals = input_vals

    def register_hook(self, hook: Callable):
        self.hooks.append(hook)

    def __repr__(self):
        return f"<GradNode {self.name} n_out={self.n_outputs}>"


def _check_nan_inf(name, arrays):
    for a in arrays:
        if isinstance(a, jax.core.Tracer):
            # inside a jit/lax trace the value is symbolic — the debug
            # check only applies to concrete eager outputs
            continue
        if jnp.issubdtype(a.dtype, jnp.floating):
            bad = bool(jnp.any(~jnp.isfinite(a)))
            if bad:
                raise FloatingPointError(
                    f"NaN/Inf detected in output of op '{name}' "
                    f"(FLAGS_check_nan_inf is on; reference parity: "
                    f"paddle/fluid/eager/nan_inf_utils.cc)")


def apply_op(fn: Callable, *inputs, op_name: Optional[str] = None, **attrs):
    """Run one op eagerly, recording a GradNode when gradients are required.

    ``fn`` is a pure function over jax arrays (Tensors in ``inputs`` are unwrapped,
    other leaves pass through). This is the analog of a generated ``*_ad_func``
    (reference anatomy: eager/api/manual/eager_manual/forwards/add_n_fwd_func.cc:25-80 —
    profiling scope, AMP cast, PHI call, nan/inf check, GradNode wiring), except
    dispatch is a direct call into jax and the GradNode is the vjp closure.
    """
    from .tensor import Tensor  # local import to break the cycle

    flat, treedef = jax.tree_util.tree_flatten(
        inputs, is_leaf=lambda x: isinstance(x, Tensor))
    t_idx = [i for i, x in enumerate(flat) if isinstance(x, Tensor)]
    t_inputs = [flat[i] for i in t_idx]
    arrays = [t.data for t in t_inputs]

    # AMP autocast: the analog of the reference's per-op EagerAmpAutoCasts
    # in every generated forward (eager/amp_utils.h) — cast floating inputs
    # by the active policy before dispatch
    arrays = _maybe_autocast(op_name or getattr(fn, "__name__", ""), arrays)

    def pure(*arrs):
        buf = list(flat)
        for i, a in zip(t_idx, arrs):
            buf[i] = a
        res = fn(*jax.tree_util.tree_unflatten(treedef, buf), **attrs)
        return tuple(res) if isinstance(res, list) else res

    requires = is_grad_enabled() and any(not t.stop_gradient for t in t_inputs)

    # profiler instrumentation (reference: RecordEvent in every generated
    # forward, add_n_fwd_func.cc:27); None — and zero overhead — unless a
    # Profiler is actively recording or the flight recorder is armed. The
    # operand arrays ride along for Profiler(record_shapes=True).
    _prof_ev = _record_op_event(op_name or getattr(fn, "__name__", "op"),
                                arrays)
    try:
        if requires:
            out, vjp_fn = jax.vjp(pure, *arrays)
        else:
            out = pure(*arrays)
    finally:
        if _prof_ev is not None:
            _prof_ev.end()

    multi = isinstance(out, (tuple, list))
    out_arrays = list(out) if multi else [out]

    if flags.flag("check_nan_inf"):
        _check_nan_inf(op_name or fn.__name__, out_arrays)

    # Only float outputs participate in AD.
    any_float_out = any(jnp.issubdtype(a.dtype, jnp.inexact) for a in out_arrays)
    node = None
    if requires and any_float_out:
        edges = []
        for t in t_inputs:
            if t.stop_gradient:
                edges.append(None)
            elif t._grad_node is not None:
                edges.append(("node", t._grad_node, t._out_idx))
            else:
                edges.append(("leaf", t))
        node = GradNode(
            op_name or getattr(fn, "__name__", "op"), vjp_fn, edges,
            len(out_arrays), [(a.shape, a.dtype) for a in out_arrays],
            multi=multi, fwd=pure, input_tensors=list(t_inputs),
            input_vals=list(arrays))

    outs = []
    for i, a in enumerate(out_arrays):
        differentiable = node is not None and jnp.issubdtype(a.dtype, jnp.inexact)
        t = Tensor(a, stop_gradient=not differentiable)
        if differentiable:
            t._grad_node = node
            t._out_idx = i
        outs.append(t)
    return tuple(outs) if multi else outs[0]


_record_op_hook = None


def _record_op_event(name, inputs=None):
    global _record_op_hook
    if _record_op_hook is None:
        try:
            from paddle_tpu.profiler import record_op
        except ImportError:
            record_op = None
        _record_op_hook = record_op if record_op is not None else False
    if _record_op_hook is False:
        return None
    return _record_op_hook(name, inputs)


def _maybe_autocast(op_name, arrays):
    try:
        from paddle_tpu.amp.auto_cast import amp_state, _policy_dtype
    except ImportError:
        return arrays
    state = amp_state()
    if state is None or not state.enable:
        return arrays
    target = _policy_dtype(state, op_name)
    if target is None:
        return arrays
    tgt = jnp.dtype({"bfloat16": jnp.bfloat16, "float16": jnp.float16,
                     "float32": jnp.float32}[target])
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != tgt:
            out.append(a.astype(tgt))
        else:
            out.append(a)
    return out


def _coerce_cot(g, aval):
    """Cast an accumulated cotangent to the forward output's dtype — under
    AMP a bf16 op can receive an f32 cotangent from a downstream fp32 op
    (the reference's GradTensorHolder performs the same cast)."""
    _, dtype = aval
    if hasattr(g, "dtype") and g.dtype != dtype and \
            jnp.issubdtype(g.dtype, jnp.inexact) and \
            jnp.issubdtype(dtype, jnp.inexact):
        return g.astype(dtype)
    return g


def _zeros_like_aval(aval):
    shape, dtype = aval
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    # integer/bool output slots take symbolic-zero (float0) cotangents
    import numpy as np
    return np.zeros(shape, jax.dtypes.float0)


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             grad_map: Optional[dict] = None,
             taps: Optional[dict] = None):
    """Run the tape backward from ``tensors`` (paddle.autograd.backward parity).

    BFS with in-degree counting, mirroring the reference RunBackward
    (paddle/fluid/eager/backward.cc:104): dependency counts are computed by a DFS
    over the subgraph reachable from the roots, then nodes execute once all their
    consumers have contributed cotangents. Root nodes that are themselves
    consumed by other roots (``backward([z, y])`` with ``z = f(y)``) are
    deferred until their consumers have run, matching the reference's
    re-queue-on-nonzero-in-degree check.

    When ``grad_map`` is given (the ``paddle.grad`` path), leaf gradients are
    collected into it keyed by ``id(leaf)`` instead of being written to
    ``Tensor.grad`` — so ``grad()`` never pollutes parameter ``.grad`` fields.
    ``taps`` maps ``id(tensor) -> (node, slot)`` for *intermediate* tensors
    whose accumulated cotangent should also be captured into ``grad_map``
    (the reference's GeneralGrad input-watching, eager/general_grad.h).
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # node -> list of accumulated output cotangents (per slot)
    holders = {}
    pending_leaf = {}

    def seed(t: Tensor, g):
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    "pass grad_tensors for non-scalar backward()")
            g = jnp.ones(t.data.shape, t.data.dtype)
        else:
            g = g.data if isinstance(g, Tensor) else jnp.asarray(g)
        if t._grad_node is None:
            if not t.stop_gradient:
                _accum_leaf(t, g)
            return None
        _accum_holder(t._grad_node, t._out_idx, g)
        return t._grad_node

    def _accum_holder(node, slot, g):
        h = holders.get(node)
        if h is None:
            h = [None] * node.n_outputs
            holders[node] = h
        h[slot] = g if h[slot] is None else h[slot] + g

    def _accum_leaf(t, g):
        # leaf grads carry the parameter's dtype (reference GradNodeAccum
        # casts the same way) — under AMP a bf16-cast op otherwise writes
        # bf16 grads for f32 params and accumulation loses mantissa bits
        if hasattr(g, "dtype") and hasattr(t.data, "dtype") and \
                g.dtype != t.data.dtype and \
                jnp.issubdtype(g.dtype, jnp.inexact) and \
                jnp.issubdtype(t.data.dtype, jnp.inexact):
            g = g.astype(t.data.dtype)
        if id(t) in pending_leaf:
            g = pending_leaf[id(t)][1] + g
        pending_leaf[id(t)] = (t, g)

    roots = []
    for t, g in zip(tensors, grad_tensors):
        n = seed(t, g)
        if n is not None:
            roots.append(n)

    # dependency counting (consumers per node)
    indeg = {}
    seen = set()
    stack = list(dict.fromkeys(roots))
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        for e in n.edges:
            if e is not None and e[0] == "node":
                p = e[1]
                indeg[id(p)] = indeg.get(id(p), 0) + 1
                stack.append(p)

    ready = [n for n in dict.fromkeys(roots) if indeg.get(id(n), 0) == 0]
    processed = set()
    while ready:
        node = ready.pop()
        if id(node) in processed:
            continue
        processed.add(id(node))
        h = holders.pop(node, None)
        if h is None:
            h = [None] * node.n_outputs
        if taps:
            for tid, (tn, slot) in taps.items():
                if tn is node and h[slot] is not None and grad_map is not None:
                    grad_map[tid] = h[slot]
        cots = tuple(
            _coerce_cot(h[i], node.out_avals[i])
            if h[i] is not None else _zeros_like_aval(node.out_avals[i])
            for i in range(node.n_outputs))
        for hook in node.hooks:
            cots = hook(cots) or cots
        if node.vjp_fn is None:
            raise RuntimeError(
                f"trying to backward through node '{node.name}' a second time "
                "but the saved intermediates were freed; call backward/grad "
                "with retain_graph=True the first time")
        # backward dispatch is instrumented like forward dispatch (the
        # reference spans every GradNode run in RunBackward)
        _ev = _record_op_event(f"grad::{node.name}")
        try:
            in_cots = node.vjp_fn(cots if _vjp_multi(node) else cots[0])
        finally:
            if _ev is not None:
                _ev.end()
        if not retain_graph:
            # free residuals AND replay metadata (fwd closes over the same
            # activations; keeping it would defeat the free)
            node.vjp_fn = None
            node.fwd = None
            node.input_tensors = None
            node.input_vals = None
        for e, g in zip(node.edges, in_cots):
            if e is None:
                continue
            real = g is not None and not _is_float0(g)
            if e[0] == "leaf":
                if real:
                    _accum_leaf(e[1], g)
            else:
                _, p, slot = e
                if real:
                    _accum_holder(p, slot, g)
                # decrement even for dropped cotangents or the parent never fires
                indeg[id(p)] -= 1
                if indeg[id(p)] == 0:
                    ready.append(p)
    for t, g in list(pending_leaf.values()):
        if grad_map is not None:
            grad_map[id(t)] = _run_leaf_hooks(t, g)
        else:
            _write_leaf_grad(t, g)


def _topo_nodes(outputs):
    """Producer-first topological order of nodes reachable from outputs."""
    order, seen = [], set()

    def visit(node):
        if node is None or id(node) in seen:
            return
        seen.add(id(node))
        for t in node.input_tensors or ():
            if t._grad_node is not None:
                visit(t._grad_node)
        order.append(node)
    for t in outputs:
        if t._grad_node is not None:
            visit(t._grad_node)
    return order


def make_replay_fn(outputs, leaves):
    """Rebuild the recorded computation reaching ``outputs`` as one pure
    jax function of ``leaves``' values (the static-graph executor's seam;
    the reference's analog is running a captured Program through
    InterpreterCore, SURVEY.md §2.3).

    Returns ``fn(*leaf_arrays) -> tuple(output_arrays)``. Tensors not in
    ``leaves`` take their recorded values; requires the tape's replay
    metadata (i.e. no backward(retain_graph=False) ran over this graph).
    """
    nodes = _topo_nodes(outputs)
    if any(n.fwd is None for n in nodes):
        raise RuntimeError(
            "replay requires the recorded forward functions; part of this "
            "graph was freed (backward without retain_graph?)")
    # an output that is itself a leaf argument resolves to the replay
    # ARGUMENT (grad(y, y) is the identity), not its recomputed value
    leaf_ids = {id(t) for t in leaves}
    out_keys = [("leaf", id(t)) if (id(t) in leaf_ids
                                    or t._grad_node is None)
                else (id(t._grad_node), t._out_idx) for t in outputs]

    def replay(*inner):
        env = {}
        leaf_env = {id(t): a for t, a in zip(leaves, inner)}
        for node in nodes:
            vals = []
            for t, recorded in zip(node.input_tensors, node.input_vals):
                if id(t) in leaf_env:
                    vals.append(leaf_env[id(t)])
                elif t._grad_node is not None and \
                        (id(t._grad_node), t._out_idx) in env:
                    vals.append(env[(id(t._grad_node), t._out_idx)])
                else:
                    vals.append(recorded)
            res = node.fwd(*vals)
            res_list = list(res) if isinstance(res, (tuple, list)) \
                else [res]
            for slot, v in enumerate(res_list):
                env[(id(node), slot)] = v
        outs = []
        for key, t in zip(out_keys, outputs):
            if key[0] == "leaf":
                outs.append(leaf_env.get(id(t), t.data))
            else:
                outs.append(env[key])
        return tuple(outs)

    return replay


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused):
    """Higher-order paddle.grad: rebuild the recorded computation as one
    pure jax function (replaying each node's stored forward), differentiate
    with jax.vjp, and run the result THROUGH the tape so it is itself
    differentiable (reference: eager/general_grad.h create_graph path)."""
    from .tensor import Tensor

    nodes = _topo_nodes(outputs)
    if any(n.fwd is None for n in nodes):
        raise RuntimeError(
            "create_graph requires the recorded forward functions; part of "
            "this graph was freed (backward without retain_graph?)")
    # connectivity check for allow_unused semantics (outputs themselves
    # are reachable: grad(y, y) is the identity cotangent)
    reachable = {id(t) for t in outputs}
    for n in nodes:
        for t in n.input_tensors:
            reachable.add(id(t))

    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    # Tensor-valued cotangents enter the differentiable call as arguments —
    # the result must stay differentiable w.r.t. them (forward_grad's
    # vjp-of-vjp construction depends on d(J^T w)/dw; the reference keeps
    # this linearity because its grads are graph ops over grad_outputs)
    cot_tensors = [g for g in grad_outputs if isinstance(g, Tensor)]

    # every OTHER differentiable leaf also enters the replay as an argument
    # so the returned grads stay differentiable w.r.t. them (mixed partials
    # like d2z/dxdy where only x was requested in the first grad call)
    extras, seen_extra = [], {id(t) for t in inputs}
    for n in nodes:
        for t in n.input_tensors:
            if not t.stop_gradient and id(t) not in seen_extra and \
                    t._grad_node is None:
                seen_extra.add(id(t))
                extras.append(t)
    all_args = list(inputs) + extras
    replay = make_replay_fn(outputs, all_args)

    def g_fn(*arrs):
        leaf_arrs = arrs[: len(all_args)]
        cot_arrs = iter(arrs[len(all_args):])
        cots = []
        for t, g in zip(outputs, grad_outputs):
            if isinstance(g, Tensor):
                cots.append(next(cot_arrs))
            elif g is None:
                cots.append(jnp.ones(t.data.shape, t.data.dtype))
            else:
                cots.append(jnp.asarray(g))
        _, vjp = jax.vjp(replay, *leaf_arrs)
        return vjp(tuple(cots))[: len(inputs)]

    grads = apply_op(g_fn, *all_args, *cot_tensors, op_name="grad")
    grads = list(grads) if isinstance(grads, (tuple, list)) else [grads]
    results = []
    for t, g in zip(inputs, grads):
        # stop_gradient inputs get no gradient, matching the first-order
        # path (the replay would otherwise happily differentiate them)
        if id(t) not in reachable or t.stop_gradient:
            if not allow_unused:
                raise RuntimeError(
                    "one of the input tensors received no gradient; pass "
                    "allow_unused=True to get None instead")
            results.append(None)
        else:
            results.append(g)
    return results


def _vjp_multi(node):
    return node.multi


def _is_float0(g):
    return hasattr(g, "dtype") and g.dtype == jax.dtypes.float0


def _run_leaf_hooks(t, g):
    from .tensor import Tensor
    for hook in t._hooks:
        out = hook(Tensor(g, stop_gradient=True))
        if out is not None:
            g = out.data if isinstance(out, Tensor) else out
    return g


def _write_leaf_grad(t, g):
    from .tensor import Tensor
    g = _run_leaf_hooks(t, g)
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad.data + g, stop_gradient=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """paddle.grad parity (first order; reference: eager/general_grad.h).

    Leaf grads are collected into a side map during the backward walk, so no
    ``.grad`` field anywhere in the model is touched.
    """
    from .tensor import Tensor
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if create_graph:
        return _grad_create_graph(outputs, inputs, grad_outputs,
                                  allow_unused)

    gmap: dict = {}
    taps = {id(t): (t._grad_node, t._out_idx)
            for t in inputs if t._grad_node is not None}
    backward(outputs, grad_outputs, retain_graph=retain_graph, grad_map=gmap,
             taps=taps)
    results = []
    for t in inputs:
        g = gmap.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(
                "one of the input tensors received no gradient; pass "
                "allow_unused=True to get None instead")
        if g is not None and hasattr(g, "dtype") and \
                g.dtype != t.data.dtype and \
                jnp.issubdtype(g.dtype, jnp.inexact) and \
                jnp.issubdtype(t.data.dtype, jnp.inexact):
            g = g.astype(t.data.dtype)  # AMP: grads in the input's dtype
        results.append(Tensor(g, stop_gradient=True) if g is not None else None)
    return results
