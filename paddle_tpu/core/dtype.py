"""Dtype system for paddle_tpu.

Capability parity with the reference's ``phi::DataType`` / ``paddle/phi/common/data_type.h``
(see SURVEY.md §2.1 "DDim/layout/dtype"), redesigned for TPU: dtypes are thin wrappers
over jnp dtypes, bfloat16 is first-class (the TPU-native 16-bit format), and there is no
per-backend layout enum — XLA owns layout.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = [
    "DType",
    "float32",
    "float64",
    "float16",
    "bfloat16",
    "int8",
    "int16",
    "int32",
    "int64",
    "uint8",
    "uint16",
    "uint32",
    "uint64",
    "bool_",
    "complex64",
    "complex128",
    "float8_e4m3fn",
    "float8_e5m2",
    "convert_dtype",
    "is_floating_point",
    "is_integer",
    "finfo",
    "iinfo",
]


class DType:
    """A named dtype. Compares equal to its string name and to the jnp dtype."""

    __slots__ = ("name", "np_dtype")

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = jnp.dtype(np_dtype)

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.np_dtype == other.np_dtype
        if isinstance(other, str):
            try:
                return self.np_dtype == convert_dtype(other).np_dtype
            except (TypeError, ValueError):
                return False
        try:
            return self.np_dtype == jnp.dtype(other)
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(self.np_dtype)

    @property
    def itemsize(self) -> int:
        return self.np_dtype.itemsize

    def __str__(self):
        return self.name


float32 = DType("float32", jnp.float32)
float64 = DType("float64", jnp.float64)
float16 = DType("float16", jnp.float16)
bfloat16 = DType("bfloat16", jnp.bfloat16)
int8 = DType("int8", jnp.int8)
int16 = DType("int16", jnp.int16)
int32 = DType("int32", jnp.int32)
int64 = DType("int64", jnp.int64)
uint8 = DType("uint8", jnp.uint8)
uint16 = DType("uint16", jnp.uint16)
uint32 = DType("uint32", jnp.uint32)
uint64 = DType("uint64", jnp.uint64)
bool_ = DType("bool", jnp.bool_)
complex64 = DType("complex64", jnp.complex64)
complex128 = DType("complex128", jnp.complex128)
float8_e4m3fn = DType("float8_e4m3fn", jnp.float8_e4m3fn)
float8_e5m2 = DType("float8_e5m2", jnp.float8_e5m2)

_ALL = [
    float32, float64, float16, bfloat16, int8, int16, int32, int64,
    uint8, uint16, uint32, uint64, bool_, complex64, complex128,
    float8_e4m3fn, float8_e5m2,
]
_BY_NAME = {d.name: d for d in _ALL}
_BY_NAME["bool_"] = bool_
_BY_NAME["float"] = float32
_BY_NAME["double"] = float64
_BY_NAME["half"] = float16
_BY_NAME["int"] = int32
_BY_NAME["long"] = int64
_BY_NP = {d.np_dtype: d for d in _ALL}


def convert_dtype(dtype) -> DType:
    """Normalize any dtype spec (DType, str, numpy/jnp dtype, python type) to DType."""
    if dtype is None:
        return float32
    if isinstance(dtype, DType):
        return dtype
    if isinstance(dtype, str):
        if dtype in _BY_NAME:
            return _BY_NAME[dtype]
        return _BY_NP.get(jnp.dtype(dtype)) or DType(dtype, jnp.dtype(dtype))
    npd = jnp.dtype(dtype)
    got = _BY_NP.get(npd)
    if got is None:
        got = DType(npd.name, npd)
    return got


def is_floating_point(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype).np_dtype, jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(convert_dtype(dtype).np_dtype, jnp.integer)


def finfo(dtype):
    return jnp.finfo(convert_dtype(dtype).np_dtype)


def iinfo(dtype):
    return np.iinfo(convert_dtype(dtype).np_dtype)
