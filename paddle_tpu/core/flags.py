"""Global flag registry.

TPU-native analog of the reference's exported gflags
(``paddle/phi/core/flags.cc`` — 90 ``FLAGS_*`` entries — surfaced to Python through
``paddle.set_flags`` / ``paddle.get_flags``; SURVEY.md §5 "Config / flag system").
Flags here are plain Python with env-var override (``FLAGS_<name>``), since there is no
C++ gflags layer between Python and XLA on TPU.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable, Union

_REGISTRY: Dict[str, "_Flag"] = {}


class _Flag:
    __slots__ = ("name", "value", "default", "doc", "type")

    def __init__(self, name: str, default: Any, doc: str = ""):
        self.name = name
        self.default = default
        self.doc = doc
        self.type = type(default)
        env = os.environ.get("FLAGS_" + name)
        if env is not None:
            self.value = _parse(env, self.type)
        else:
            self.value = default


def _parse(s: str, ty):
    if ty is bool:
        return s.lower() in ("1", "true", "yes", "on")
    if ty is int:
        return int(s)
    if ty is float:
        return float(s)
    return s


def define_flag(name: str, default: Any, doc: str = "") -> None:
    if name not in _REGISTRY:
        _REGISTRY[name] = _Flag(name, default, doc)


def set_flags(flags: Dict[str, Any]) -> None:
    """paddle.set_flags parity (reference: pybind global_value_getter_setter.cc)."""
    for k, v in flags.items():
        k = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        if k not in _REGISTRY:
            define_flag(k, v)
        else:
            _REGISTRY[k].value = v


def get_flags(flags: Union[str, Iterable[str]]) -> Dict[str, Any]:
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        key = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {k!r}")
        out[k] = _REGISTRY[key].value
    return out


def flag(name: str) -> Any:
    """Fast internal accessor."""
    return _REGISTRY[name].value


# Core flags (subset of the reference's inventory that is meaningful on TPU).
define_flag("check_nan_inf", False, "check every op output for NaN/Inf (reference: FLAGS_check_nan_inf)")
define_flag("eager_delete_tensor_gb", 0.0, "compat no-op: XLA owns buffer lifetime")
define_flag("allocator_strategy", "xla", "compat: TPU memory is managed by the XLA runtime")
define_flag("benchmark", False, "sync after every op for timing")
define_flag("default_dtype", "float32", "default floating dtype for tensor creation")
define_flag("matmul_precision", "default", "jax matmul precision: default|high|highest")
define_flag("use_pallas_kernels", True, "use Pallas fused kernels (flash attention etc.) when on TPU")
define_flag("log_level", 0, "VLOG-style verbosity")
define_flag("use_autotune", False, "sweep Pallas block sizes / fused-CE chunk counts once per shape signature and cache the winner (reference: FLAGS_use_autotune + phi/kernels/autotune)")
