"""The eager Tensor.

Capability parity with the reference's dygraph Tensor
(``phi::DenseTensor`` + the eager ``paddle::Tensor`` with autograd meta;
``paddle/phi/core/dense_tensor.h:38``, ``paddle/fluid/eager/``; SURVEY.md §2.1/§2.3),
redesigned for TPU: the storage is a ``jax.Array`` (device memory owned by the XLA
runtime — no framework allocator needed, cf. reference ``fluid/memory/``), shape/dtype
come from the array's aval (no separate DDim/InferMeta bookkeeping in eager mode), and
autograd metadata is the tape described in :mod:`paddle_tpu.core.autograd`.

Paddle semantics preserved:
  * ``stop_gradient`` defaults to True for user-created tensors and False for
    ``Parameter``s.
  * ``.grad`` is populated by ``backward()`` and accumulates across calls until
    ``clear_grad()``.
  * inplace-style APIs (``set_value``, ``fill_``, ``zero_``...) mutate the leaf's
    storage reference (functional under the hood — the old array is replaced).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd as _ag
from .dtype import DType, convert_dtype

__all__ = ["Tensor", "Parameter", "to_tensor"]


def _ops():
    from paddle_tpu import ops
    return ops


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_grad_node", "_out_idx",
                 "name", "persistable", "_hooks", "_version", "_sharding_spec",
                 "trainable", "__weakref__", "__dict__")

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        elif not isinstance(data, jax.Array) and not _is_tracer(data):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_idx = 0
        self.name = name or ""
        self.persistable = False
        self._hooks = []
        self._version = 0
        self._sharding_spec = None  # distributed placement annotation (dist module)
        self.trainable = not stop_gradient

    # -- storage ---------------------------------------------------------------
    @property
    def data(self):
        return self._data

    @data.setter
    def data(self, value):
        self._data = value._data if isinstance(value, Tensor) else value
        self._version += 1

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self) -> DType:
        return convert_dtype(self._data.dtype)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    ndimension = ndim

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    def numel(self):
        return to_tensor(self.size, dtype="int64")

    def dim(self):
        return self.ndim

    @property
    def place(self) -> str:
        try:
            devs = self._data.devices()
            d = next(iter(devs))
            return f"{d.platform}:{d.id}"
        except Exception:
            return "traced"

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    # -- conversion ------------------------------------------------------------
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        return self._data.item(*args)

    def tolist(self):
        return np.asarray(self._data).tolist()

    def astype(self, dtype):
        return _ops().cast(self, dtype)

    def cast(self, dtype):
        return _ops().cast(self, dtype)

    def cpu(self):
        cpu_dev = jax.devices("cpu")[0]
        return Tensor(jax.device_put(self._data, cpu_dev),
                      stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        dtype = kwargs.pop("dtype", None)
        device = kwargs.pop("device", None) or kwargs.pop("place", None)
        kwargs.pop("blocking", None)
        for a in args:
            if isinstance(a, DType):
                dtype = a
            elif isinstance(a, str):
                try:
                    dtype = convert_dtype(a)
                except (KeyError, ValueError, TypeError):
                    device = a
            elif isinstance(a, Tensor):
                dtype = a.dtype
        t = self
        if device is not None:
            plat, _, idx = str(device).partition(":")
            plat = {"xpu": "tpu"}.get(plat, plat)
            try:
                devs = jax.devices(plat)
            except RuntimeError as e:
                raise ValueError(f"unknown device '{device}': {e}") from None
            d = devs[int(idx)] if idx else devs[0]
            # routed through the tape (identity vjp) so transfers mid-graph
            # keep gradients flowing to upstream leaves
            t = _ag.apply_op(lambda v: jax.device_put(v, d), t,
                             op_name="device_put")
        if dtype is not None:
            t = t.astype(dtype)
        return t

    def pin_memory(self):
        return self  # host staging is managed by the XLA runtime on TPU

    # -- autograd --------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        _ag.backward([self], [grad_tensor], retain_graph=retain_graph)

    def register_hook(self, hook):
        if self.stop_gradient:
            raise RuntimeError("cannot register hook on a tensor with "
                               "stop_gradient=True")
        self._hooks.append(hook)

        class _Handle:
            def remove(_self):
                if hook in self._hooks:
                    self._hooks.remove(hook)
        return _Handle()

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True, name=self.name)

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        return _ops().assign(self)

    @property
    def inplace_version(self):
        return self._version

    # -- inplace-style mutation (leaf storage replacement) ---------------------
    def set_value(self, value):
        value = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._data.shape}")
        self._data = value.astype(self._data.dtype)
        self._version += 1
        return self

    def copy_(self, other):
        return self.set_value(other)

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        self._version += 1
        return self

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        self._version += 1
        return self

    def scale_(self, scale):
        self._data = self._data * scale
        self._version += 1
        return self

    def _inplace(self, new_data):
        self._data = new_data
        self._version += 1
        return self

    def _inplace_keep_dtype(self, new_data):
        # in-place ops preserve dtype AND shape (set_value invariants):
        # an int tensor must not silently become float, and a parameter
        # must not be broadcast into a new shape under its optimizer
        if tuple(new_data.shape) != tuple(self._data.shape):
            raise ValueError(
                f"in-place op would change shape "
                f"{tuple(self._data.shape)} -> {tuple(new_data.shape)}")
        return self._inplace(new_data.astype(self._data.dtype))

    def add_(self, other):
        return self._inplace_keep_dtype(self._data + (
            other._data if isinstance(other, Tensor) else other))

    def subtract_(self, other):
        return self._inplace_keep_dtype(self._data - (
            other._data if isinstance(other, Tensor) else other))

    def multiply_(self, other):
        return self._inplace_keep_dtype(self._data * (
            other._data if isinstance(other, Tensor) else other))

    def clip_(self, min=None, max=None):
        return self._inplace_keep_dtype(jnp.clip(self._data, min, max))

    def uniform_(self, min=-1.0, max=1.0, seed=0):
        # same key derivation as ops.uniform (creation.py): identical
        # seeds must reproduce across the two APIs
        from .generator import next_key
        key = jax.random.key(seed) if seed else next_key()
        return self._inplace(jax.random.uniform(
            key, self._data.shape, self._data.dtype, min, max))

    def normal_(self, mean=0.0, std=1.0, name=None):
        from .generator import next_key
        return self._inplace(mean + std * jax.random.normal(
            next_key(), self._data.shape, self._data.dtype))

    def exponential_(self, lam=1.0):
        return _ops().exponential_(self, lam)

    # -- torch/paddle convenience surface -------------------------------------
    def element_size(self) -> int:
        return self._data.dtype.itemsize

    def nelement(self) -> int:
        return self.size

    def is_contiguous(self) -> bool:
        return True  # jax arrays are always dense row-major to the user

    def contiguous(self):
        return self

    def cuda(self, device_id=None, blocking=True):
        # no CUDA in this build (BASELINE.md); the accelerator is whatever
        # PJRT provides — placement is a no-op like .cpu()
        return self

    def bfloat16(self):
        return self.astype("bfloat16")

    def half(self):
        return self.astype("float16")

    def float(self):
        return self.astype("float32")

    def sub(self, other):
        return _ops().subtract(self, other)

    # -- indexing --------------------------------------------------------------
    def __getitem__(self, idx):
        if isinstance(idx, Tensor):
            idx = idx._data
        elif isinstance(idx, tuple):
            idx = tuple(i._data if isinstance(i, Tensor) else i for i in idx)
        return _ag.apply_op(lambda x: x[idx], self, op_name="getitem")

    def __setitem__(self, idx, value):
        if isinstance(idx, Tensor):
            idx = idx._data
        elif isinstance(idx, tuple):
            idx = tuple(i._data if isinstance(i, Tensor) else i for i in idx)
        value = value._data if isinstance(value, Tensor) else value
        self._data = self._data.at[idx].set(value)
        self._version += 1

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    # -- arithmetic dunders (delegate to ops for tape recording) ---------------
    def __add__(self, o):
        return _ops().add(self, o)

    __radd__ = __add__

    def __sub__(self, o):
        return _ops().subtract(self, o)

    def __rsub__(self, o):
        return _ops().subtract(o, self)

    def __mul__(self, o):
        return _ops().multiply(self, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return _ops().divide(self, o)

    def __rtruediv__(self, o):
        return _ops().divide(o, self)

    def __floordiv__(self, o):
        return _ops().floor_divide(self, o)

    def __mod__(self, o):
        return _ops().remainder(self, o)

    def __pow__(self, o):
        return _ops().pow(self, o)

    def __rpow__(self, o):
        return _ops().pow(o, self)

    def __matmul__(self, o):
        return _ops().matmul(self, o)

    def __rmatmul__(self, o):
        return _ops().matmul(o, self)

    def __neg__(self):
        return _ops().scale(self, -1.0)

    def __abs__(self):
        return _ops().abs(self)

    def __invert__(self):
        return _ops().logical_not(self)

    def __eq__(self, o):
        return _ops().equal(self, o)

    def __ne__(self, o):
        return _ops().not_equal(self, o)

    def __lt__(self, o):
        return _ops().less_than(self, o)

    def __le__(self, o):
        return _ops().less_equal(self, o)

    def __gt__(self, o):
        return _ops().greater_than(self, o)

    def __ge__(self, o):
        return _ops().greater_equal(self, o)

    def __and__(self, o):
        return _ops().logical_and(self, o)

    def __or__(self, o):
        return _ops().logical_or(self, o)

    def __hash__(self):
        return id(self)

    def __bool__(self):
        try:
            return bool(self._data)
        except Exception as e:  # jax TracerBoolConversionError
            if "Tracer" in type(e).__name__ or "racer" in str(e):
                raise TypeError(
                    "a Tensor's truth value was read during trace capture "
                    "(to_static / TrainStep / Executor): data-dependent "
                    "Python `if`/`while` cannot be compiled. Use "
                    "paddle_tpu.static.nn.cond(pred, true_fn, false_fn) "
                    "or paddle_tpu.static.nn.while_loop(cond, body, "
                    "loop_vars) — XLA-native control flow that stays "
                    "inside the compiled program.") from e
            raise

    def __float__(self):
        return float(self._data)

    def __int__(self):
        return int(self._data)

    def __index__(self):
        return int(self._data)

    @property
    def T(self):
        return _ops().transpose(self, list(range(self.ndim))[::-1])

    # numpy interop
    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        try:
            data_repr = repr(np.asarray(self._data))
        except Exception:
            data_repr = f"<traced {self._data.shape} {self._data.dtype}>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_info},\n       {data_repr})")


class Parameter(Tensor):
    """A trainable leaf tensor (reference: ``paddle.fluid.framework.Parameter``)."""

    def __init__(self, data, name: Optional[str] = None, trainable: bool = True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor parity."""
    if isinstance(data, Tensor):
        arr = data._data
    elif isinstance(data, (jax.Array,)) or _is_tracer(data):
        arr = data
    else:
        arr = np.asarray(data)
        # Paddle defaults python floats to the default float dtype, ints to int64.
        if dtype is None and arr.dtype == np.float64 and isinstance(
                data, (float, list, tuple)):
            arr = arr.astype(np.float32)
        arr = jnp.asarray(arr)
    if dtype is not None:
        arr = arr.astype(convert_dtype(dtype).np_dtype)
    return Tensor(arr, stop_gradient=stop_gradient)
