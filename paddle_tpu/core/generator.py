"""RNG state management.

TPU-native replacement for the reference's stateful Philox generator
(``phi::Generator``, ``paddle/phi/core/generator.h:36``) and the tensor-parallel
``RNGStatesTracker`` (``python/paddle/distributed/fleet/layers/mpu/random.py:35``).

JAX RNG is key-based and functional; we expose Paddle's stateful-seed UX on top of it:
each :class:`Generator` owns (seed, counter) and derives key #n as
``fold_in(key(seed), n)`` — deterministic, replayable, and safe under jit tracing via
:func:`rng_guard`, which rebases the generator on an explicitly-threaded traced key
(the functional train step passes the key in as an argument; see paddle_tpu/jit).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax

__all__ = ["Generator", "default_generator", "seed", "get_rng_state", "set_rng_state",
           "rng_guard", "RNGStatesTracker", "get_rng_tracker", "next_key"]

_tls = threading.local()


class Generator:
    """Stateful seed/counter pair producing a deterministic stream of JAX PRNG keys."""

    def __init__(self, seed: int = 0):
        self._seed = int(seed)
        self._count = 0
        self._base_override = None  # traced key installed by rng_guard
        self._base_cache = None     # (seed, key): jax.random.key is pure

    def manual_seed(self, seed: int) -> "Generator":
        self._seed = int(seed)
        self._count = 0
        return self

    def seed(self) -> int:
        return self._seed

    def get_state(self):
        return (self._seed, self._count)

    def set_state(self, state):
        self._seed, self._count = int(state[0]), int(state[1])

    def _base_key(self):
        if self._base_override is not None:
            return self._base_override
        # cache the base key per seed: rebuilding it is an eager XLA
        # dispatch that measurably taxes every compiled train step
        # (next_key runs once per step on the hot path)
        if self._base_cache is None or self._base_cache[0] != self._seed:
            self._base_cache = (self._seed, jax.random.key(self._seed))
        return self._base_cache[1]

    def next_key(self):
        """Return the next PRNG key in this generator's stream."""
        k = jax.random.fold_in(self._base_key(), self._count)
        self._count += 1
        return k

    def next_key_parts(self):
        """``(base_key, count)`` with the counter advanced — for hot
        paths that run ``fold_in(base, count)`` INSIDE their compiled
        program instead of paying an eager dispatch per step.
        ``fold_in(base, count)`` equals what ``next_key()`` would have
        returned."""
        base = self._base_key()
        c = self._count
        self._count += 1
        return base, c


default_generator = Generator(0)


def seed(s: int) -> Generator:
    """paddle.seed parity: seeds the default generator and every tracker state."""
    default_generator.manual_seed(s)
    tracker = get_rng_tracker()
    for name in list(tracker._states):
        tracker._states[name] = Generator(s + tracker._offsets.get(name, 0))
    return default_generator


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)


def next_key():
    """Next key from whichever generator is active (tracker state or default)."""
    gen = getattr(_tls, "active_generator", None) or default_generator
    return gen.next_key()


def next_key_parts():
    """``(base_key, count)`` from the active generator — fold inside a
    compiled program instead of paying an eager per-step dispatch."""
    gen = getattr(_tls, "active_generator", None) or default_generator
    return gen.next_key_parts()


@contextlib.contextmanager
def rng_guard(key, generator: Optional[Generator] = None):
    """Rebase a generator onto an explicit (possibly traced) key for the duration.

    Used by the functional/jit path to keep randomness pure: the caller threads a key
    through the step function and all stateful ``next_key()`` calls inside derive from
    it with a counter reset, so retracing is deterministic.
    """
    gen = generator or default_generator
    old = (gen._base_override, gen._count)
    gen._base_override = key
    gen._count = 0
    try:
        yield gen
    finally:
        gen._base_override, gen._count = old


class RNGStatesTracker:
    """Named RNG streams for tensor parallelism.

    Parity with the reference's tracker (mpu/random.py:35): distinguishes e.g. a
    ``global_seed`` stream (same across the model-parallel group — dropout on
    replicated activations) from ``local_seed`` (different per mp rank — dropout on
    sharded activations).
    """

    def __init__(self):
        self._states = {}
        self._offsets = {}

    def add(self, name: str, seed: int):
        if name in self._states:
            raise ValueError(f"rng state {name!r} already exists")
        self._states[name] = Generator(seed)
        self._offsets[name] = seed - default_generator.seed()

    def states(self):
        return dict(self._states)

    @contextlib.contextmanager
    def rng_state(self, name: str):
        if name not in self._states:
            raise ValueError(f"unknown rng state {name!r}")
        prev = getattr(_tls, "active_generator", None)
        _tls.active_generator = self._states[name]
        try:
            yield
        finally:
            _tls.active_generator = prev


_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    return _TRACKER
