from . import dtype, flags, generator, autograd  # noqa: F401
from .tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .autograd import no_grad, enable_grad, is_grad_enabled, grad  # noqa: F401
