"""paddle.metric parity (reference: ``python/paddle/metric/metrics.py``:
Metric base, Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import numpy as np

from paddle_tpu.core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc"]


def _np(x):
    return np.asarray(x.data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional pre-processing hook run on (pred, label) before update
        (reference lets it run in-graph; here it is host-side)."""
        return args


class Accuracy(Metric):
    """top-k accuracy (reference: metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        order = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = label[..., 0]  # paddle's [N, 1] class-index labels
        elif label.ndim == pred.ndim:  # one-hot / soft labels
            label = label.argmax(-1)
        correct = order == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        for i, k in enumerate(self.topk):
            num = correct[..., :k].sum()
            self.total[i] += num
            self.count[i] += correct[..., :1].size
        acc = self.total / np.maximum(self.count, 1)
        return acc[0] if len(self.topk) == 1 else acc

    def accumulate(self):
        acc = self.total / np.maximum(self.count, 1)
        return float(acc[0]) if len(self.topk) == 1 else acc.tolist()

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision over probability predictions (reference semantics:
    pred > 0.5 counts positive)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds).ravel() > 0.5).astype(np.int64)
        y = _np(labels).ravel().astype(np.int64)
        self.tp += int(((p == 1) & (y == 1)).sum())
        self.fp += int(((p == 1) & (y == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds).ravel() > 0.5).astype(np.int64)
        y = _np(labels).ravel().astype(np.int64)
        self.tp += int(((p == 1) & (y == 1)).sum())
        self.fn += int(((p == 0) & (y == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via the reference's thresholded-bucket accumulation
    (metrics.py Auc: num_thresholds bins, trapezoid area)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        n = self._num_thresholds + 1
        self._stat_pos = np.zeros(n)
        self._stat_neg = np.zeros(n)

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]  # prob of the positive class
        preds = preds.ravel()
        labels = _np(labels).ravel().astype(np.int64)
        idx = np.clip((preds * self._num_thresholds).astype(np.int64), 0,
                      self._num_thresholds)
        np.add.at(self._stat_pos, idx[labels == 1], 1)
        np.add.at(self._stat_neg, idx[labels == 0], 1)

    def accumulate(self):
        # walk thresholds high→low accumulating TP/FP; trapezoid area
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = np.concatenate([[0.0], tp / tot_pos])
        fpr = np.concatenate([[0.0], fp / tot_neg])
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name
