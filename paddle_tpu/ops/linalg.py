"""Linear algebra ops.

matmul/bmm/einsum are MXU territory: kept as single lax.dot_general calls so XLA
tiles them onto the 128x128 systolic array (reference equivalents:
phi/kernels/impl/matmul_kernel_impl.h over cuBLAS; funcs/blas). Decompositions
(svd/qr/...) delegate to jnp.linalg (CPU/host lowering where TPU lacks them, as
the reference delegates to cuSolver)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._registry import op
from ._common import LONG
from paddle_tpu.core import flags


def _precision():
    p = flags.flag("matmul_precision")
    return None if p == "default" else p


@op
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y, precision=_precision())


@op
def bmm(x, y):
    return jnp.matmul(x, y, precision=_precision())


@op
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@op
def inner(x, y):
    return jnp.inner(x, y)


@op
def outer(x, y):
    return jnp.outer(x, y)


@op
def mv(x, vec):
    return jnp.matmul(x, vec, precision=_precision())


@op
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y, precision=_precision())


@op
def einsum(equation, *operands):
    return jnp.einsum(equation, *operands, precision=_precision())


@op
def norm(x, p="fro", axis=None, keepdim=False):
    if p == "fro":
        if axis is None:
            return jnp.sqrt(jnp.sum(jnp.square(x)))
        return jnp.linalg.norm(x, ord="fro" if isinstance(axis, (list, tuple))
                               else None, axis=axis if isinstance(axis, int)
                               else tuple(axis), keepdims=keepdim)
    if p == float("inf") or p == "inf":
        ordv = jnp.inf
    elif p == float("-inf") or p == "-inf":
        ordv = -jnp.inf
    else:
        ordv = p
    if axis is None:
        return jnp.linalg.norm(jnp.ravel(x), ord=ordv, keepdims=keepdim)
    return jnp.linalg.norm(x, ord=ordv,
                           axis=axis if isinstance(axis, int) else tuple(axis),
                           keepdims=keepdim)


@op
def dist(x, y, p=2.0):
    d = x - y
    if p == 0:
        return jnp.sum(d != 0).astype(x.dtype)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)


@op
def cross(x, y, axis=None):
    return jnp.cross(x, y, axis=-1 if axis is None else int(axis))


@op
def cdist(x, y, p=2.0):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(jnp.square(diff), axis=-1) + 0.0)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(diff), p), axis=-1), 1.0 / p)


@op
def histogram(x, bins=100, min=0, max=0):
    rng = None if (min == 0 and max == 0) else (min, max)
    hist, _ = jnp.histogram(x, bins=bins, range=rng)
    return hist.astype(LONG)


@op
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength,
                        length=None)


# -- decompositions / solvers --------------------------------------------------
@op
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@op
def cholesky_solve(x, y, upper=False):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@op
def qr(x, mode="reduced"):
    q, r = jnp.linalg.qr(x, mode=mode)
    return q, r


@op
def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


@op
def eig(x):
    return jnp.linalg.eig(x)


@op
def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


@op
def eigvals(x):
    return jnp.linalg.eigvals(x)


@op
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@op
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol).astype(LONG)


@op
def det(x):
    return jnp.linalg.det(x)


@op
def slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@op
def inverse(x):
    return jnp.linalg.inv(x)


@op
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@op
def solve(x, y):
    return jnp.linalg.solve(x, y)


@op
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@op
def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@op
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, int(n))


@op
def multi_dot(xs):
    return jnp.linalg.multi_dot(xs, precision=_precision())


@op
def lu(x, pivot=True):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv.astype(jnp.int32) + 1  # paddle returns 1-based pivots


@op
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@op
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)
