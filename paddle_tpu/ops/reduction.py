"""Reduction & search ops (reference: phi/kernels/*/reduce_*, arg_min_max, top_k,
kthvalue, mode; the reference's elaborate reduce machinery in
phi/kernels/funcs/reduce_function.h collapses to XLA reduce ops which tile onto
the VPU natively)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._registry import op
from ._common import LONG


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@op
def sum(x, axis=None, dtype=None, keepdim=False):
    return jnp.sum(x, axis=_axis(axis), dtype=dtype, keepdims=keepdim)


@op
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@op
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@op
def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=_axis(axis), dtype=dtype, keepdims=keepdim)


@op
def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_axis(axis), dtype=dtype, keepdims=keepdim)


@op(name="max")
def max_(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@op(name="min")
def min_(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@op
def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@op
def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@op
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@op
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@op
def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=_axis(axis), keepdims=keepdim)


@op
def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=_axis(axis), keepdims=keepdim)


@op
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


@op
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=None if axis is None else int(axis),
                     keepdims=keepdim)
    return out.astype(jax.dtypes.canonicalize_dtype(jnp.dtype(str(dtype))))


@op
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=None if axis is None else int(axis),
                     keepdims=keepdim)
    return out.astype(jax.dtypes.canonicalize_dtype(jnp.dtype(str(dtype))))


@op(name="all")
def all_(x, axis=None, keepdim=False):
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


@op(name="any")
def any_(x, axis=None, keepdim=False):
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


@op
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim).astype(
        LONG)


@op
def topk(x, k, axis=-1, largest=True, sorted=True):
    axis = int(axis)
    if axis != -1 and axis != x.ndim - 1:
        xm = jnp.moveaxis(x, axis, -1)
    else:
        xm = x
    if largest:
        vals, idx = jax.lax.top_k(xm, k)
    else:
        vals, idx = jax.lax.top_k(-xm, k)
        vals = -vals
    if axis != -1 and axis != x.ndim - 1:
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(LONG)


@op
def kthvalue(x, k, axis=-1, keepdim=False):
    s = jnp.sort(x, axis=axis)
    si = jnp.argsort(x, axis=axis)
    vals = jnp.take(s, k - 1, axis=axis)
    idx = jnp.take(si, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(LONG)


@op
def mode(x, axis=-1, keepdim=False):
    # O(n^2) pairwise-count formulation — static shapes, VPU-friendly, and fine
    # for the small trailing dims this op is used with.
    ax = axis if axis >= 0 else x.ndim + axis
    xm = jnp.moveaxis(x, ax, -1)
    counts = jnp.sum(xm[..., :, None] == xm[..., None, :], axis=-1)
    # break count ties toward the largest value (paddle returns the last max)
    order = jnp.argsort(xm, axis=-1)
    xs = jnp.take_along_axis(xm, order, axis=-1)
    cs = jnp.take_along_axis(counts, order, axis=-1)
    best = jnp.argmax(cs + jnp.arange(cs.shape[-1]) * 0, axis=-1,
                      keepdims=True)
    vals = jnp.take_along_axis(xs, best, axis=-1)
    idx = jnp.argmax((xm == vals).astype(jnp.int32)
                     * jnp.arange(1, xm.shape[-1] + 1), axis=-1, keepdims=True)
    vals_out = jnp.moveaxis(vals, -1, ax)
    idx_out = jnp.moveaxis(idx, -1, ax)
    if not keepdim:
        vals_out = jnp.squeeze(vals_out, ax)
        idx_out = jnp.squeeze(idx_out, ax)
    return vals_out, idx_out.astype(LONG)
