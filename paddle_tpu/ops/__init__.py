"""Functional op library.

The single-backend (XLA) replacement for the reference's entire kernel stack
(SURVEY.md §2.1): PHI kernels, kernel registry, InferMeta, YAML codegen, compat
layer. Op semantics follow ``python/paddle/tensor/*`` and
``paddle/phi/api/yaml/ops.yaml``; each op here is one pure JAX function registered
via :mod:`._registry` (eager tape dispatch + jit-traceable).
"""
from __future__ import annotations

from ._registry import OPS, RAW, get_op, op  # noqa: F401

from .math import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .reduction import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .array import (  # noqa: F401
    TensorArray, array_length, array_read, array_write, create_array,
)
from .extras import *  # noqa: F401,F403
from . import paged_attention  # noqa: F401

from . import math as _math
from . import creation as _creation
from . import reduction as _reduction
from . import manipulation as _manipulation
from . import linalg as _linalg

# re-export every registered op at module scope
import sys as _sys
_self = _sys.modules[__name__]
for _name, _fn in OPS.items():
    if not hasattr(_self, _name):
        setattr(_self, _name, _fn)


def monkey_patch_tensor():
    """Attach the op surface as Tensor methods.

    Mirrors the reference's varbase patching
    (python/paddle/fluid/dygraph/varbase_patch_methods.py): the long tail of
    ``Tensor.sum()/reshape()/...`` methods delegates to the functional ops.
    """
    from paddle_tpu.core.tensor import Tensor

    method_names = [
        # math
        "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
        "pow", "maximum", "minimum", "abs", "exp", "log", "log2", "log10",
        "log1p", "sqrt", "rsqrt", "square", "reciprocal", "sign", "floor",
        "ceil", "round", "trunc", "sin", "cos", "tan", "tanh", "sigmoid",
        "erf", "clip", "scale", "cumsum", "cumprod", "isnan", "isinf",
        "isfinite", "equal", "not_equal", "less_than", "less_equal",
        "greater_than", "greater_equal", "logical_and", "logical_or",
        "logical_not", "logical_xor", "bitwise_and", "bitwise_or",
        "bitwise_xor", "bitwise_not", "allclose", "isclose", "equal_all",
        "lerp", "nan_to_num",
        # reduction
        "sum", "mean", "prod", "max", "min", "amax", "amin", "std", "var",
        "median", "logsumexp", "argmax", "argmin", "all", "any", "topk",
        "kthvalue", "mode", "count_nonzero", "nanmean", "nansum", "quantile",
        # manipulation
        "cast", "reshape", "transpose", "concat", "split", "chunk", "squeeze",
        "unsqueeze", "flatten", "tile", "expand", "broadcast_to", "expand_as",
        "flip", "roll", "gather", "gather_nd", "take_along_axis",
        "put_along_axis", "scatter", "scatter_nd_add", "index_select",
        "index_sample", "index_add", "masked_select", "masked_fill", "where",
        "nonzero", "tril", "triu", "pad", "repeat_interleave", "sort",
        "argsort", "unbind", "unique", "diagonal", "diff", "moveaxis",
        "swapaxes", "one_hot", "slice", "strided_slice", "bucketize",
        "searchsorted",
        # linalg
        "matmul", "bmm", "dot", "mv", "norm", "dist", "cross", "cholesky",
        "qr", "svd", "eig", "eigh", "det", "slogdet", "inverse", "pinv",
        "solve", "matrix_power", "t", "histogram", "bincount", "addmm",
        "outer", "inner",
    ]
    for name in method_names:
        fn = OPS.get(name)
        if fn is None:
            continue
        setattr(Tensor, name, fn)

    # aliases matching paddle method names
    Tensor.mm = OPS["matmul"]
    Tensor.mod = OPS["remainder"]
    Tensor.rsub = lambda self, o: OPS["subtract"](o, self)


monkey_patch_tensor()
