"""Shape/layout manipulation ops (reference: phi/kernels/*/concat_kernel,
split, transpose, reshape (zero-copy there, zero-copy here via XLA bitcast),
gather/scatter family, pad, tile/expand; Python surface
python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._registry import op
from ._common import LONG
from paddle_tpu.core.tensor import Tensor


def _ints(v):
    if isinstance(v, (int, np.integer)):
        return int(v)
    return [int(x.item()) if isinstance(x, Tensor) else int(x) for x in v]


def _dims(v):
    """Shape-list coercion that lets SYMBOLIC dims (jax.export shape
    polymorphism) pass through untouched — int() on a _DimExpr raises and
    would pin exported artifacts to static shapes."""
    def one(s):
        if isinstance(s, Tensor):
            return int(s.item())
        try:
            return int(s)
        except Exception:
            return s  # symbolic dim
    if isinstance(v, (int, np.integer)):
        return int(v)
    return [one(s) for s in v]


def _is_concrete(s) -> bool:
    return isinstance(s, (int, np.integer))


@op
def cast(x, dtype):
    from paddle_tpu.core.dtype import convert_dtype
    return x.astype(convert_dtype(dtype).np_dtype)


@op
def assign(x):
    return jnp.array(x, copy=True)


@op
def reshape(x, shape):
    dims = _dims(shape)
    # paddle semantics: a 0 entry copies the input dim at that index
    dims = [x.shape[i] if _is_concrete(s) and s == 0 else s
            for i, s in enumerate(dims)]
    return jnp.reshape(x, dims)


@op
def transpose(x, perm):
    return jnp.transpose(x, [int(p) for p in perm])


@op(name="t")
def t_(x):
    return x.T


@op
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


@op
def swapaxes(x, axis1, axis2):
    return jnp.swapaxes(x, int(axis1), int(axis2))


@op
def concat(xs, axis=0):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return jnp.concatenate(xs, axis=axis)


@op
def stack(xs, axis=0):
    return jnp.stack(xs, axis=int(axis))


@op
def split(x, num_or_sections, axis=0):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    sections = []
    total = x.shape[axis]
    known = builtins_sum(s for s in num_or_sections if s >= 0)
    sizes = [s if s >= 0 else total - known for s in num_or_sections]
    offs = np.cumsum([0] + sizes)
    return tuple(jax.lax.slice_in_dim(x, int(offs[i]), int(offs[i + 1]),
                                      axis=axis)
                 for i in range(len(sizes)))


builtins_sum = sum


@op
def chunk(x, chunks, axis=0):
    return tuple(jnp.array_split(x, int(chunks), axis=int(axis)))


@op
def unbind(x, axis=0):
    axis = int(axis)
    return tuple(jnp.squeeze(s, axis)
                 for s in jnp.split(x, x.shape[axis], axis=axis))


@op
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        ax = tuple(a for a in (int(a) for a in axis) if x.shape[a] == 1)
        return jnp.squeeze(x, ax) if ax else x
    axis = int(axis)
    return jnp.squeeze(x, axis) if x.shape[axis] == 1 else x


@op
def unsqueeze(x, axis):
    if isinstance(axis, (list, tuple)):
        for a in sorted(int(v) for v in axis):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, int(axis))


@op
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    start = start_axis % nd if nd else 0
    stop = stop_axis % nd if nd else 0
    new_shape = (x.shape[:start]
                 + (int(np.prod(x.shape[start:stop + 1]) or 1),)
                 + x.shape[stop + 1:])
    return jnp.reshape(x, new_shape)


@op
def tile(x, repeat_times):
    return jnp.tile(x, _ints(repeat_times))


@op
def expand(x, shape):
    shape = _dims(shape)
    # -1 entries keep the original dim (paddle semantics); the compare
    # only applies to concrete entries (symbolic dims are never -1)
    full = []
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if _is_concrete(s) and s == -1:
            full.append(x.shape[i - offset])
        else:
            full.append(s)
    return jnp.broadcast_to(x, full)


@op
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, _dims(shape))


@op
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@op
def broadcast_tensors(xs):
    return tuple(jnp.broadcast_arrays(*xs))


@op
def flip(x, axis):
    return jnp.flip(x, axis if isinstance(axis, int) else tuple(axis))


@op
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts,
                    axis=axis if axis is None or isinstance(axis, int)
                    else tuple(axis))


@op
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k, axes)


@op
def gather(x, index, axis=0):
    # paddle gather accepts index of shape [N] or [N, 1]
    if hasattr(index, "ndim") and index.ndim == 2 and index.shape[1] == 1:
        index = jnp.reshape(index, (-1,))
    return jnp.take(x, index, axis=int(axis))


@op
def gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@op
def take_along_axis(x, indices, axis, broadcast=True):
    if broadcast:
        shape = list(x.shape)
        shape[axis] = indices.shape[axis]
        indices = jnp.broadcast_to(indices, shape)
    return jnp.take_along_axis(x, indices, axis=int(axis))


@op
def put_along_axis(x, indices, values, axis, reduce="assign"):
    if not hasattr(values, "shape") or values.shape != indices.shape:
        values = jnp.broadcast_to(values, indices.shape)
    axis = int(axis)
    dims = [jnp.arange(s) for s in indices.shape]
    grids = jnp.meshgrid(*dims, indexing="ij")
    grids[axis] = indices
    idx = tuple(grids)
    if reduce == "assign":
        return x.at[idx].set(values)
    if reduce in ("add", "sum"):
        return x.at[idx].add(values)
    if reduce in ("mul", "multiply"):
        return x.at[idx].multiply(values)
    raise ValueError(f"unsupported reduce {reduce!r}")


@op
def scatter(x, index, updates, overwrite=True):
    index = jnp.reshape(index, (-1,))
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@op
def scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@op
def scatter_nd(index, updates, shape):
    zeros = jnp.zeros([int(s) for s in shape], updates.dtype)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return zeros.at[idx].add(updates)


@op
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=int(axis))


@op
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@op
def index_add(x, index, axis, value):
    axis = int(axis)
    xm = jnp.moveaxis(x, axis, 0)
    vm = jnp.moveaxis(value, axis, 0)
    out = xm.at[index].add(vm)
    return jnp.moveaxis(out, 0, axis)


@op
def masked_select(x, mask):
    # dynamic output shape — host-side op; not jittable (documented limitation,
    # same as the reference's masked_select requiring a D2H sync)
    return x[mask]


@op
def masked_fill(x, mask, value):
    return jnp.where(mask, value, x)


@op
def where(condition, x=None, y=None):
    if x is None and y is None:
        return jnp.stack(jnp.nonzero(condition), axis=-1).astype(LONG)
    return jnp.where(condition, x, y)


@op
def nonzero(x, as_tuple=False):
    nz = jnp.nonzero(x)
    if as_tuple:
        return tuple(n.astype(LONG) for n in nz)
    return jnp.stack(nz, axis=-1).astype(LONG)


@op
def tril(x, diagonal=0):
    return jnp.tril(x, int(diagonal))


@op
def triu(x, diagonal=0):
    return jnp.triu(x, int(diagonal))


@op
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW"):
    pad = _ints(pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # paddle F.pad convention: pad applies to last len(pad)//2 spatial dims,
        # ordered from the last dim backwards, honoring data_format
        cfg = [(0, 0)] * nd
        npairs = len(pad) // 2
        # paddle order [left, right, top, bottom, front, back]: the first
        # pair pads the LAST spatial dim, walking backwards
        if data_format.endswith("C"):  # NHWC-like: spatial dims 1..nd-2
            dims = list(range(nd - 2, nd - 2 - npairs, -1))
        else:  # NCHW-like: spatial dims 2..nd-1
            dims = list(range(nd - 1, nd - 1 - npairs, -1))
        for i, d in enumerate(dims):
            cfg[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


@op
def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@op
def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=int(axis))
    if descending:
        out = jnp.flip(out, axis=int(axis))
    return out


@op
def argsort(x, axis=-1, descending=False):
    out = jnp.argsort(x, axis=int(axis))
    if descending:
        out = jnp.flip(out, axis=int(axis))
    return out.astype(LONG)


@op
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    out = jnp.searchsorted(sorted_sequence, values,
                           side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else LONG)


@op
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    out = jnp.searchsorted(sorted_sequence, x,
                           side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else LONG)


@op
def one_hot(x, num_classes):
    return jax.nn.one_hot(x, int(num_classes), dtype=jnp.float32)


@op
def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None):
    # dynamic shape — host-side like the reference's unique kernel
    res = jnp.unique(x, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    return res


@op
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None):
    vals = jnp.asarray(np.unique(np.asarray(x)))
    return vals


@op
def slice(x, axes, starts, ends):
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en in zip(_ints(axes) if not isinstance(axes, int) else [axes],
                          _ints(starts) if not isinstance(starts, int) else [starts],
                          _ints(ends) if not isinstance(ends, int) else [ends]):
        idx[ax] = jnp.s_[st:en]
    return x[tuple(idx)]


@op
def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for ax, st, en, sr in zip(_ints(axes), _ints(starts), _ints(ends),
                              _ints(strides)):
        idx[ax] = jnp.s_[st:en:sr]
    return x[tuple(idx)]


@op
def crop(x, shape, offsets):
    shape = _ints(shape)
    offsets = _ints(offsets)
    return jax.lax.dynamic_slice(x, offsets, shape)


@op
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset, int(axis1), int(axis2))


@op
def diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


@op
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@op
def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


@op
def numel(x):
    return jnp.asarray(np.prod(x.shape) if x.shape else 1, LONG)


@op
def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    size = index_num // nshards
    lo, hi = shard_id * size, (shard_id + 1) * size
    inside = (x >= lo) & (x < hi)
    return jnp.where(inside, x - lo, ignore_value)
