"""Ragged Paged Attention — one Pallas TPU kernel for mixed
prefill+decode serving batches over the block-paged KV pool.

The serving engine's read path before this kernel was the XLA gather
fallback (``ops/paged_attention.py``): materialize every row's ENTIRE
padded paged context (``pool[block_tables]`` →
``[B, max_blocks_per_seq * block_size, n_kv, hd]``) and run dense masked
softmax over it — O(B · L_max) HBM traffic per step regardless of how
much context each row really has, plus a second compiled executable
because no one kernel shape covered both ``[1, prefill_chunk]`` prefill
and ``[max_batch, 1]`` decode. Following the RPA paper (PAPERS.md,
arxiv 2604.15464) this kernel takes the batch **token-packed**:

    q              : [total_tokens, n_heads, hd] — every sequence's new
                     tokens back to back (prefill chunks with S>1 and
                     decode rows with S=1 in the same flat axis)
    k_pool/v_pool  : [num_blocks + 1, block_size, n_kv, hd]
                     (physical block 0 is the reserved null block)
    block_tables   : [max_seqs + 1, max_blocks_per_seq] int32 — row
                     ``max_seqs`` is the all-null sentinel row that
                     padding tokens and dead grid steps resolve through
    cu_seqlens     : [max_seqs + 2] int32 — sequence s's new tokens
                     occupy flat positions [cu[s], cu[s+1])
    context_lens   : [max_seqs + 1] int32 — tokens already cached
                     BEFORE this step's writes, per sequence

and streams each sequence's KV **page by page with only its real
``context_len`` worth of pages** — no ``[B, L_max]`` materialization, no
f32 score tensor in HBM, online softmax in VMEM scratch.

Grid design
-----------
``grid = (n_kv_heads, num_q_tiles, max_steps)``. The flat token axis is
cut into fixed ``tile_q``-token tiles; a tile may span several ragged
sequences, so the inner grid dimension walks a host-built work list
(``build_step_maps``): step ``(j, i)`` names ``(sequence, kv page)`` in
scalar-prefetched int32 maps, and the K/V BlockSpec index maps chase
``block_tables[step_seq[j,i], step_blk[j,i]]`` straight from SMEM — the
pipeline's revolving buffers double-buffer the page DMAs exactly like
the classic paged kernel (boom_attention_tricks.md §9–11), with no
manual descriptors. Rows of the score tile that don't belong to the
step's sequence are masked dead (their online-softmax state is provably
untouched: p = 0 rows with α folded to carry ``m``/``l`` through), so
prefill chunks (in-chunk causal via ``kpos <= ctx + (t - cu[s])``) and
decode rows coexist in one tile. Dead padding steps map to the null
page; consecutive equal indices are not re-fetched, so the padded tail
of a tile's work list costs one null-page DMA, not one per step.

``max_steps`` is static: ``min(tile_q * max_blocks_per_seq,
pool_capacity)`` — at most ``tile_q`` sequences overlap one tile, each
bounded by its table width, and all sequences in a tile together can't
hold more pages than the pool has blocks.

Off-TPU the kernel runs in Pallas interpret mode, which is what tier-1
parity tests exercise on the CPU mesh (`tests/test_ragged_paged_attention.py`).
``tile_q`` registers through ``ops/pallas/autotune.py`` exactly like
``flash_attention.py``'s block sizes.
"""
from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["ragged_paged_attention", "build_step_maps", "rpa_tile_q",
           "rpa_max_steps", "DEFAULT_TILE_Q"]

#: default flat-token tile height; MXU sublane granularity for f32 is 8,
#: so 8 is the no-waste floor for decode-heavy mixes (each decode row
#: contributes group-many score rows on top)
DEFAULT_TILE_Q = 8

_LANES = 128
# finite stand-in for -inf (same trick as flash_attention.py): keeps the
# m/l/alpha arithmetic NaN-free on fully-masked tiles
_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)

#: tile_q candidates for the runtime autotuner (default first: a sweep
#: that ties keeps the hand-picked value)
_TILE_CANDIDATES = (8, 16, 32)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params():
    sem = ("parallel", "parallel", "arbitrary")
    try:
        return pltpu.CompilerParams(dimension_semantics=sem)
    except (AttributeError, TypeError):
        return pltpu.TPUCompilerParams(dimension_semantics=sem)


def rpa_max_steps(tile_q: int, max_blocks_per_seq: int,
                  pool_blocks: int) -> int:
    """Static bound on the per-tile work-list length. A tile of
    ``tile_q`` tokens overlaps at most ``tile_q`` sequences; each streams
    at most ``max_blocks_per_seq`` pages; and all sequences overlapping
    one tile are distinct, so together they can't hold more pages than
    the pool has allocatable blocks."""
    return max(1, min(tile_q * max_blocks_per_seq, pool_blocks))


def build_step_maps(cu_seqlens, kv_lens, *, total_tokens, tile_q,
                    block_size, max_steps, max_seqs):
    """Host-side (numpy) kernel work list for one engine step.

    ``cu_seqlens``: int array ``[num_seqs + 1]`` — prefix sums of the
    LIVE sequences' new-token counts (packed order). ``kv_lens``: int
    array ``[num_seqs]`` — each sequence's total KV length after this
    step's writes (``context_len + new_len``).

    Returns ``(step_seq, step_blk)``, both ``[num_q_tiles, max_steps]``
    int32: for q tile ``j``, the live steps enumerate every
    ``(sequence, kv page)`` pair the tile's tokens attend over — pages
    only up to ``ceil(kv_len / block_size)``, i.e. only the real
    context. Dead steps carry the ``max_seqs`` sentinel (the all-null
    block-table row).
    """
    cu = np.asarray(cu_seqlens, np.int64)
    kv = np.asarray(kv_lens, np.int64)
    num_seqs = len(kv)
    if total_tokens % tile_q:
        raise ValueError(
            f"total_tokens {total_tokens} not a multiple of tile_q "
            f"{tile_q}")
    num_tiles = total_tokens // tile_q
    step_seq = np.full((num_tiles, max_steps), max_seqs, np.int32)
    step_blk = np.zeros((num_tiles, max_steps), np.int32)
    for j in range(num_tiles):
        lo, hi = j * tile_q, (j + 1) * tile_q
        used = 0
        for s in range(num_seqs):
            if cu[s] >= cu[s + 1] or cu[s + 1] <= lo or cu[s] >= hi:
                # no tokens at all (a new_len == 0 padding slot) or none
                # in this tile: contributes no work steps — the static
                # max_steps bound counts only sequences with real tokens
                continue
            n_pages = -(-int(kv[s]) // block_size)
            if used + n_pages > max_steps:
                raise ValueError(
                    f"tile {j} needs {used + n_pages} kv steps > "
                    f"max_steps {max_steps} — the scheduler admitted "
                    f"more pages than the static bound (bug)")
            step_seq[j, used:used + n_pages] = s
            step_blk[j, used:used + n_pages] = np.arange(n_pages)
            used += n_pages
    return step_seq, step_blk


# =========================== kernel ==========================================
def _rpa_kernel(ss_ref, sb_ref, bt_ref, cu_ref, ctx_ref,
                q_ref, k_ref, v_ref, o_ref, m_sc, l_sc, acc_sc,
                *, tile_q, group, block_size, max_steps, max_seqs,
                sm_scale):
    j = pl.program_id(1)
    i = pl.program_id(2)
    rows = tile_q * group

    @pl.when(i == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc[...], -jnp.inf)
        l_sc[...] = jnp.zeros_like(l_sc[...])
        acc_sc[...] = jnp.zeros_like(acc_sc[...])

    ss = ss_ref[j, i]

    @pl.when(ss < max_seqs)
    def _compute():
        sb = sb_ref[j, i]
        q = q_ref[...]                                  # [rows, hd]
        k = k_ref[...]                                  # [bs, hd]
        v = v_ref[...]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        # row r of the tile is (token j*tile_q + r//group, head r%group)
        r = jax.lax.broadcasted_iota(jnp.int32, (rows, block_size), 0)
        tok = j * tile_q + r // group
        start = cu_ref[ss]
        owned = (tok >= start) & (tok < cu_ref[ss + 1])
        qpos = ctx_ref[ss] + tok - start
        kpos = sb * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_size), 1)
        # one bound covers prior context, in-chunk causality, and (with
        # page enumeration stopping at ceil(kv_len/bs)) page raggedness
        visible = owned & (kpos <= qpos)
        s = jnp.maximum(jnp.where(visible, s, _MASK_VALUE), _MASK_VALUE)
        m_prev = m_sc[:, :1]                            # lane-replicated
        l_prev = l_sc[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        # rows with no live key in THIS step (another sequence's rows, or
        # causally-dead decode rows) would contribute exp(MASK-MASK)=1
        # per column; zeroing them keeps their l at 0 so their m/l/acc
        # state rides through untouched (alpha re-scales acc by the same
        # factor l absorbs)
        p = jnp.where(jnp.any(visible, axis=-1, keepdims=True), p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[...] = acc_sc[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(i == max_steps - 1)
    def _finish():
        # rows that saw no live step (padding tokens): exact 0 output
        l = l_sc[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_sc[...] / l_safe).astype(o_ref.dtype)


def _rpa_call(q_heads, k_pool, v_pool, step_seq, step_blk, block_tables,
              cu_seqlens, context_lens, *, tile_q, group, sm_scale):
    """``q_heads`` [n_kv, T*group, hd] (token-major rows per kv head) →
    out in the same layout."""
    n_kv, tg, hd = q_heads.shape
    block_size = k_pool.shape[1]
    max_seqs = block_tables.shape[0] - 1
    num_tiles, max_steps = step_seq.shape
    rows = tile_q * group

    kernel = functools.partial(
        _rpa_kernel, tile_q=tile_q, group=group, block_size=block_size,
        max_steps=max_steps, max_seqs=max_seqs, sm_scale=sm_scale)

    def q_map(h, j, i, ss, sb, bt, cu, ctx):
        return (h, j, 0)

    def kv_map(h, j, i, ss, sb, bt, cu, ctx):
        # scalar-prefetch chase: physical page of this step's (seq, blk).
        # Dead steps resolve through the sentinel table row to the null
        # page 0; consecutive equal indices are not re-fetched, so a
        # padded work-list tail costs one DMA, not one per step.
        return (bt[ss[j, i], sb[j, i]], 0, h, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(n_kv, num_tiles, max_steps),
        in_specs=[
            pl.BlockSpec((None, rows, hd), q_map),
            pl.BlockSpec((None, block_size, None, hd), kv_map),
            pl.BlockSpec((None, block_size, None, hd), kv_map),
        ],
        out_specs=pl.BlockSpec((None, rows, hd), q_map),
        scratch_shapes=[
            pltpu.VMEM((rows, _LANES), jnp.float32),
            pltpu.VMEM((rows, _LANES), jnp.float32),
            pltpu.VMEM((rows, hd), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_kv, tg, hd), q_heads.dtype),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(step_seq, step_blk, block_tables, cu_seqlens, context_lens,
      q_heads, k_pool, v_pool)


def ragged_paged_attention(q, k_pool, v_pool, block_tables, cu_seqlens,
                           context_lens, step_seq, step_blk, *,
                           sm_scale=None):
    """GQA attention for a token-packed ragged batch over paged KV.

    ``q`` [total_tokens, n_heads, hd]; pools
    ``[num_blocks + 1, block_size, n_kv, hd]`` (this step's new K/V
    already scattered in — the kernel is a pure read); metadata as
    documented in the module docstring (``build_step_maps`` produces the
    step maps). Returns ``[total_tokens, n_heads, hd]``. Outputs at
    padding tokens (sentinel ``seq_id``) are exactly 0.
    """
    T, n_heads, hd = q.shape
    n_kv = k_pool.shape[2]
    if n_heads % n_kv:
        raise ValueError(
            f"q heads {n_heads} must be a multiple of kv heads {n_kv}")
    group = n_heads // n_kv
    num_tiles = step_seq.shape[0]
    if num_tiles == 0 or T % num_tiles:
        raise ValueError(
            f"step maps have {num_tiles} tiles for {T} tokens")
    tile_q = T // num_tiles
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(hd)
    # [T, n_heads, hd] -> [n_kv, T*group, hd], rows token-major within a
    # kv head so q tile j covers exactly tokens [j*tile_q, (j+1)*tile_q)
    qh = q.reshape(T, n_kv, group, hd).transpose(1, 0, 2, 3) \
          .reshape(n_kv, T * group, hd)
    out = _rpa_call(
        qh, k_pool, v_pool,
        jnp.asarray(step_seq, jnp.int32), jnp.asarray(step_blk, jnp.int32),
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(cu_seqlens, jnp.int32),
        jnp.asarray(context_lens, jnp.int32),
        tile_q=tile_q, group=group, sm_scale=float(sm_scale))
    return out.reshape(n_kv, T, group, hd).transpose(1, 0, 2, 3) \
              .reshape(T, n_heads, hd)


# =========================== tile autotune ===================================
def rpa_tile_q(budget_tokens, n_heads, n_kv, head_dim, block_size,
               max_blocks_per_seq, pool_blocks, dtype="float32") -> int:
    """The flat-token tile height for an engine at this signature — the
    hand-picked :data:`DEFAULT_TILE_Q`, or (with ``FLAGS_use_autotune``
    on chip) the winner of an on-device sweep over
    ``_TILE_CANDIDATES`` measured once per signature and cached
    (``ops/pallas/autotune.py``, the flash-attention pattern). The
    engine rounds its token budget up to a multiple of the returned
    tile, so any candidate is legal."""
    default = DEFAULT_TILE_Q
    if _interpret():
        return default  # interpret mode: timing a sweep is meaningless
    from paddle_tpu.core.flags import flag
    if not flag("use_autotune"):
        return default
    from .autotune import autotune

    sig = (int(budget_tokens), int(n_heads), int(n_kv), int(head_dim),
           int(block_size), int(max_blocks_per_seq), int(pool_blocks),
           str(dtype))

    def build(tile):
        from .autotune import aot_runner
        T = -(-int(budget_tokens) // tile) * tile
        max_seqs = max(2, min(T, 8))
        max_steps = rpa_max_steps(tile, max_blocks_per_seq, pool_blocks)
        # representative mix: one prefill chunk spanning half the budget
        # plus decode rows for the rest, each with a page of context
        n_dec = min(max_seqs - 1, max(1, T // 2))
        new_lens = [T - n_dec] + [1] * n_dec
        ctx = [0] + [block_size] * n_dec
        cu = np.zeros(max_seqs + 2, np.int32)
        cu[1:len(new_lens) + 1] = np.cumsum(new_lens)
        cu[len(new_lens) + 1:] = cu[len(new_lens)]
        ctx_arr = np.zeros(max_seqs + 1, np.int32)
        ctx_arr[:len(ctx)] = ctx
        kv_lens = [n + c for n, c in zip(new_lens, ctx)]
        bt = np.zeros((max_seqs + 1, max_blocks_per_seq), np.int32)
        nxt = 1
        for s, kv in enumerate(kv_lens):
            n_pages = -(-kv // block_size)
            bt[s, :n_pages] = np.arange(nxt, nxt + n_pages)
            nxt += n_pages
        if nxt - 1 > pool_blocks:
            raise ValueError("synthetic workload exceeds pool")
        ssq, sbk = build_step_maps(
            cu[:len(new_lens) + 1], kv_lens, total_tokens=T,
            tile_q=tile, block_size=block_size, max_steps=max_steps,
            max_seqs=max_seqs)
        with jax.ensure_compile_time_eval():
            dt = jnp.dtype(dtype)
            q0 = jnp.zeros((T, n_heads, head_dim), dt)
            kp = jnp.zeros((pool_blocks + 1, block_size, n_kv, head_dim),
                           dt)
        return aot_runner(
            lambda qa, kpa, vpa: ragged_paged_attention(
                qa, kpa, vpa, bt, cu, ctx_arr, ssq, sbk),
            q0, kp, kp)

    return autotune("ragged_paged_attention", sig, _TILE_CANDIDATES,
                    build, default)
