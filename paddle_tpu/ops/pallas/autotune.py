"""Runtime kernel-config autotuning with a hit-rate-managed cache.

TPU analog of the reference's conv/algo autotuner
(``paddle/phi/kernels/autotune/cache.h`` AutoTuneCache — per-op maps keyed
by a shape/dtype signature, hit/miss accounting;
``auto_tune_base.h`` AutoTuneBase::Run — measure every candidate once,
serve the cached winner after). Here the tunables are the Pallas flash
-attention block sizes and the fused-CE vocab chunk count; candidates are
measured on the REAL chip with synthetic operands at the exact
(shape, dtype, variant) signature, outside any enclosing trace, so a
`TrainStep` trace picks up tuned constants without ever timing tracers.

Off by default (`FLAGS_use_autotune=1` / ``set_flags`` enables); when off,
callers keep their hand-swept defaults. The cache can persist across
processes through ``PADDLE_AUTOTUNE_CACHE`` (a JSON file), mirroring the
reference's serialized autotune status.
"""
from __future__ import annotations

import json
import os
import time
from typing import Callable, Iterable, Optional, Tuple

__all__ = ["AutoTuneCache", "autotune", "aot_runner"]


class AutoTuneCache:
    """Process-wide (op, signature) -> winning-config store."""

    _instance: Optional["AutoTuneCache"] = None

    def __init__(self):
        self._store: dict = {}
        self.hits = 0
        self.misses = 0
        path = os.environ.get("PADDLE_AUTOTUNE_CACHE")
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._store = {
                        tuple(json.loads(k)):
                            tuple(v) if isinstance(v, list) else v
                        for k, v in json.load(f).items()}
            except Exception:
                self._store = {}

    @classmethod
    def instance(cls) -> "AutoTuneCache":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def lookup(self, key: Tuple):
        got = self._store.get(key)
        if got is None:
            self.misses += 1
        else:
            self.hits += 1
        return got

    def put(self, key: Tuple, value):
        self._store[key] = value
        path = os.environ.get("PADDLE_AUTOTUNE_CACHE")
        if path:
            try:
                # merge-then-replace: re-read the file so concurrent
                # processes sharing the cache don't erase each other's
                # winners from stale snapshots (last-writer-wins only per
                # KEY), and write atomically so a reader never sees a
                # torn file (which the loader would silently discard)
                merged = dict(self._store)
                try:
                    with open(path) as f:
                        for k, v in json.load(f).items():
                            merged.setdefault(
                                tuple(json.loads(k)),
                                tuple(v) if isinstance(v, list) else v)
                except Exception:
                    pass
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump({json.dumps(list(k)):
                               list(v) if isinstance(v, (tuple, list))
                               else v
                               for k, v in merged.items()}, f)
                os.replace(tmp, path)
            except Exception:
                pass

    def stats(self) -> dict:
        total = self.hits + self.misses
        return {"size": len(self._store), "hits": self.hits,
                "misses": self.misses,
                "hit_rate": self.hits / total if total else 0.0}

    def clear(self):
        self._store.clear()
        self.hits = self.misses = 0


def aot_runner(fn: Callable, *operands) -> Callable[[], object]:
    """Zero-arg runner executing ``jit(fn)`` on concrete synthetic
    ``operands`` — safe to call while an ENCLOSING trace is active (the
    normal first-use site: inside a TrainStep trace). Two traps this
    sidesteps: array creation inside a trace stages tracers (escaped via
    ``ensure_compile_time_eval`` for the operands), and a nested ``jit``
    call inlines into the outer trace instead of executing (escaped by
    AOT ``lower().compile()`` — running a compiled executable on concrete
    buffers never touches the trace machinery)."""
    import jax
    import jax.numpy as jnp

    with jax.ensure_compile_time_eval():
        concrete = [jnp.asarray(o) for o in operands]
    compiled = jax.jit(fn).lower(*concrete).compile()
    return lambda: compiled(*concrete)


def _measure(fn: Callable[[], object], iters: int = 4) -> float:
    """Seconds per call by slope (two windows — the per-window sync/RTT
    constant cancels; see bench.py's methodology notes)."""
    import numpy as np

    def window(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn()
        np.asarray(jax_leaf(out))
        return time.perf_counter() - t0

    def jax_leaf(o):
        import jax
        return jax.tree_util.tree_leaves(o)[0]

    window(1)  # warm (compile)
    # min over >=2 positive slopes (bench.py's reps-of-min methodology):
    # a single noisy window must not crown a suboptimal candidate, since
    # the winner persists cross-process via PADDLE_AUTOTUNE_CACHE
    slopes = []
    for _ in range(4):
        t1 = window(iters)
        t2 = window(3 * iters)
        slope = (t2 - t1) / (2 * iters)
        if slope > 0:
            slopes.append(slope)
        if len(slopes) >= 2:
            return min(slopes)
    # fewer than two positive slopes in four attempts: the measurement is
    # noise (loaded host) — treat the candidate as failed rather than
    # crowning it on a fluke
    raise RuntimeError("unstable timing (non-positive slope)")


def autotune(op: str, signature: Tuple, candidates: Iterable,
             build_measure: Callable[[object], Callable[[], object]],
             default):
    """Return the best candidate for ``(op, signature)``.

    Cache hit: the stored winner. Miss with tuning DISABLED (the default):
    ``default``, uncached (enabling the flag later still sweeps). Miss with
    ``FLAGS_use_autotune``: measure every candidate —
    ``build_measure(cand)`` returns a zero-arg callable executing the
    kernel at this signature — keep the fastest, cache it. A candidate
    that fails to build/run is skipped (illegal tile shapes lose, not
    crash)."""
    from paddle_tpu.core.flags import flag

    if not flag("use_autotune"):
        # flag off means hand-swept defaults, FULL STOP — a cache file
        # from an earlier tuned run must not silently win an A/B debug
        return default
    cache = AutoTuneCache.instance()
    key = (op,) + tuple(signature)
    got = cache.lookup(key)
    if got is not None:
        return got
    try:
        import jax
        multi_host = jax.process_count() > 1
    except Exception:
        multi_host = False
    if multi_host:
        # independent per-host sweeps would cache DIFFERENT winners on
        # timing noise, and the hosts would then trace divergent SPMD
        # programs that deadlock at the first collective. Multi-host jobs
        # consume a pre-warmed PADDLE_AUTOTUNE_CACHE (tuned single-host)
        # or the defaults — never a local sweep.
        return default
    best, best_t = default, float("inf")
    # builders use aot_runner(), so measurement executes on device even
    # when this sweep fires inside an enclosing trace — the trace only
    # ever sees the chosen constants
    for cand in candidates:
        try:
            fn = build_measure(cand)
            dt = _measure(fn)
        except Exception:
            continue
        if dt < best_t:
            best, best_t = cand, dt
    if best_t == float("inf"):
        # every candidate failed (transient OOM, loaded host): do NOT
        # cache — a later call deserves a real sweep
        return default
    cache.put(key, best)
    return best
