"""Hand-written Pallas TPU kernels — the analog of the reference's fused
kernel zoo (``paddle/phi/kernels/fusion``, ``operators/fused``; SURVEY.md
§2.10 item 6): flash attention now, MoE grouped GEMM and vocab-parallel CE
as they land. Everything else rides XLA fusion by design (SURVEY.md §7)."""
from .flash_attention import flash_attention_bshd  # noqa: F401
from .ragged_paged_attention import ragged_paged_attention  # noqa: F401
