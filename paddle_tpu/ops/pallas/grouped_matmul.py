"""Grouped matrix multiply (MoE expert GEMM) as a Pallas TPU kernel.

The TPU answer to the reference's cutlass grouped GEMM
(``paddle/phi/kernels/fusion/cutlass/moe/moe_kernel.cu``): tokens arrive
SORTED by expert, ``group_sizes[e]`` rows belong to expert ``e``, and one
kernel computes ``out[rows_e] = lhs[rows_e] @ rhs[e]`` for every expert —
compute scales with the ACTUAL token count (plus at most one partial tile
per expert boundary), not with the padded ``E * capacity`` slot count the
einsum formulation pays, and the expert selection happens in the kernel's
index maps (scalar-prefetched metadata) instead of a materialized
one-hot/dispatch tensor.

Design (the megablocks/gmm recipe, grid over row-block x expert tiles):

* metadata — for each row block ``b`` (``bm`` rows) the experts whose row
  ranges intersect it; a tile ``t = (b, e)`` multiplies the block's rows
  masked to ``[offsets[e], offsets[e+1])`` by ``rhs[e]`` and accumulates
  into out-block ``b``. Tiles are ordered block-major so revisits of an
  output block are consecutive (the Pallas accumulation pattern); there
  are at most ``n_blocks + E`` tiles, a static bound.
* the transposed variant ``tgmm`` (``out[e] = lhs[rows_e].T @ g[rows_e]``,
  the d_rhs of autodiff) runs the same tiles EXPERT-major, accumulating
  into out-block ``e``; empty experts get one zeroing tile.
* backward: d_lhs is ``gmm`` with per-expert transposed rhs; d_rhs is
  ``tgmm`` — both exact, wired through ``custom_vjp``.

Off-TPU both kernels run in Pallas interpret mode (tests on the CPU
mesh); on chip, ``bm`` rows x full-width weights double-buffer in VMEM.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["gmm", "tgmm", "gmm_aligned"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params():
    sem = ("arbitrary",)
    try:
        return pltpu.CompilerParams(dimension_semantics=sem)
    except (AttributeError, TypeError):
        return pltpu.TPUCompilerParams(dimension_semantics=sem)


def _metadata(offsets_ext, n_blocks: int, n_groups: int, bm: int,
              expert_major: bool):
    """Static-size tile metadata from the (traced) group offsets.

    ``offsets_ext`` [n_groups + 2]: 0, cumsum(group_sizes), R_pad — the
    last entry closes the sentinel pad group. Returns int32 arrays of
    length ``n_tiles = n_blocks + n_groups + 1``:

      block_ids[t], group_ids[t] — the (row-block, group) pair,
      flags[t] — bit0 valid, bit1 first-visit-of-output-block.

    Invalid (padding) tiles point at the last real tile's output block
    with bit0 clear: the kernel adds nothing and never re-zeroes.
    ``expert_major`` orders tiles (e, b) for tgmm — where every REAL group
    additionally owns at least one tile (empty experts must still zero
    their output block).
    """
    G1 = n_groups + 1          # + sentinel pad group
    starts = offsets_ext[:-1]  # [G1]
    ends = offsets_ext[1:]
    bs = jnp.arange(n_blocks, dtype=jnp.int32) * bm
    inter = (starts[None, :] < bs[:, None] + bm) & \
        (ends[None, :] > bs[:, None])           # [n_blocks, G1]
    if expert_major:
        # the output blocks are the E real groups: exclude sentinel tiles
        # (they would index out[E]); ensure every real group — including
        # EMPTY ones — owns >= 1 tile so its output block gets zeroed
        inter = inter.at[:, n_groups].set(False)
        home = jnp.clip(starts[:n_groups] // bm, 0, n_blocks - 1)
        empty = jax.nn.one_hot(home, n_blocks, dtype=jnp.bool_).T \
            & (starts[:n_groups] == ends[:n_groups])[None, :]
        inter = inter.at[:, :n_groups].set(inter[:, :n_groups] | empty)
        key = jnp.arange(G1, dtype=jnp.int32)[None, :] * n_blocks + \
            jnp.arange(n_blocks, dtype=jnp.int32)[:, None]
    else:
        key = jnp.arange(n_blocks, dtype=jnp.int32)[:, None] * G1 + \
            jnp.arange(G1, dtype=jnp.int32)[None, :]
    n_tiles = min(n_blocks + G1, n_blocks * G1)
    big = n_blocks * G1 + 1
    flat_key = jnp.where(inter, key, big).ravel()
    order = jnp.argsort(flat_key)[:n_tiles]
    valid = jnp.take(inter.ravel(), order)
    taken = jnp.take(key.ravel(), order)
    if expert_major:
        b_of, g_of = taken % n_blocks, taken // n_blocks
    else:
        b_of, g_of = taken // G1, taken % G1
    block_ids = jnp.where(valid, b_of, 0).astype(jnp.int32)
    group_ids = jnp.where(valid, g_of, n_groups).astype(jnp.int32)
    outs = group_ids if expert_major else block_ids
    prev = jnp.concatenate([jnp.full((1,), -1, outs.dtype), outs[:-1]])
    first = valid & (outs != prev)
    # invalid tiles: keep pointing at the LAST valid tile's out block so
    # the revisit chain stays monotone for Pallas
    last_valid_out = outs[jnp.maximum(jnp.sum(valid) - 1, 0)]
    outs = jnp.where(valid, outs, last_valid_out)
    nxt = jnp.concatenate([outs[1:], jnp.full((1,), -1, outs.dtype)])
    nxt_valid = jnp.concatenate([valid[1:],
                                 jnp.zeros((1,), valid.dtype)])
    last = valid & ((outs != nxt) | ~nxt_valid)
    flags = valid.astype(jnp.int32) + 2 * first.astype(jnp.int32) \
        + 4 * last.astype(jnp.int32)
    return block_ids, group_ids, outs.astype(jnp.int32), flags


def _gmm_fwd(lhs, rhs, offsets_ext, bm: int):
    """lhs [R_pad, M] sorted by group; rhs [E, M, H]; offsets_ext [E+2].
    Returns out [R_pad, H] float32."""
    R, M = lhs.shape
    E, _, H = rhs.shape
    n_blocks = R // bm
    block_ids, group_ids, outs, flags = _metadata(
        offsets_ext, n_blocks, E, bm, expert_major=False)
    n_tiles = int(block_ids.shape[0])

    def kernel(offs, bids, gids, oids, flgs, lhs_ref, rhs_ref, out_ref,
               acc_ref):
        t = pl.program_id(0)
        g = gids[t]
        start = offs[jnp.minimum(g, E)]
        end = offs[jnp.minimum(g, E) + 1]
        row0 = bids[t] * bm
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
        live = (flgs[t] % 2 == 1) & (g < E)
        mask = (rows >= start) & (rows < end) & live
        x = jnp.where(mask, lhs_ref[...], 0)
        acc = jax.lax.dot(x, rhs_ref[0],
                          preferred_element_type=jnp.float32)
        first = (flgs[t] // 2) % 2 == 1
        last = flgs[t] >= 4

        # accumulate across the block's tiles in an f32 VMEM scratch;
        # write the (possibly narrower) output dtype ONCE on the block's
        # last tile — halves the out bandwidth vs an f32 out buffer
        @pl.when(first)
        def _():
            acc_ref[...] = acc

        @pl.when(jnp.logical_not(first))
        def _():
            acc_ref[...] += acc

        @pl.when(last)
        def _():
            out_ref[...] = acc_ref[...].astype(out_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((bm, M),
                         lambda t, offs, bids, gids, oids, flgs:
                         (bids[t], 0)),
            pl.BlockSpec((1, M, H),
                         lambda t, offs, bids, gids, oids, flgs:
                         (jnp.minimum(gids[t], E - 1), 0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, H),
                               lambda t, offs, bids, gids, oids, flgs:
                               (oids[t], 0)),
        scratch_shapes=[pltpu.VMEM((bm, H), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, H), lhs.dtype),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(offsets_ext, block_ids, group_ids, outs, flags, lhs, rhs)


def _tgmm_fwd(lhs, g, offsets_ext, E: int, bm: int):
    """d_rhs: out[e] = lhs[rows_e].T @ g[rows_e]. lhs [R_pad, M],
    g [R_pad, H] -> [E, M, H] float32."""
    R, M = lhs.shape
    H = g.shape[1]
    n_blocks = R // bm
    block_ids, group_ids, outs, flags = _metadata(
        offsets_ext, n_blocks, E, bm, expert_major=True)
    n_tiles = int(block_ids.shape[0])

    def kernel(offs, bids, gids, oids, flgs, lhs_ref, g_ref, out_ref,
               acc_ref):
        t = pl.program_id(0)
        gid = gids[t]
        start = offs[jnp.minimum(gid, E)]
        end = offs[jnp.minimum(gid, E) + 1]
        row0 = bids[t] * bm
        rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
        live = (flgs[t] % 2 == 1) & (gid < E)
        mask = (rows >= start) & (rows < end) & live
        x = jnp.where(mask, lhs_ref[...], 0)
        acc = jax.lax.dot_general(
            x, g_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[None]

        first = (flgs[t] // 2) % 2 == 1
        last = flgs[t] >= 4

        @pl.when(first)
        def _():
            acc_ref[...] = acc

        @pl.when(jnp.logical_not(first))
        def _():
            acc_ref[...] += acc

        @pl.when(last)
        def _():
            out_ref[...] = acc_ref[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((bm, M),
                         lambda t, offs, bids, gids, oids, flgs:
                         (bids[t], 0)),
            pl.BlockSpec((bm, H),
                         lambda t, offs, bids, gids, oids, flgs:
                         (bids[t], 0)),
        ],
        out_specs=pl.BlockSpec((1, M, H),
                               lambda t, offs, bids, gids, oids, flgs:
                               (oids[t], 0, 0)),
        scratch_shapes=[pltpu.VMEM((1, M, H), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((E, M, H), jnp.float32),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(offsets_ext, block_ids, group_ids, outs, flags, lhs, g)


def _block_experts(group_sizes, n_blocks, E, bm):
    """Per-row-block expert id for the ALIGNED layout (every group size a
    multiple of ``bm``): block b belongs to the unique group whose range
    contains row b*bm; trailing blocks past the data clamp to E-1 (their
    lhs rows are zero pads -> zero output)."""
    offs = jnp.cumsum(group_sizes.astype(jnp.int32))
    bs = jnp.arange(n_blocks, dtype=jnp.int32) * bm
    be = jnp.searchsorted(offs, bs, side="right").astype(jnp.int32)
    return jnp.minimum(be, E - 1)


def _gmm_aligned_fwd(lhs, rhs, block_experts, bm):
    """Mask-free grouped matmul for the aligned layout: tiles == blocks,
    one expert per block, no accumulation — the hot path (masking a
    [bm, M] tile measured ~2x the whole tile's MXU time)."""
    R, M = lhs.shape
    E, _, H = rhs.shape
    nb = R // bm

    def kernel(be, lhs_ref, rhs_ref, out_ref):
        out_ref[...] = jax.lax.dot(
            lhs_ref[...], rhs_ref[0],
            preferred_element_type=jnp.float32).astype(out_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((bm, M), lambda t, be: (t, 0)),
            pl.BlockSpec((1, M, H), lambda t, be: (be[t], 0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, H), lambda t, be: (t, 0)),
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, H), lhs.dtype),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(block_experts, lhs, rhs)


def _tgmm_aligned_fwd(lhs, g, block_experts, E, bm):
    """Aligned d_rhs: blocks arrive expert-sorted, so out[e] accumulates
    over that expert's consecutive blocks in an f32 scratch. Experts with
    no block keep garbage — the caller zeroes them via (counts > 0)."""
    R, M = lhs.shape
    H = g.shape[1]
    nb = R // bm
    be = block_experts
    prev = jnp.concatenate([jnp.full((1,), -1, be.dtype), be[:-1]])
    nxt = jnp.concatenate([be[1:], jnp.full((1,), -1, be.dtype)])
    flags = ((be != prev).astype(jnp.int32) * 2
             + (be != nxt).astype(jnp.int32) * 4 + 1)

    def kernel(be_ref, flg, lhs_ref, g_ref, out_ref, acc_ref):
        t = pl.program_id(0)
        acc = jax.lax.dot_general(
            lhs_ref[...], g_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)[None]
        first = (flg[t] // 2) % 2 == 1
        last = flg[t] >= 4

        @pl.when(first)
        def _():
            acc_ref[...] = acc

        @pl.when(jnp.logical_not(first))
        def _():
            acc_ref[...] += acc

        @pl.when(last)
        def _():
            out_ref[...] = acc_ref[...]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((bm, M), lambda t, be, flg: (t, 0)),
            pl.BlockSpec((bm, H), lambda t, be, flg: (t, 0)),
        ],
        out_specs=pl.BlockSpec((1, M, H),
                               lambda t, be, flg: (be[t], 0, 0)),
        scratch_shapes=[pltpu.VMEM((1, M, H), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((E, M, H), jnp.float32),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(be, flags, lhs, g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def gmm_aligned(lhs, rhs, group_sizes, bm: int = 512):
    """Grouped matmul over the bm-ALIGNED sorted layout: every
    ``group_sizes[e]`` is a multiple of ``bm`` (pad each group's rows up
    and zero the pad rows). No tile ever straddles a group boundary, so
    the kernel runs mask-free at dense-matmul throughput — the layout the
    MoE dispatcher produces. Returns [R, H] in lhs.dtype."""
    out, _ = _gmm_aligned_vjp_fwd(lhs, rhs, group_sizes, bm)
    return out


def _gmm_aligned_vjp_fwd(lhs, rhs, group_sizes, bm):
    R = lhs.shape[0]
    if R % bm:
        raise ValueError(f"gmm_aligned rows {R} must divide bm {bm}")
    E = rhs.shape[0]
    be = _block_experts(group_sizes, R // bm, E, bm)
    out = _gmm_aligned_fwd(lhs, rhs, be, bm)
    return out, (lhs, rhs, group_sizes, be)


def _gmm_aligned_vjp_bwd(bm, res, g):
    lhs, rhs, group_sizes, be = res
    E = rhs.shape[0]
    d_lhs = _gmm_aligned_fwd(g, jnp.swapaxes(rhs, 1, 2), be, bm)
    d_rhs = _tgmm_aligned_fwd(lhs, g, be, E, bm)
    # experts with zero blocks never wrote their slab: replace the
    # garbage (where, not multiply — uninitialized memory can be NaN)
    live = (group_sizes > 0)[:, None, None]
    d_rhs = jnp.where(live, d_rhs, 0)
    return (d_lhs.astype(lhs.dtype), d_rhs.astype(rhs.dtype),
            np.zeros(group_sizes.shape, jax.dtypes.float0))


gmm_aligned.defvjp(_gmm_aligned_vjp_fwd, _gmm_aligned_vjp_bwd)


def _offsets_ext(group_sizes, R_pad):
    off = jnp.concatenate([
        jnp.zeros((1,), jnp.int32),
        jnp.cumsum(group_sizes.astype(jnp.int32))])
    return jnp.concatenate([off, jnp.full((1,), R_pad, jnp.int32)])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def gmm(lhs, rhs, group_sizes, bm: int = 512):
    """Grouped matmul: ``out[rows_of_group_e] = lhs[rows] @ rhs[e]``.

    ``lhs`` [R, M] with rows SORTED by group (rows past
    ``sum(group_sizes)`` are padding and produce zeros); ``rhs``
    [E, M, H]; ``group_sizes`` [E] int. R must divide by ``bm``.
    Returns [R, H] in lhs.dtype (accumulation is f32 in VMEM scratch).
    Differentiable in lhs/rhs (group_sizes takes a zero cotangent)."""
    out, _ = _gmm_vjp_fwd(lhs, rhs, group_sizes, bm)
    return out


def _gmm_vjp_fwd(lhs, rhs, group_sizes, bm):
    R = lhs.shape[0]
    if R % bm:
        raise ValueError(f"gmm rows {R} must divide block size {bm}")
    offs = _offsets_ext(group_sizes, R)
    out = _gmm_fwd(lhs, rhs, offs, bm)
    return out, (lhs, rhs, group_sizes, offs)


def _gmm_vjp_bwd(bm, res, g):
    lhs, rhs, group_sizes, offs = res
    g = g.astype(jnp.float32)
    # d_lhs rows of group e = g rows @ rhs[e].T  -> gmm with swapped rhs
    d_lhs = _gmm_fwd(g, jnp.swapaxes(rhs, 1, 2), offs, bm)
    d_rhs = _tgmm_fwd(lhs.astype(jnp.float32), g, offs, rhs.shape[0], bm)
    return (d_lhs.astype(lhs.dtype), d_rhs.astype(rhs.dtype),
            np.zeros(group_sizes.shape, jax.dtypes.float0))


gmm.defvjp(_gmm_vjp_fwd, _gmm_vjp_bwd)


def tgmm(lhs, g, group_sizes, n_groups: int, bm: int = 512):
    """Transposed grouped matmul: ``out[e] = lhs[rows_e].T @ g[rows_e]``
    (exposed for tests; gmm's backward uses it internally)."""
    R = lhs.shape[0]
    if R % bm:
        raise ValueError(f"tgmm rows {R} must divide block size {bm}")
    offs = _offsets_ext(group_sizes, R)
    return _tgmm_fwd(lhs.astype(jnp.float32), g.astype(jnp.float32),
                     offs, n_groups, bm)
