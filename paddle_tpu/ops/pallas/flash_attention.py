"""Flash attention as Pallas TPU kernels.

Capability parity with the reference's FlashAttention integration
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu`` wrapping the external CUDA
lib): O(S) memory attention with online softmax, plus the standard
recompute-based flash backward (dq and dk/dv kernels), wired into the tape
via ``jax.custom_vjp``.

Kernel shape: inputs are flattened to [BH, S, D]; every kernel walks a
(batch*heads, outer blocks, inner blocks) grid with the inner dimension
marked "arbitrary" so K/V (or Q) blocks stream HBM→VMEM with double
buffering — VMEM holds only a handful of blocks regardless of sequence
length (seq 16K+ runs in the same footprint as 1K). Softmax statistics are
carried across inner steps in fp32 VMEM scratch, lane-replicated to honor
the (8, 128) tile rule. Causal blocks above the diagonal are skipped with
``pl.when`` predication.

Off-TPU the kernels run in Pallas interpret mode so the numerics are
testable on the CPU mesh (the reference cannot test its CUDA kernel without
a GPU; SURVEY.md §4 calls out this improvement).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_bshd", "flash_attention_bhsd"]

_DEF_BLOCK_Q = 512
_DEF_BLOCK_K = 512
_LANES = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _compiler_params():
    sem = ("parallel", "parallel", "arbitrary")
    try:
        return pltpu.CompilerParams(dimension_semantics=sem)
    except (AttributeError, TypeError):
        return pltpu.TPUCompilerParams(dimension_semantics=sem)


def _causal_mask(s, j, i, block_q, block_k):
    qi = j * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    ki = i * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(qi >= ki, s, -jnp.inf)


# =========================== forward =========================================
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc, *,
                sm_scale, causal, block_q, block_k, nk):
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc[...], -jnp.inf)
        l_sc[...] = jnp.zeros_like(l_sc[...])
        acc_sc[...] = jnp.zeros_like(acc_sc[...])

    live = (i * block_k < (j + 1) * block_q) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale  # [bq, bk] f32
        if causal:
            s = _causal_mask(s, j, i, block_q, block_k)
        m_prev = m_sc[:, :1]  # [bq, 1] (lane-replicated storage)
        l_prev = l_sc[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_sc[...] = acc_sc[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(i == nk - 1)
    def _finish():
        l = l_sc[:, :1]
        o_ref[...] = (acc_sc[...] / l).astype(o_ref.dtype)
        lse_ref[0, :] = m_sc[:, 0] + jnp.log(l_sc[:, 0])


def _fwd(q, k, v, causal, sm_scale, block_q, block_k):
    bh, seq, d = q.shape
    nq, nk = seq // block_q, seq // block_k
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_q=block_q, block_k=block_k, nk=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, j, i: (b, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# =========================== backward ========================================
def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_sc, *, sm_scale, causal, block_q, block_k, nk):
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc[...])

    live = (i * block_k < (j + 1) * block_q) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[0, :]
        delta = delta_ref[0, :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        if causal:
            s = _causal_mask(s, j, i, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(k.dtype)
        dq_sc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == nk - 1)
    def _finish():
        dq_ref[...] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_sc, dv_sc, *, sm_scale, causal, block_q, block_k,
                nq):
    i = pl.program_id(1)  # k block
    j = pl.program_id(2)  # q block

    @pl.when(j == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc[...])
        dv_sc[...] = jnp.zeros_like(dv_sc[...])

    live = ((j + 1) * block_q > i * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[0, :]
        delta = delta_ref[0, :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s = s * sm_scale
        if causal:
            s = _causal_mask(s, j, i, block_q, block_k)
        p = jnp.exp(s - lse[:, None])  # [bq, bk] f32
        dv_sc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(q.dtype)
        dk_sc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == nq - 1)
    def _finish():
        dk_ref[...] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_sc[...].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, causal, sm_scale, block_q, block_k):
    bh, seq, d = q.shape
    nq, nk = seq // block_q, seq // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [bh, 1, seq]

    dq_kernel = functools.partial(_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block_q=block_q,
                                  block_k=block_k, nk=nk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, j, i: (b, 0, j)),
            pl.BlockSpec((None, 1, block_q), lambda b, j, i: (b, 0, j)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda b, j, i: (b, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dkv_kernel = functools.partial(_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block_q=block_q,
                                   block_k=block_k, nq=nq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((None, 1, block_q), lambda b, i, j: (b, 0, j)),
            pl.BlockSpec((None, 1, block_q), lambda b, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# =========================== custom-vjp wrapper ==============================
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, sm_scale, block_q, block_k):
    o, _ = _fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    o, lse = _fwd(q, k, v, causal, sm_scale, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, causal, sm_scale, block_q,
                      block_k)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)

# module-level jit so EAGER calls hit the compile cache: without this,
# every eager flash_attention re-traces and re-compiles the pallas_call
# (~1s/call on chip vs ~1ms steady-state — measured). Under an outer
# jit/TrainStep trace this inlines and changes nothing.
_flash_cached = functools.partial(jax.jit, static_argnums=(3, 4, 5, 6))(
    _flash)


def flash_attention_bhsd(q, k, v, causal=False, sm_scale=None,
                         block_q=_DEF_BLOCK_Q, block_k=_DEF_BLOCK_K):
    """Flash attention on arrays in [B, H, S, D] (or [BH, S, D]) layout."""
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            f"flash attention requires matching q/k/v shapes, got "
            f"{q.shape}/{k.shape}/{v.shape}; cross-attention with a "
            "different key length is not supported by this kernel yet")
    squeeze = False
    if q.ndim == 4:
        b, h, s, d = q.shape
        q = q.reshape(b * h, s, d)
        k = k.reshape(b * h, s, d)
        v = v.reshape(b * h, s, d)
        squeeze = (b, h)
    bh, s, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if not _interpret() and block_q % _LANES and block_q != s:
        # the lse output block (1, block_q) must satisfy the TPU tile rule:
        # last dim a multiple of 128 or equal to the array dim — pick the
        # largest lane-multiple that still divides the sequence
        cands = [b for b in range(_LANES, min(block_q, s) + 1, _LANES)
                 if s % b == 0]
        if cands:
            block_q = cands[-1]  # largest lane-multiple <= requested
        else:
            # requested block too small to tile: smallest valid block above
            # it, falling back to the whole sequence (always a legal tile)
            bigger = [b for b in range(_LANES, s, _LANES) if s % b == 0]
            block_q = bigger[0] if bigger else s
    if s % block_q or s % block_k:
        raise ValueError(
            f"flash attention requires seq {s} divisible by block sizes "
            f"({block_q}, {block_k}); pad the sequence")
    out = _flash_cached(q, k, v, causal, float(sm_scale), block_q, block_k)
    if squeeze:
        b, h = squeeze
        out = out.reshape(b, h, s, d)
    return out


def flash_attention_bshd(query, key, value, causal=False, sm_scale=None,
                         block_q=_DEF_BLOCK_Q, block_k=_DEF_BLOCK_K):
    """Flash attention with paddle's [batch, seq, heads, head_dim] layout,
    Tensor-in/Tensor-out, recorded on the autograd tape."""
    from paddle_tpu.core.autograd import apply_op

    def f(q, k, v):
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        o = flash_attention_bhsd(qt, kt, vt, causal=causal,
                                 sm_scale=sm_scale, block_q=block_q,
                                 block_k=block_k)
        return jnp.swapaxes(o, 1, 2)
    return apply_op(f, query, key, value, op_name="flash_attention")
