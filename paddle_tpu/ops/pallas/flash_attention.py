"""Flash attention as Pallas TPU kernels.

Capability parity with the reference's FlashAttention integration
(``paddle/phi/kernels/gpu/flash_attn_kernel.cu`` — ``FlashAttnKernel`` and
``FlashAttnUnpaddedKernel`` wrapping the external CUDA lib, plus
``paddle/fluid/operators/fused/fused_attention_op.cc`` which takes arbitrary
additive masks): O(S) memory attention with online softmax and the standard
recompute-based flash backward (dq and dk/dv kernels), wired into the tape
via ``jax.custom_vjp``.

Supported generality (all combinations compose):
  * causal masking with a key/query length offset (chunked prefill, decode);
  * cross attention: ``kv_len != q_len``;
  * native GQA/MQA: ``num_kv_heads < num_q_heads`` served by grid index maps
    — each query head streams its shared KV head straight from HBM, no
    KV replication materialized (the reference replicates KV for its
    non-flash path);
  * segment ids (the TPU-idiomatic form of the reference's
    varlen/unpadded seam): per-token integer ids for q and kv; tokens
    attend only within equal ids. Padding masks are segment ids with a
    sentinel. Fully-masked *tiles* are skipped dynamically — padding-heavy
    batches don't pay for dead FLOPs. Fully-masked rows produce 0 output
    and 0 gradient (exactly, via the l==0 guard).
  * arbitrary additive bias/mask, streamed tile-by-tile from HBM
    ([B|1, H|1, Sq, Sk] broadcasting): O(S) VMEM still holds, and the
    backward is the fused flash backward. Bias is treated as a constant
    (zero gradient) — it serves attention *masks*, which never train.
  * post-softmax dropout, in-kernel: a murmur-style position hash of
    (head, q_pos, k_pos, seed) generates the keep mask — pure integer
    jnp ops (works in interpret mode, unlike pltpu.prng) and identical
    by construction across the forward and both backward kernels
    whatever their grid layouts. ``l`` keeps the raw softmax
    denominator; only value contributions drop (standard semantics).

Kernel shape: q flattens to [B*Hq, Sq, D], kv to [B*Hkv, Sk, D]; every
kernel walks a (flat heads, outer blocks, inner blocks) grid with the inner
dimension marked "arbitrary" so K/V (or Q) blocks stream HBM→VMEM with
double buffering. Softmax statistics are carried across inner steps in fp32
VMEM scratch, lane-replicated to honor the (8, 128) tile rule. Causal tiles
above the diagonal are skipped with static ``pl.when`` predication;
segment-dead tiles with dynamic predication.

Off-TPU the kernels run in Pallas interpret mode so the numerics are
testable on the CPU mesh (the reference cannot test its CUDA kernel without
a GPU; SURVEY.md §4 calls out this improvement).
"""
from __future__ import annotations

import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_bshd", "flash_attention_bhsd"]

_DEF_BLOCK_Q = 1024  # swept on v5e: 1024/1024 beats 512/512 by ~16% fwd+bwd
_DEF_BLOCK_K = 1024
_BIAS_BLOCK = 512    # bias tiles are f32 [bq, bk]: cap so VMEM double-buffers
_LANES = 128
# refuse block sizes that can't double-buffer in ~16MB VMEM; callers fall
# back to the composite instead of paying a doomed Mosaic compile (hit by
# odd kv lengths — e.g. decode at long context — that force block == seq)
_MAX_BLOCK = 2048
# finite stand-in for -inf (the official TPU flash kernels use the same
# trick): keeps m/l/alpha arithmetic NaN-free when a tile is fully masked
_MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# candidate (block_q, block_k) pairs for the runtime autotuner; the
# hand-swept default stays first so a sweep that ties keeps it
_BLOCK_CANDIDATES = [(1024, 1024), (512, 512), (512, 1024), (1024, 512),
                     (2048, 1024), (256, 1024), (1024, 256)]


def _auto_blocks(b, sq, sk, d, hq, hkv, dtype, causal, bias_kind, has_seg,
                 has_drop):
    """(block_q, block_k) for this call signature: the hand-swept default,
    or — with ``FLAGS_use_autotune`` — the winner of an on-chip sweep over
    ``_BLOCK_CANDIDATES``, measured once per signature with synthetic
    operands (fwd+bwd, the full kernel trio) and cached (the reference's
    ``AutoTuneBase::Run`` + ``AutoTuneCache`` shape, phi/kernels/autotune).

    ``bias_kind``: None | "row" (a [.., 1, Sk] key-padding mask — streams
    uncapped) | "full" (full-tile bias — block sizes get the _BIAS_BLOCK
    cap). The two kinds tile differently, so they are distinct signatures
    and the synthetic bias reproduces the caller's kind; candidates are
    deduped AFTER clamping so a short sequence never times the same
    effective tiling twice.
    """
    default = (_DEF_BLOCK_Q, _DEF_BLOCK_K)
    if _interpret():
        return default  # interpret mode: timing a sweep is meaningless
    from paddle_tpu.core.flags import flag
    if not flag("use_autotune"):
        # fast exit BEFORE any candidate bookkeeping: the default path
        # (eager dispatch included) must not pay for a disabled feature
        return default
    from .autotune import autotune

    sig = (b, sq, sk, d, hq, hkv, dtype, causal, bias_kind, has_seg,
           has_drop)

    def effective(cand):
        bq, bk = cand
        if bias_kind == "full":
            bq, bk = min(bq, _BIAS_BLOCK), min(bk, _BIAS_BLOCK)
        return (_pick_block(bq, sq), _pick_block(bk, sk))

    seen, cands = set(), []
    for cand in _BLOCK_CANDIDATES:
        eff = effective(cand)
        if sq % eff[0] or sk % eff[1] or eff in seen:
            continue
        if eff[0] > _MAX_BLOCK or eff[1] > _MAX_BLOCK:
            # the shape forces seq-sized tiles beyond VMEM — let the
            # normal path raise its cheap early error instead of paying
            # (and re-paying: failures are uncached) doomed Mosaic
            # compiles in the sweep
            continue
        seen.add(eff)
        cands.append(eff)

    def build(cand):
        from .autotune import aot_runner
        bq, bk = cand
        # operands created CONCRETE even under an enclosing trace
        # (ensure_compile_time_eval), committed to device once by the
        # aot_runner
        with jax.ensure_compile_time_eval():
            dt = jnp.dtype(dtype)
            q0 = jnp.zeros((b, hq, sq, d), dt)
            k0 = jnp.zeros((b, hkv, sk, d), dt)
            v0 = jnp.zeros((b, hkv, sk, d), dt)
            kw = dict(causal=causal, block_q=bq, block_k=bk)
            if bias_kind == "row":
                kw["bias"] = jnp.zeros((1, 1, 1, sk), jnp.float32)
            elif bias_kind == "full":
                kw["bias"] = jnp.zeros((1, 1, sq, sk), jnp.float32)
            if has_seg:
                kw["q_segment_ids"] = jnp.zeros((b, sq), jnp.int32)
                kw["kv_segment_ids"] = jnp.zeros((b, sk), jnp.int32)
            if has_drop:
                kw["dropout_p"] = 0.1
                kw["dropout_seed"] = jnp.zeros((1,), jnp.int32)

        return aot_runner(jax.value_and_grad(
            lambda qa, ka, va: flash_attention_bhsd(
                qa, ka, va, **kw).astype(jnp.float32).sum(),
            argnums=(0, 1, 2)), q0, k0, v0)

    return autotune("flash_attention", sig, cands, build, default)


def _compiler_params():
    sem = ("parallel", "parallel", "arbitrary")
    try:
        return pltpu.CompilerParams(dimension_semantics=sem)
    except (AttributeError, TypeError):
        return pltpu.TPUCompilerParams(dimension_semantics=sem)


def _masked_scores(q, k, bias_ref, seg, j, i, *, sm_scale, causal, offset,
                   block_q, block_k):
    """Scaled q·kᵀ for one tile with causal/segment/bias masking applied,
    clamped finite. Shared verbatim by forward and both backward kernels so
    the recomputed probabilities match the forward bit-for-bit."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    s = s * sm_scale
    if bias_ref is not None:
        s = s + bias_ref[...].astype(jnp.float32)
    if seg is not None:
        s = jnp.where(seg, s, _MASK_VALUE)
    if causal:
        qi = j * block_q + offset + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        ki = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(qi >= ki, s, _MASK_VALUE)
    return jnp.maximum(s, _MASK_VALUE)


def _threshold(dropout_p: float) -> int:
    """uint32 drop threshold: bits below it drop (P = dropout_p)."""
    return min(int(dropout_p * 2**32), 2**32 - 1)


def _dropout_keep(seed_ref, bh, j, i, *, block_q, block_k, threshold):
    """Deterministic keep-mask for one tile from GLOBAL (head, q, k)
    positions — murmur3-style integer hash, pure jnp ops (portable to
    interpret mode, identical in forward and both backward kernels
    regardless of their different grid layouts)."""
    qi = j * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    ki = i * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    # fold q and k positions separately — a qi*sk+ki linearization would
    # alias rows once sq*sk exceeds 2^32 at extreme context lengths
    x = qi.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
    x = x ^ (ki.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35))
    x = x ^ (bh.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
    x = x ^ seed_ref[0].astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x >= jnp.uint32(threshold)


def _qflat(b, t, *, hq, hkv, group, nq):
    """Flat (batch, Q head) index for the dkv grid's (b over B*Hkv, t over
    group*nq) coordinates. The dropout mask AND the q/do/lse BlockSpecs
    must use this SAME mapping — one definition, used by both."""
    return (b // hkv) * hq + (b % hkv) * group + t // nq


def _causal_live(j, i, *, offset, block_q, block_k):
    """Static tile-liveness: any (q row, k col) in tile satisfies
    q_abs >= k_abs, where q_abs = q + offset (offset = Sk - Sq)."""
    return i * block_k < (j + 1) * block_q + offset


def _segments(qseg_ref, kvseg_ref):
    if qseg_ref is None:
        return None
    qs = qseg_ref[0, :]   # [block_q] (stored lane-tiled as [1, block_q])
    ks = kvseg_ref[0, :]  # [block_k]
    return qs[:, None] == ks[None, :]


# =========================== forward =========================================
def _fwd_kernel(*refs, sm_scale, causal, offset, block_q, block_k, nk,
                has_bias, has_seg, dropout_p, sk, threshold):
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    qseg_ref = next(it) if has_seg else None
    kvseg_ref = next(it) if has_seg else None
    seed_ref = next(it) if dropout_p > 0 else None
    o_ref, lse_ref = next(it), next(it)
    m_sc, l_sc, acc_sc = next(it), next(it), next(it)

    bh = pl.program_id(0)
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc[...], -jnp.inf)
        l_sc[...] = jnp.zeros_like(l_sc[...])
        acc_sc[...] = jnp.zeros_like(acc_sc[...])

    live = _causal_live(j, i, offset=offset, block_q=block_q,
                        block_k=block_k) if causal else True

    def _compute(seg):
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        s = _masked_scores(q, k, bias_ref, seg, j, i, sm_scale=sm_scale,
                           causal=causal, offset=offset, block_q=block_q,
                           block_k=block_k)
        m_prev = m_sc[:, :1]  # [bq, 1] (lane-replicated storage)
        l_prev = l_sc[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        if seg is not None:
            # rows with no live key in THIS tile would otherwise contribute
            # p = exp(MASK - MASK) = 1 per column; zeroing them keeps l == 0
            # for fully-masked rows so the finish-guard emits exact 0
            p = jnp.where(jnp.any(seg, axis=-1, keepdims=True), p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        p_acc = p
        if dropout_p > 0:
            # l keeps the RAW softmax denominator; only the value
            # contributions drop (standard post-softmax dropout)
            keep = _dropout_keep(seed_ref, bh, j, i, block_q=block_q,
                                 block_k=block_k, threshold=threshold)
            p_acc = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_p))
        acc_sc[...] = acc_sc[...] * alpha + jax.lax.dot_general(
            p_acc.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(live)
    def _outer():
        if has_seg:
            seg = _segments(qseg_ref, kvseg_ref)

            @pl.when(jnp.any(seg))
            def _inner():
                _compute(seg)
        else:
            _compute(None)

    @pl.when(i == nk - 1)
    def _finish():
        l = l_sc[:, :1]
        # rows that saw no live tile (fully-masked padding rows): exact 0
        # output and a sentinel lse of 0 so the backward's
        # p = exp(MASK - lse) underflows to 0 — zero grads, no NaN
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_sc[...] / l_safe).astype(o_ref.dtype)
        lse = jnp.where(l_sc[:, 0] == 0.0, 0.0,
                        m_sc[:, 0] + jnp.log(l_safe[:, 0]))
        lse_ref[0, :] = lse


def _build_specs(block_q, block_k, d, hq, hkv, bias_bh):
    """Input block specs for the (bhq, nq, nk) grids (forward and dq); the
    dkv kernel's (bhkv, nk, group*nq) grid builds its own maps in _bwd."""
    group = hq // hkv

    def kv_of(b):
        return (b // hq) * hkv + (b % hq) // group

    def batch_of(b):
        return b // hq

    specs = {
        "q": pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, j, 0)),
        "kv": pl.BlockSpec((None, block_k, d),
                           lambda b, j, i: (kv_of(b), i, 0)),
        "row_q": pl.BlockSpec((None, 1, block_q),
                              lambda b, j, i: (b, 0, j)),
        "qseg": pl.BlockSpec((None, 1, block_q),
                             lambda b, j, i: (batch_of(b), 0, j)),
        "kvseg": pl.BlockSpec((None, 1, block_k),
                              lambda b, j, i: (batch_of(b), 0, i)),
    }
    if bias_bh is not None:
        bb_n, hb_n, row_bcast = bias_bh

        def bias_of(b):
            bb = (b // hq) if bb_n > 1 else 0
            hh = (b % hq) if hb_n > 1 else 0
            return bb * hb_n + hh
        if row_bcast:  # [.., 1, Sk] key-padding mask: one row per tile
            specs["bias"] = pl.BlockSpec((None, 1, block_k),
                                         lambda b, j, i: (bias_of(b), 0, i))
        else:
            specs["bias"] = pl.BlockSpec(
                (None, block_q, block_k),
                lambda b, j, i: (bias_of(b), j, i))
    return specs


def _fwd(q, k, v, bias, q_seg, kv_seg, seed, causal, sm_scale, block_q,
         block_k, hq, hkv, bias_bh, dropout_p):
    bhq, sq, d = q.shape
    _, sk, _ = k.shape
    nq, nk = sq // block_q, sk // block_k
    offset = sk - sq
    has_bias = bias is not None
    has_seg = q_seg is not None
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, offset=offset,
        block_q=block_q, block_k=block_k, nk=nk, has_bias=has_bias,
        has_seg=has_seg, dropout_p=dropout_p, sk=sk,
        threshold=_threshold(dropout_p))
    sp = _build_specs(block_q, block_k, d, hq, hkv, bias_bh)
    in_specs = [sp["q"], sp["kv"], sp["kv"]]
    inputs = [q, k, v]
    if has_bias:
        in_specs.append(sp["bias"])
        inputs.append(bias)
    if has_seg:
        in_specs += [sp["qseg"], sp["kvseg"]]
        inputs += [q_seg, kv_seg]
    if dropout_p > 0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        inputs.append(seed)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bhq, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, j, i: (b, j, 0)),
            sp["row_q"],
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bhq, 1, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*inputs)
    return o, lse


# =========================== backward ========================================
def _dq_kernel(*refs, sm_scale, causal, offset, block_q, block_k, nk,
               has_bias, has_seg, dropout_p, sk, threshold):
    it = iter(refs)
    q_ref, k_ref, v_ref, do_ref = next(it), next(it), next(it), next(it)
    lse_ref, delta_ref = next(it), next(it)
    bias_ref = next(it) if has_bias else None
    qseg_ref = next(it) if has_seg else None
    kvseg_ref = next(it) if has_seg else None
    seed_ref = next(it) if dropout_p > 0 else None
    dq_ref = next(it)
    dq_sc = next(it)

    bh = pl.program_id(0)
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc[...])

    live = _causal_live(j, i, offset=offset, block_q=block_q,
                        block_k=block_k) if causal else True

    def _compute(seg):
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[0, :]
        delta = delta_ref[0, :]
        s = _masked_scores(q, k, bias_ref, seg, j, i, sm_scale=sm_scale,
                           causal=causal, offset=offset, block_q=block_q,
                           block_k=block_k)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0:
            keep = _dropout_keep(seed_ref, bh, j, i, block_q=block_q,
                                 block_k=block_k, threshold=threshold)
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - dropout_p))
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(k.dtype)
        dq_sc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(live)
    def _outer():
        if has_seg:
            seg = _segments(qseg_ref, kvseg_ref)

            @pl.when(jnp.any(seg))
            def _inner():
                _compute(seg)
        else:
            _compute(None)

    @pl.when(i == nk - 1)
    def _finish():
        dq_ref[...] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(*refs, sm_scale, causal, offset, block_q, block_k, nq,
                group, has_bias, has_seg, dropout_p, sk, threshold, hq,
                hkv):
    it = iter(refs)
    q_ref, k_ref, v_ref, do_ref = next(it), next(it), next(it), next(it)
    lse_ref, delta_ref = next(it), next(it)
    bias_ref = next(it) if has_bias else None
    qseg_ref = next(it) if has_seg else None
    kvseg_ref = next(it) if has_seg else None
    seed_ref = next(it) if dropout_p > 0 else None
    dk_ref, dv_ref = next(it), next(it)
    dk_sc, dv_sc = next(it), next(it)

    b = pl.program_id(0)   # flat (batch, kv head)
    i = pl.program_id(1)   # k block
    t = pl.program_id(2)   # fused (query head in group, q block)
    j = t % nq
    gnq = group * nq
    # flat (batch, Q head) index — the dropout mask is defined per q-head
    bh_q = _qflat(b, t, hq=hq, hkv=hkv, group=group, nq=nq)

    @pl.when(t == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc[...])
        dv_sc[...] = jnp.zeros_like(dv_sc[...])

    live = _causal_live(j, i, offset=offset, block_q=block_q,
                        block_k=block_k) if causal else True

    def _compute(seg):
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[0, :]
        delta = delta_ref[0, :]
        s = _masked_scores(q, k, bias_ref, seg, j, i, sm_scale=sm_scale,
                           causal=causal, offset=offset, block_q=block_q,
                           block_k=block_k)
        p = jnp.exp(s - lse[:, None])  # [bq, bk] f32
        p_v = p
        if dropout_p > 0:
            keep = _dropout_keep(seed_ref, bh_q, j, i, block_q=block_q,
                                 block_k=block_k, threshold=threshold)
            p_v = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_p))
        dv_sc[...] += jax.lax.dot_general(
            p_v.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_p > 0:
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - dropout_p))
        ds = (p * (dp - delta[:, None]) * sm_scale).astype(q.dtype)
        dk_sc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(live)
    def _outer():
        if has_seg:
            seg = _segments(qseg_ref, kvseg_ref)

            @pl.when(jnp.any(seg))
            def _inner():
                _compute(seg)
        else:
            _compute(None)

    @pl.when(t == gnq - 1)
    def _finish():
        dk_ref[...] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_sc[...].astype(dv_ref.dtype)


def _bwd(q, k, v, o, lse, do, bias, q_seg, kv_seg, seed, causal, sm_scale,
         block_q, block_k, hq, hkv, bias_bh, dropout_p):
    bhq, sq, d = q.shape
    bhkv, sk, _ = k.shape
    group = hq // hkv
    nq, nk = sq // block_q, sk // block_k
    offset = sk - sq
    has_bias = bias is not None
    has_seg = q_seg is not None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [bhq, 1, sq]

    sp = _build_specs(block_q, block_k, d, hq, hkv, bias_bh)
    dq_kernel = functools.partial(
        _dq_kernel, sm_scale=sm_scale, causal=causal, offset=offset,
        block_q=block_q, block_k=block_k, nk=nk, has_bias=has_bias,
        has_seg=has_seg, dropout_p=dropout_p, sk=sk,
        threshold=_threshold(dropout_p))
    in_specs = [sp["q"], sp["kv"], sp["kv"], sp["q"], sp["row_q"],
                sp["row_q"]]
    inputs = [q, k, v, do, lse, delta]
    if has_bias:
        in_specs.append(sp["bias"])
        inputs.append(bias)
    if has_seg:
        in_specs += [sp["qseg"], sp["kvseg"]]
        inputs += [q_seg, kv_seg]
    if dropout_p > 0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        inputs.append(seed)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bhq, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda b, j, i: (b, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bhq, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*inputs)

    # dk/dv at KV-head resolution: grid (B*Hkv, nk, group*nq) — the inner
    # fused dimension walks every (query head in the group, q block) pair,
    # accumulating into one [block_k, d] scratch. GQA head reduction happens
    # in-kernel; dk/dv never inflate to Hq.
    def qflat(b, t):
        return _qflat(b, t, hq=hq, hkv=hkv, group=group, nq=nq)

    dkv_in_specs = [
        pl.BlockSpec((None, block_q, d),
                     lambda b, i, t: (qflat(b, t), t % nq, 0)),       # q
        pl.BlockSpec((None, block_k, d), lambda b, i, t: (b, i, 0)),  # k
        pl.BlockSpec((None, block_k, d), lambda b, i, t: (b, i, 0)),  # v
        pl.BlockSpec((None, block_q, d),
                     lambda b, i, t: (qflat(b, t), t % nq, 0)),       # do
        pl.BlockSpec((None, 1, block_q),
                     lambda b, i, t: (qflat(b, t), 0, t % nq)),       # lse
        pl.BlockSpec((None, 1, block_q),
                     lambda b, i, t: (qflat(b, t), 0, t % nq)),       # delta
    ]
    dkv_inputs = [q, k, v, do, lse, delta]
    if has_bias:
        bb_n, hb_n, row_bcast = bias_bh

        def bias_of(b, t):
            bb = (b // hkv) if bb_n > 1 else 0
            hh = ((b % hkv) * group + t // nq) if hb_n > 1 else 0
            return bb * hb_n + hh
        if row_bcast:
            dkv_in_specs.append(pl.BlockSpec(
                (None, 1, block_k),
                lambda b, i, t: (bias_of(b, t), 0, i)))
        else:
            dkv_in_specs.append(pl.BlockSpec(
                (None, block_q, block_k),
                lambda b, i, t: (bias_of(b, t), t % nq, i)))
        dkv_inputs.append(bias)
    if has_seg:
        dkv_in_specs += [
            pl.BlockSpec((None, 1, block_q),
                         lambda b, i, t: (b // hkv, 0, t % nq)),
            pl.BlockSpec((None, 1, block_k),
                         lambda b, i, t: (b // hkv, 0, i)),
        ]
        dkv_inputs += [q_seg, kv_seg]
    if dropout_p > 0:
        dkv_in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dkv_inputs.append(seed)

    dkv_kernel = functools.partial(
        _dkv_kernel, sm_scale=sm_scale, causal=causal, offset=offset,
        block_q=block_q, block_k=block_k, nq=nq, group=group,
        has_bias=has_bias, has_seg=has_seg, dropout_p=dropout_p, sk=sk,
        threshold=_threshold(dropout_p), hq=hq, hkv=hkv)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bhkv, nk, group * nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, i, t: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, i, t: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhkv, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bhkv, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*dkv_inputs)
    return dq, dk, dv


# =========================== custom-vjp wrapper ==============================
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(7, 8, 9, 10, 11, 12, 13, 14))
def _flash(q, k, v, bias, q_seg, kv_seg, seed, causal, sm_scale, block_q,
           block_k, hq, hkv, bias_bh, dropout_p):
    o, _ = _fwd(q, k, v, bias, q_seg, kv_seg, seed, causal, sm_scale,
                block_q, block_k, hq, hkv, bias_bh, dropout_p)
    return o


def _flash_fwd(q, k, v, bias, q_seg, kv_seg, seed, causal, sm_scale,
               block_q, block_k, hq, hkv, bias_bh, dropout_p):
    o, lse = _fwd(q, k, v, bias, q_seg, kv_seg, seed, causal, sm_scale,
                  block_q, block_k, hq, hkv, bias_bh, dropout_p)
    return o, (q, k, v, bias, q_seg, kv_seg, seed, o, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, hq, hkv, bias_bh,
               dropout_p, res, do):
    q, k, v, bias, q_seg, kv_seg, seed, o, lse = res
    dq, dk, dv = _bwd(q, k, v, o, lse, do, bias, q_seg, kv_seg, seed,
                      causal, sm_scale, block_q, block_k, hq, hkv, bias_bh,
                      dropout_p)
    # bias is an attention mask: constant by contract (zero grad); segment
    # ids are carried as f32 so integer-cotangent (float0) plumbing never
    # enters the picture; the seed is integer state (no grad)
    dbias = None if bias is None else jnp.zeros_like(bias)
    dqs = None if q_seg is None else jnp.zeros_like(q_seg)
    dks = None if kv_seg is None else jnp.zeros_like(kv_seg)
    return dq, dk, dv, dbias, dqs, dks, None


_flash.defvjp(_flash_fwd, _flash_bwd)

# module-level jit so EAGER calls hit the compile cache: without this,
# every eager flash_attention re-traces and re-compiles the pallas_call
# (~1s/call on chip vs ~1ms steady-state — measured). Under an outer
# jit/TrainStep trace this inlines and changes nothing. None-valued
# optional inputs are empty pytrees — one jitted callable serves every
# bias/segment combination.
_flash_cached = functools.partial(
    jax.jit, static_argnums=(7, 8, 9, 10, 11, 12, 13, 14))(_flash)


def _pick_block(requested, seq):
    """Largest lane-multiple block <= requested that divides seq, else the
    smallest lane-multiple divisor above it, else the whole sequence (always
    a legal tile). The (1, block) rows (lse, segment ids) must satisfy the
    TPU tile rule: last dim a multiple of 128 or equal to the array dim.
    Interpret mode (CPU tests) keeps the raw clamp so indivisible shapes
    still surface as ValueError."""
    block = min(requested, seq)
    if _interpret():
        return block
    if seq % block == 0 and (block % _LANES == 0 or block == seq):
        return block
    cands = [b for b in range(_LANES, block + 1, _LANES) if seq % b == 0]
    if cands:
        return cands[-1]
    bigger = [b for b in range(_LANES, seq, _LANES) if seq % b == 0]
    return bigger[0] if bigger else seq


def _norm_bias(bias, b, hq, sq, sk):
    """Normalize bias to (flat [Bb*Hb, Sq|1, Sk], (Bb, Hb, row_bcast)) with
    Bb in {1,B}, Hb in {1,Hq}. A size-1 q dim (the [B, 1, 1, Sk]
    key-padding-mask shape) is served by a one-row BlockSpec — never
    broadcast to Sq in HBM."""
    bias = jnp.asarray(bias)
    if bias.dtype == jnp.bool_:  # bool convention: True = attend
        bias = jnp.where(bias, 0.0,
                         jnp.float32(jnp.finfo(jnp.float32).min))
    if bias.ndim == 2:
        bias = bias[None, None]
    elif bias.ndim == 3:  # [B|H ambiguous, Sq, Sk] — treat as per-head
        bias = bias[None]
    if bias.ndim != 4:
        raise ValueError(f"bias must be 2/3/4-D, got shape {bias.shape}")
    bb, hb = bias.shape[0], bias.shape[1]
    if bb not in (1, b) or hb not in (1, hq):
        raise ValueError(
            f"bias batch/head dims {bias.shape[:2]} must be 1 or match "
            f"(batch={b}, heads={hq})")
    rows = bias.shape[2]
    if rows not in (1, sq) or bias.shape[3] != sk:
        raise ValueError(
            f"bias tail {bias.shape[2:]} must equal (q_len|1, kv_len)="
            f"({sq}|1, {sk})")
    return (bias.reshape(bb * hb, rows, sk), (bb, hb, rows == 1))


def _norm_seg(seg, b, s, name):
    seg = jnp.asarray(seg)
    if seg.ndim == 1:
        seg = seg[None]
    if seg.shape != (b, s):
        raise ValueError(f"{name} must have shape [batch={b}, {s}], got "
                         f"{tuple(seg.shape)}")
    # f32 carrier: exact for ids < 2^24 and sidesteps integer cotangents
    return seg.astype(jnp.float32).reshape(b, 1, s)


def flash_attention_bhsd(q, k, v, causal=False, sm_scale=None, bias=None,
                         q_segment_ids=None, kv_segment_ids=None,
                         dropout_p=0.0, dropout_seed=None,
                         block_q=None, block_k=None):
    """Flash attention on arrays in [B, H, S, D] (or [BH, S, D]) layout.

    GQA: 4-D ``k``/``v`` may carry fewer heads than ``q`` (``Hq % Hkv == 0``)
    — the kernel maps each query head onto its shared KV head; nothing is
    replicated. Cross attention: ``kv_len`` may differ from ``q_len``; with
    ``causal=True`` query i attends keys <= i + (kv_len - q_len) (the
    chunked-prefill/decode convention). ``bias`` is an additive mask
    broadcastable to [B, Hq, Sq, Sk]. Segment ids ([B, Sq]/[B, Sk] ints)
    restrict attention to equal ids; for 3-D inputs their batch dim is BH.
    """
    squeeze = None
    if q.ndim == 4:
        b, hq, sq, d = q.shape
        _, hkv, sk, _ = k.shape
        if k.shape != (b, hkv, sk, d) or v.shape != (b, hkv, sk, d):
            raise ValueError(f"k/v shapes {k.shape}/{v.shape} inconsistent")
        if hq % hkv:
            raise ValueError(
                f"q heads {hq} must be a multiple of kv heads {hkv}")
        q = q.reshape(b * hq, sq, d)
        k = k.reshape(b * hkv, sk, d)
        v = v.reshape(b * hkv, sk, d)
        squeeze = (b, hq)
    else:
        b, hq, hkv = q.shape[0], 1, 1
        if (k.shape[0] != b or k.shape[2] != q.shape[2]
                or v.shape != k.shape):
            raise ValueError(
                f"3-D flash attention requires matching batch*heads and "
                f"head_dim (and v matching k), got "
                f"{q.shape}/{k.shape}/{v.shape}")
        sq, sk, d = q.shape[1], k.shape[1], q.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    # validate BEFORE block resolution: an invalid call must fail in
    # microseconds, not after a ~24 s autotune sweep
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("segment ids must be given for both q and kv")
    dropout_p = float(dropout_p)
    if not 0.0 <= dropout_p < 1.0:
        raise ValueError(f"dropout_p must be in [0, 1), got {dropout_p}")
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError(
            "dropout_p > 0 requires dropout_seed (an int or int32 "
            "array) so forward and recompute-backward agree")
    if block_q is None or block_k is None:
        bias_kind = None
        if bias is not None:
            rows = bias.shape[-2] if bias.ndim >= 2 else 1
            bias_kind = "row" if rows == 1 else "full"
        tq, tk = _auto_blocks(b, sq, sk, d, hq, hkv, str(q.dtype), causal,
                              bias_kind, q_segment_ids is not None,
                              dropout_p > 0.0)
        block_q = tq if block_q is None else block_q
        block_k = tk if block_k is None else block_k
    bias_bh = None
    if bias is not None:
        bias, bias_bh = _norm_bias(bias, b, hq, sq, sk)
        if not bias_bh[2]:  # full [bq, bk] f32 tiles: cap for VMEM; the
            # one-row key-padding shape streams [1, bk] and keeps the
            # swept-fast 1024 blocks
            block_q = min(block_q, _BIAS_BLOCK)
            block_k = min(block_k, _BIAS_BLOCK)
    req_q, req_k = block_q, block_k
    block_q = _pick_block(block_q, sq)
    block_k = _pick_block(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"flash attention requires q_len {sq} / kv_len {sk} divisible "
            f"by block sizes ({block_q}, {block_k}); pad the sequence")
    if (block_q > max(req_q, _MAX_BLOCK)
            or block_k > max(req_k, _MAX_BLOCK)):
        # seq has no lane-multiple divisor (odd lengths) and is too long to
        # stream as one tile — cheap early error, no Mosaic compile attempt
        raise ValueError(
            f"no VMEM-safe block tiling for q_len {sq} / kv_len {sk} "
            f"(forced blocks ({block_q}, {block_k}) exceed {_MAX_BLOCK}); "
            "pad the sequence to a multiple of 128")

    q_seg = kv_seg = None
    if q_segment_ids is not None:
        q_seg = _norm_seg(q_segment_ids, b, sq, "q_segment_ids")
        kv_seg = _norm_seg(kv_segment_ids, b, sk, "kv_segment_ids")
    seed = None
    if dropout_p > 0.0:
        seed = jnp.atleast_1d(jnp.asarray(dropout_seed)).astype(
            jnp.int32)[:1]

    out = _flash_cached(q, k, v, bias, q_seg, kv_seg, seed, causal,
                        float(sm_scale), block_q, block_k, hq, hkv,
                        bias_bh, dropout_p)
    if squeeze:
        b, hq = squeeze
        out = out.reshape(b, hq, sq, d)
    return out


def flash_attention_bshd(query, key, value, causal=False, sm_scale=None,
                         bias=None, q_segment_ids=None, kv_segment_ids=None,
                         dropout_p=0.0, dropout_seed=None,
                         block_q=None, block_k=None):
    """Flash attention with paddle's [batch, seq, heads, head_dim] layout,
    Tensor-in/Tensor-out, recorded on the autograd tape. ``key``/``value``
    may carry fewer heads (GQA) and a different sequence length (cross
    attention) than ``query``. ``bias``/segment ids are mask constants —
    closed over, not taped."""
    from paddle_tpu.core.autograd import apply_op

    def _raw(x):
        return x.data if hasattr(x, "data") else jnp.asarray(x)

    bias_arr = None if bias is None else _raw(bias)
    qseg_arr = None if q_segment_ids is None else _raw(q_segment_ids)
    kvseg_arr = None if kv_segment_ids is None else _raw(kv_segment_ids)

    def f(q, k, v):
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        o = flash_attention_bhsd(qt, kt, vt, causal=causal,
                                 sm_scale=sm_scale, bias=bias_arr,
                                 q_segment_ids=qseg_arr,
                                 kv_segment_ids=kvseg_arr,
                                 dropout_p=dropout_p,
                                 dropout_seed=dropout_seed,
                                 block_q=block_q, block_k=block_k)
        return jnp.swapaxes(o, 1, 2)
    return apply_op(f, query, key, value, op_name="flash_attention")
