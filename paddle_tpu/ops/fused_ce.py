"""Fused vocab-chunked cross entropy: loss(h @ Wᵀ, labels) without ever
materializing the [T, V] logits.

The vocab projection is the single biggest matmul in a causal-LM step
(V=128K: logits are ~2 GB in f32 at bench shapes, written+read several
times by a naive softmax-CE). This op streams W in vocab chunks with an
online logsumexp (the flash-attention trick applied to CE) and recomputes
each chunk's softmax in the backward — peak extra memory is one
[T, V/chunks] block. The reference reaches the same goal with its fused
``softmax_with_cross_entropy`` CUDA kernels
(``paddle/phi/kernels/gpu/cross_entropy_kernel.cu``) and the
c_softmax_with_cross_entropy op for the model-parallel case; here XLA gets
MXU-shaped [T, d] x [d, Vc] matmuls it can pipeline, wrapped in a
``jax.custom_vjp`` so autodiff cannot silently save every chunk.

Returns PER-TOKEN losses [T] (callers reduce), matching
``F.cross_entropy(..., reduction='none')`` semantics for hard labels.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["matmul_cross_entropy", "causal_lm_loss"]

_DEF_CHUNKS = 8


def _chunks(w_vd, n_chunks):
    V, d = w_vd.shape
    vc = V // n_chunks
    return w_vd.reshape(n_chunks, vc, d), vc


def _fwd(h, w_vd, labels, valid, n_chunks):
    T = h.shape[0]
    wc, vc = _chunks(w_vd, n_chunks)
    starts = jnp.arange(n_chunks, dtype=jnp.int32) * vc

    def body(carry, chunk):
        m, s, lab = carry
        w, start = chunk
        logits = jax.lax.dot_general(
            h, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [T, vc]
        m_new = jnp.maximum(m, logits.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logits - m_new[:, None]).sum(axis=-1)
        idx = jnp.clip(labels - start, 0, vc - 1)
        ll = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        in_chunk = (labels >= start) & (labels < start + vc)
        lab = jnp.where(in_chunk, ll, lab)
        return (m_new, s, lab), None

    init = (jnp.full((T,), -jnp.inf, jnp.float32),
            jnp.zeros((T,), jnp.float32),
            jnp.zeros((T,), jnp.float32))
    (m, s, lab), _ = jax.lax.scan(body, init, (wc, starts))
    lse = m + jnp.log(s)
    # ignored tokens: zero loss; callers reducing to a mean must divide by
    # the VALID-token count (F.cross_entropy masked-mean semantics)
    return jnp.where(valid, lse - lab, 0.0), lse


def _bwd(h, w_vd, labels, valid, lse, dout, n_chunks):
    wc, vc = _chunks(w_vd, n_chunks)
    starts = jnp.arange(n_chunks, dtype=jnp.int32) * vc
    dout = dout * valid.astype(dout.dtype)  # ignored tokens: zero grad

    def body(dh, chunk):
        w, start = chunk
        logits = jax.lax.dot_general(
            h, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lse[:, None])  # softmax chunk, recomputed
        idx = labels - start
        onehot = (idx[:, None] == jnp.arange(vc)[None, :])
        g = (p - onehot.astype(p.dtype)) * dout[:, None]  # [T, vc] f32
        dh = dh + jax.lax.dot_general(
            g.astype(h.dtype), w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dw = jax.lax.dot_general(
            g.astype(h.dtype), h, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [vc, d]
        return dh, dw.astype(w_vd.dtype)

    dh0 = jnp.zeros(h.shape, jnp.float32)
    dh, dw = jax.lax.scan(body, dh0, (wc, starts))
    return dh.astype(h.dtype), dw.reshape(w_vd.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _mce(h, w_vd, labels, valid, n_chunks):
    loss, _ = _fwd(h, w_vd, labels, valid, n_chunks)
    return loss


def _mce_fwd(h, w_vd, labels, valid, n_chunks):
    loss, lse = _fwd(h, w_vd, labels, valid, n_chunks)
    return loss, (h, w_vd, labels, valid, lse)


def _mce_bwd(n_chunks, res, dout):
    h, w_vd, labels, valid, lse = res
    dh, dw = _bwd(h, w_vd, labels, valid, lse, dout, n_chunks)
    return dh, dw, None, None


_mce.defvjp(_mce_fwd, _mce_bwd)


def _auto_chunks(T, V, d, dtype) -> int:
    """Vocab chunk count for this signature: the default, or — with
    ``FLAGS_use_autotune`` — the winner of an on-chip sweep, cached per
    (T, V, d, dtype) like the flash block sizes (reference
    phi/kernels/autotune AutoTuneCache analog)."""
    from paddle_tpu.core.flags import flag
    if not flag("use_autotune"):
        return _DEF_CHUNKS  # fast exit: no backend probe when disabled
    import jax
    if jax.default_backend() != "tpu":
        return _DEF_CHUNKS
    from paddle_tpu.ops.pallas.autotune import autotune

    def build(nc):
        from paddle_tpu.ops.pallas.autotune import aot_runner
        if V % nc:
            raise ValueError("chunk count must divide V")
        with jax.ensure_compile_time_eval():
            dt = jnp.dtype(dtype)
            h0 = jnp.zeros((T, d), dt)
            w0 = jnp.zeros((V, d), dt)
            lab0 = jnp.zeros((T,), jnp.int32)
            valid0 = jnp.ones((T,), bool)
        return aot_runner(jax.value_and_grad(
            lambda ha, wa: _mce(ha, wa, lab0, valid0, nc).sum(),
            argnums=(0, 1)), h0, w0)

    return autotune("fused_ce_chunks", (T, V, d, str(dtype)),
                    [4, 8, 16, 32], build, _DEF_CHUNKS)


def matmul_cross_entropy(h, w_vd, labels, ignore_index: int = -100,
                         n_chunks=None):
    """Per-token CE of ``h @ w_vdᵀ`` against int ``labels``.

    ``h``: [T, d] (or [..., d], flattened), ``w_vd``: [V, d] (embedding
    -layout weight, as tied LM heads store it), ``labels``: int [T].
    Tokens whose label equals ``ignore_index`` contribute zero loss and
    zero gradient (``F.cross_entropy`` semantics). ``n_chunks`` must
    divide V; falls back to 1 chunk (still fused) when it doesn't;
    ``None`` picks the default (or the autotuned winner under
    ``FLAGS_use_autotune``).
    """
    lead = h.shape[:-1]
    h2 = h.reshape(-1, h.shape[-1])
    lab = labels.reshape(-1).astype(jnp.int32)
    valid = lab != ignore_index
    lab = jnp.where(valid, lab, 0)  # safe index for the chunk gather
    V = w_vd.shape[0]
    if n_chunks is None:
        n_chunks = _auto_chunks(h2.shape[0], V, h2.shape[1],
                                str(h2.dtype))
    if V % n_chunks:
        n_chunks = 1
    loss = _mce(h2, w_vd, lab, valid, n_chunks)
    return loss.reshape(lead)


def causal_lm_loss(h, w_vd, labels, ignore_index: int = -100):
    """Masked-mean causal-LM loss over the fused chunked matmul-CE —
    the ONE definition shared by the zoo's tied/untied LMs (position t
    predicts token t+1, the HF shift; ``ignore_index`` positions
    contribute zero loss and zero denominator). ``h`` [B, S, d] raw
    arrays, ``w_vd`` [V, d]."""
    tgt = labels[:, 1:].reshape(-1)
    per_tok = matmul_cross_entropy(
        h[:, :-1, :].reshape(-1, h.shape[-1]), w_vd, tgt,
        ignore_index=ignore_index)
    valid = (tgt != ignore_index).astype(per_tok.dtype)
    return per_tok.sum() / jnp.maximum(valid.sum(), 1.0)
