"""TensorArray + array ops (reference: ``paddle/phi/core/tensor_array.h``
TensorArray; Python surface ``python/paddle/tensor/array.py``
create_array / array_write / array_read / array_length).

Eager-mode design: a Python list of Tensors (the reference dygraph path
does exactly this — ``array.py`` appends to a list when in dygraph mode).
Inside jit-captured code, prefer ``lax.scan`` via the nn RNN layers; the
list form is the dygraph UX."""
from __future__ import annotations

from typing import List, Optional

from paddle_tpu.core.tensor import Tensor

__all__ = ["TensorArray", "create_array", "array_write", "array_read",
           "array_length"]


class TensorArray(list):
    """A list of Tensors with the reference's dtype tag."""

    def __init__(self, dtype: str = "float32"):
        super().__init__()
        self.dtype = dtype


def create_array(dtype: str = "float32", initialized_list=None):
    arr = TensorArray(dtype)
    for t in initialized_list or ():
        arr.append(t if isinstance(t, Tensor) else Tensor(t))
    return arr


def _index(i) -> int:
    if isinstance(i, Tensor):
        return int(i.numpy())
    return int(i)


def array_write(x: Tensor, i, array: Optional[TensorArray] = None):
    """Write ``x`` at position ``i`` (extends the array if i == len)."""
    if array is None:
        array = create_array()
    idx = _index(i)
    if idx < len(array):
        array[idx] = x
    elif idx == len(array):
        array.append(x)
    else:
        raise IndexError(
            f"array_write index {idx} beyond array length {len(array)}")
    return array


def array_read(array: TensorArray, i) -> Tensor:
    return array[_index(i)]


def array_length(array: TensorArray) -> Tensor:
    from paddle_tpu.core.tensor import to_tensor
    return to_tensor(len(array), dtype="int64")
