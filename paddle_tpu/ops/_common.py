"""Shared helpers for ops."""
import jax
import jax.numpy as jnp

# TPU runs with x64 disabled; "int64" tensors are stored 32-bit (same policy as
# torch/xla). LONG is the canonical widest int actually materialized.
LONG = jax.dtypes.canonicalize_dtype(jnp.int64)
