"""Long-tail tensor ops (reference: ``python/paddle/tensor/{math,
manipulation,linalg,creation}.py`` — the remaining surface found by the
coverage probe). One jnp delegate per op, recorded on the tape like every
other op."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor
from ._registry import op

__all__ = [
    "kron", "trapezoid", "cumulative_trapezoid", "rad2deg", "deg2rad",
    "polygamma", "igamma", "igammac", "i0", "i1", "renorm", "floor_mod",
    "clip_", "label_smooth", "increment", "nanquantile", "digitize",
    "polar", "matrix_exp", "vander", "householder_product", "pdist",
    "tensordot", "mm", "trace", "clone", "unstack", "index_fill", "rank",
    "vsplit", "hsplit", "dsplit", "tensor_split", "binomial",
]


def _d(x):
    return x.data if isinstance(x, Tensor) else jnp.asarray(x)


@op
def kron(x, y):
    return jnp.kron(x, y)


@op
def trapezoid(y, x=None, dx=None, axis=-1):
    if x is not None:
        return jnp.trapezoid(y, x=x, axis=axis)
    return jnp.trapezoid(y, dx=1.0 if dx is None else dx, axis=axis)


@op
def cumulative_trapezoid(y, x=None, dx=None, axis=-1):
    # cumulative form of the trapezoid rule along axis
    y1 = jnp.moveaxis(y, axis, -1)
    if x is not None:
        if x.ndim > 1:
            xs = jnp.moveaxis(jnp.broadcast_to(x, y.shape), axis, -1)
            widths = jnp.diff(xs, axis=-1)
        else:
            widths = jnp.diff(x)
    else:
        widths = 1.0 if dx is None else dx
    areas = (y1[..., 1:] + y1[..., :-1]) / 2 * widths
    return jnp.moveaxis(jnp.cumsum(areas, axis=-1), -1, axis)


@op
def rad2deg(x):
    return jnp.rad2deg(x)


@op
def deg2rad(x):
    return jnp.deg2rad(x)


@op
def polygamma(x, n=1):
    return jax.scipy.special.polygamma(n, x)


@op
def igamma(x, a):
    # torch/paddle convention: igamma = lower regularized P(x, a),
    # igammac = upper Q(x, a)
    return jax.scipy.special.gammainc(x, a)


@op
def igammac(x, a):
    return jax.scipy.special.gammaincc(x, a)


@op
def i0(x):
    return jax.scipy.special.i0(x)


@op
def i1(x):
    return jax.scipy.special.i1(x)


@op
def renorm(x, p, axis, max_norm):
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    norms = jnp.linalg.norm(flat, ord=p, axis=1)
    scale = jnp.where(norms > max_norm,
                      max_norm / jnp.maximum(norms, 1e-12), 1.0)
    out = flat * scale[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


@op
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


@op
def polar(abs, angle):
    return abs * jnp.exp(1j * angle.astype(jnp.result_type(angle,
                                                           jnp.complex64)))


@op
def matrix_exp(x):
    return jax.scipy.linalg.expm(x)


@op
def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


@op
def householder_product(x, tau):
    return jax.lax.linalg.householder_product(x, tau)


@op
def pdist(x, p=2.0):
    d = x[:, None, :] - x[None, :, :]
    dm = jnp.linalg.norm(d, ord=p, axis=-1)
    n = x.shape[0]
    iu = jnp.triu_indices(n, k=1)
    return dm[iu]


@op
def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


@op
def mm(input, mat2):
    return jnp.matmul(input, mat2)


@op
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@op
def clone(x):
    return x + 0  # new buffer, gradient-transparent


@op
def index_fill(x, index, axis, value):
    moved = jnp.moveaxis(x, axis, 0)
    out = moved.at[index].set(value)
    return jnp.moveaxis(out, 0, axis)


def unstack(x, axis=0, num=None):
    """paddle.unstack: split along axis and squeeze it."""
    def f(a):
        return tuple(jnp.squeeze(s, axis)
                     for s in jnp.split(a, a.shape[axis], axis))
    out = apply_op(f, x, op_name="unstack")
    return list(out)


def rank(x):
    return Tensor(jnp.asarray(_d(x).ndim, jnp.int32))


def nanquantile(x, q, axis=None, keepdim=False):
    def f(a):
        return jnp.nanquantile(a, q, axis=axis, keepdims=keepdim)
    return apply_op(f, x, op_name="nanquantile")


def digitize(x, bins, right=False):
    def f(a, b):
        return jnp.digitize(a, b, right=right)
    return apply_op(f, x, bins, op_name="digitize")


def _split_helper(x, indices_or_sections, axis):
    def f(a):
        return tuple(jnp.array_split(a, indices_or_sections, axis=axis)
                     if isinstance(indices_or_sections, int)
                     else jnp.split(a, list(indices_or_sections),
                                    axis=axis))
    return list(apply_op(f, x, op_name="tensor_split"))


def tensor_split(x, num_or_indices, axis=0):
    return _split_helper(x, num_or_indices, axis)


def vsplit(x, num_or_indices):
    return _split_helper(x, num_or_indices, 0)


def hsplit(x, num_or_indices):
    return _split_helper(x, num_or_indices, 1)


def dsplit(x, num_or_indices):
    return _split_helper(x, num_or_indices, 2)


def clip_(x, min=None, max=None):
    """In-place clip (paddle clip_) — delegates to Tensor.clip_ (the
    single dtype/shape-preserving implementation)."""
    return x.clip_(min, max)


def increment(x, value=1.0):
    """paddle.increment: in-place scalar add (static-graph counter op)."""
    x._data = _d(x) + value
    x._version += 1
    return x


def floor_mod(x, y):
    from . import math as _m
    return _m.mod(x, y)


def binomial(count, prob):
    """Sample Binomial(count, prob) elementwise (paddle.binomial).

    Exact bernoulli-sum for small counts; for max(count) > 4096 the
    normal approximation (rounded, clipped to [0, count]) keeps memory
    O(shape) instead of O(max_count * shape)."""
    from paddle_tpu.core.generator import next_key
    c = np.asarray(_d(count))
    p = _d(prob)
    cmax = int(c.max()) if c.size else 0
    if cmax > 4096:
        mean = jnp.asarray(c) * p
        std = jnp.sqrt(jnp.asarray(c) * p * (1 - p))
        g = jax.random.normal(next_key(), jnp.broadcast_shapes(
            p.shape, c.shape))
        draw = jnp.round(mean + std * g)
        return Tensor(jnp.clip(draw, 0, jnp.asarray(c)).astype(jnp.int64))
    draws = jax.random.bernoulli(
        next_key(), jnp.broadcast_to(p, (cmax,) + p.shape))
    idx = jnp.arange(cmax)
    mask = idx[(...,) + (None,) * p.ndim] < jnp.asarray(c)
    return Tensor(jnp.sum(draws * mask, axis=0).astype(jnp.int64))
