"""Op registry and eager-dispatch decorator.

TPU-native collapse of the reference's op stack (SURVEY.md §2.1, §3.1): where Paddle
needs a YAML schema (``paddle/phi/api/yaml/ops.yaml``), codegen
(``api_gen.py``/``eager_gen.py``), a kernel registry keyed by
(name, backend, layout, dtype) (``phi/core/kernel_factory.h:314``) and per-backend
kernel files, a TPU framework needs exactly one definition per op: a pure JAX
function. XLA is the only backend; dtype/layout dispatch, fusion and scheduling are
the compiler's job. The registry here exists for introspection, the Tensor-method
monkey-patch (the reference patches methods onto its Tensor too —
``python/paddle/fluid/dygraph/varbase_patch_methods.py``), and the static-capture
path which records op names.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

from paddle_tpu.core.autograd import apply_op

OPS: Dict[str, Callable] = {}      # name -> eager wrapper
RAW: Dict[str, Callable] = {}      # name -> pure jax fn


def op(fn: Optional[Callable] = None, *, name: Optional[str] = None):
    """Register a pure jax-level function as an eager op.

    The wrapper unwraps Tensor args, records a GradNode via jax.vjp when needed,
    and re-wraps outputs (see core.autograd.apply_op).
    """

    def deco(f):
        opname = name or f.__name__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            return apply_op(f, *args, op_name=opname, **kwargs)

        OPS[opname] = wrapper
        RAW[opname] = f
        return wrapper

    return deco(fn) if fn is not None else deco


def get_op(name: str) -> Callable:
    return OPS[name]
