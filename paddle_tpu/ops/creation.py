"""Tensor creation ops (reference: paddle/phi/kernels/*/full_kernel.cc, arange,
gaussian, uniform etc.; Python surface python/paddle/tensor/creation.py /
random.py). Random ops draw keys from the stateful Generator stream
(core/generator.py) so eager UX matches Paddle while staying pure under trace."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import generator as _gen
from paddle_tpu.core.dtype import convert_dtype
from paddle_tpu.core.tensor import Tensor, to_tensor
from ._common import LONG

__all__ = [
    "zeros", "ones", "full", "empty", "zeros_like", "ones_like", "full_like",
    "empty_like", "arange", "linspace", "logspace", "eye", "diag", "diagflat",
    "tril", "triu", "meshgrid", "rand", "randn", "randint", "randint_like",
    "uniform", "normal", "standard_normal", "randperm", "bernoulli",
    "multinomial", "poisson", "exponential_", "tril_indices", "triu_indices",
    "clone_detached", "complex",
]


def _dt(dtype, default="float32"):
    from paddle_tpu.core.flags import flag
    if dtype is None:
        dtype = flag("default_dtype") if default == "float32" else default
    return convert_dtype(dtype).np_dtype


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape.data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)

    def one(s):
        if isinstance(s, Tensor):
            return int(s.item())
        try:
            return int(s)
        except Exception:
            return s  # symbolic dim (jax.export shape polymorphism)
    return tuple(one(s) for s in shape)


def zeros(shape, dtype=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def zeros_like(x, dtype=None):
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.zeros_like(d, dtype=None if dtype is None else _dt(dtype)))


def ones_like(x, dtype=None):
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.ones_like(d, dtype=None if dtype is None else _dt(dtype)))


def full_like(x, fill_value, dtype=None):
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.full_like(d, fill_value,
                                dtype=None if dtype is None else _dt(dtype)))


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = ("int64" if all(isinstance(v, (int, np.integer))
                                for v in (start, end, step)) else "float32")
    return Tensor(jnp.arange(start, end, step, _dt(dtype, default=dtype)))


def linspace(start, stop, num, dtype=None):
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None):
    return Tensor(jnp.eye(int(num_rows),
                          None if num_columns is None else int(num_columns),
                          dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0):
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    if d.ndim == 1 and padding_value != 0:
        n = d.shape[0] + builtins_abs(offset)
        base = jnp.full((n, n), padding_value, d.dtype)
        return Tensor(base + jnp.diag(d, offset) - jnp.diag(
            jnp.zeros_like(d) + padding_value, offset))
    return Tensor(jnp.diag(d, offset))


builtins_abs = abs


def diagflat(x, offset=0):
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.diagflat(d, offset))


def tril(x, diagonal=0):
    from ._registry import OPS
    return OPS["tril"](x, diagonal=diagonal)


def triu(x, diagonal=0):
    from ._registry import OPS
    return OPS["triu"](x, diagonal=diagonal)


def tril_indices(row, col, offset=0):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]).astype(np.int64)))


def triu_indices(row, col=None, offset=0):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]).astype(np.int64)))


def meshgrid(*args):
    arrs = [a.data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    if len(arrs) == 1 and isinstance(arrs[0], (list, tuple)):
        arrs = list(arrs[0])
    outs = jnp.meshgrid(*arrs, indexing="ij")
    return [Tensor(o) for o in outs]


def complex(real, imag):
    r = real.data if isinstance(real, Tensor) else jnp.asarray(real)
    i = imag.data if isinstance(imag, Tensor) else jnp.asarray(imag)
    return Tensor(jax.lax.complex(r, i))


# -- random --------------------------------------------------------------------
def rand(shape, dtype=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None):
    k = _gen.next_key()
    return Tensor(jax.random.normal(k, _shape(shape), _dt(dtype)))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean.data if isinstance(mean, Tensor) else mean
        s = std.data if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            jnp.shape(m), jnp.shape(s)) if shape is None else _shape(shape)
        k = _gen.next_key()
        return Tensor(jax.random.normal(k, out_shape, jnp.result_type(m)) * s + m)
    if shape is None:
        shape = [1]
    k = _gen.next_key()
    return Tensor(jax.random.normal(k, _shape(shape), jnp.float32) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0):
    k = _gen.next_key() if not seed else jax.random.key(seed)
    return Tensor(jax.random.uniform(k, _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype=None):
    if high is None:
        low, high = 0, low
    k = _gen.next_key()
    return Tensor(jax.random.randint(k, _shape(shape), low, high,
                                     _dt(dtype or "int64", default="int64")))


def randint_like(x, low=0, high=None, dtype=None):
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64"):
    k = _gen.next_key()
    return Tensor(jax.random.permutation(k, int(n)).astype(_dt(dtype, "int64")))


def bernoulli(x):
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    k = _gen.next_key()
    return Tensor(jax.random.bernoulli(k, d).astype(d.dtype))


def multinomial(x, num_samples=1, replacement=False):
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    k = _gen.next_key()
    logits = jnp.log(jnp.maximum(d, 1e-30))
    if d.ndim == 1:
        out = jax.random.choice(k, d.shape[0], (num_samples,),
                                replace=replacement, p=d / d.sum())
        return Tensor(out.astype(LONG))
    outs = []
    for i in range(d.shape[0]):
        k, sub = jax.random.split(k)
        outs.append(jax.random.choice(sub, d.shape[1], (num_samples,),
                                      replace=replacement,
                                      p=d[i] / d[i].sum()))
    return Tensor(jnp.stack(outs).astype(LONG))


def poisson(x):
    d = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    k = _gen.next_key()
    return Tensor(jax.random.poisson(k, d).astype(d.dtype))


def exponential_(x, lam=1.0):
    k = _gen.next_key()
    x._data = jax.random.exponential(k, tuple(x.shape), x.data.dtype) / lam
    x._version += 1
    return x


def clone_detached(x):
    return Tensor(x.data)
