"""Block-paged KV-cache attention — the gather-based XLA read path.

The serving engine (``paddle_tpu.serving``) stores each layer's KV cache
as a pool of fixed-size token blocks instead of one contiguous
``[B, L, n_kv, hd]`` buffer per batch:

    k_pool / v_pool : [num_blocks + 1, block_size, n_kv, hd]
                      (row 0 is the reserved null block; allocatable
                      block ids run 1..num_blocks)
    block_tables    : [B, max_blocks_per_seq] int32 — logical block i of
                      row b lives in physical block ``block_tables[b, i]``
    context_lens    : [B] int32 — tokens already cached per row
    new_lens        : [B] int32 — valid tokens in this call's input
                      (rows may carry right-padding: a partial prefill
                      chunk, or an inactive decode slot with new_len 0)

Physical **block 0 is reserved as the null block**: padded block-table
entries point at it and every invalid token's write is redirected into
it, so padding can never clobber a live sequence's cache. The allocator
(``serving.kv_cache``) never hands block 0 out.

This mirrors the vLLM / Ragged-Paged-Attention layout (see
``/opt/skills/guides/boom_attention_tricks.md`` §8: per-sequence
``page_indices`` over non-contiguous pages). Here the read path is a
plain XLA gather (``pool[block_tables]``) + masked softmax — correct on
every backend and the seam where a Pallas kernel with async per-page DMA
slots in later without touching the serving layer above it.
"""
from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["PagedLayerCache", "write_to_pool", "gather_pool",
           "paged_attention_step"]


class PagedLayerCache(NamedTuple):
    """One layer's view of the paged KV state.

    Threaded through ``LlamaModel.forward(caches=[...])`` exactly like
    the ``(k, v)`` / ``(k_buf, v_buf, pos)`` cache forms; the attention
    layer dispatches on this type. ``block_tables`` / ``context_lens`` /
    ``new_lens`` are shared across layers (one table per sequence), the
    pools are per-layer.
    """
    k_pool: object        # [num_blocks + 1, block_size, n_kv, hd]
    v_pool: object        # [num_blocks + 1, block_size, n_kv, hd]
    block_tables: object  # [B, max_blocks_per_seq] int32
    context_lens: object  # [B] int32
    new_lens: object      # [B] int32


def _scatter_indices(block_tables, positions, valid, block_size):
    """(phys_block [B,S], slot [B,S]) for logical ``positions`` [B,S];
    invalid tokens are redirected to (null block 0, slot 0)."""
    nblk = block_tables.shape[1]
    blk = jnp.clip(positions // block_size, 0, nblk - 1)
    phys = jnp.take_along_axis(block_tables, blk, axis=1)
    slot = positions % block_size
    phys = jnp.where(valid, phys, 0)
    slot = jnp.where(valid, slot, 0)
    return phys, slot


def write_to_pool(pool, new, block_tables, positions, valid):
    """Scatter ``new`` [B, S, n_kv, hd] into ``pool`` at logical
    ``positions`` [B, S] through ``block_tables``; tokens with
    ``valid == False`` land in the null block."""
    phys, slot = _scatter_indices(block_tables, positions, valid,
                                  pool.shape[1])
    return pool.at[phys, slot].set(new.astype(pool.dtype))


def gather_pool(pool, block_tables):
    """[B, max_blocks_per_seq * block_size, n_kv, hd] contiguous view of
    each row's paged context (the XLA-gather read path)."""
    g = pool[block_tables]  # [B, nblk, bs, n_kv, hd]
    B, nblk, bs = g.shape[0], g.shape[1], g.shape[2]
    return g.reshape(B, nblk * bs, *pool.shape[2:])


def paged_attention_step(q, k, v, k_pool, v_pool, block_tables,
                         context_lens, new_lens, *, scale=None):
    """One attention step over a block-paged cache.

    ``q`` [B, S, n_heads, hd] and ``k``/``v`` [B, S, n_kv, hd] are the
    (already position-encoded) projections of this call's ``S`` input
    tokens per row — ``S`` is the prefill chunk length, or 1 in decode.
    Writes the new K/V into the pools (invalid tokens to the null
    block), gathers each row's whole paged context, and runs masked
    GQA attention: key at logical position ``l`` is visible to row
    ``b``'s query ``i`` iff ``l <= context_lens[b] + i`` — that one
    bound covers prior context, in-chunk causality, and (together with
    null-block redirection) keeps padding invisible.

    Returns ``(out [B, S, n_heads*hd], k_pool', v_pool')``. Outputs at
    padded query positions (``i >= new_lens[b]``) are garbage by
    construction and must be discarded by the caller.
    """
    B, S, n_kv, hd = k.shape
    n_heads = q.shape[2]
    grp = n_heads // n_kv
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    pos = context_lens[:, None].astype(jnp.int32) + \
        jnp.arange(S, dtype=jnp.int32)[None, :]                 # [B, S]
    valid = jnp.arange(S, dtype=jnp.int32)[None, :] < \
        new_lens[:, None].astype(jnp.int32)
    k_pool = write_to_pool(k_pool, k, block_tables, pos, valid)
    v_pool = write_to_pool(v_pool, v, block_tables, pos, valid)
    keys = gather_pool(k_pool, block_tables)                    # [B, L, ...]
    vals = gather_pool(v_pool, block_tables)
    L = keys.shape[1]
    qg = q.reshape(B, S, n_kv, grp, hd)
    s = jnp.einsum("bskgh,blkh->bskgl", qg.astype(jnp.float32),
                   keys.astype(jnp.float32)) * scale
    visible = jnp.arange(L)[None, None, :] <= pos[:, :, None]   # [B, S, L]
    s = jnp.where(visible[:, :, None, None, :], s,
                  jnp.finfo(jnp.float32).min)
    w = jax.nn.softmax(s, axis=-1).astype(vals.dtype)
    out = jnp.einsum("bskgl,blkh->bskgh", w, vals)
    return out.reshape(B, S, n_heads * hd), k_pool, v_pool
