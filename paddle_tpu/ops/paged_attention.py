"""Block-paged KV-cache attention — the gather-based XLA read path.

The serving engine (``paddle_tpu.serving``) stores each layer's KV cache
as a pool of fixed-size token blocks instead of one contiguous
``[B, L, n_kv, hd]`` buffer per batch:

    k_pool / v_pool : [num_blocks + 1, block_size, n_kv, hd]
                      (row 0 is the reserved null block; allocatable
                      block ids run 1..num_blocks)
    block_tables    : [B, max_blocks_per_seq] int32 — logical block i of
                      row b lives in physical block ``block_tables[b, i]``
    context_lens    : [B] int32 — tokens already cached per row
    new_lens        : [B] int32 — valid tokens in this call's input
                      (rows may carry right-padding: a partial prefill
                      chunk, or an inactive decode slot with new_len 0)

Physical **block 0 is reserved as the null block**: padded block-table
entries point at it and every invalid token's write is redirected into
it, so padding can never clobber a live sequence's cache. The allocator
(``serving.kv_cache``) never hands block 0 out.

This mirrors the vLLM / Ragged-Paged-Attention layout (see
``/opt/skills/guides/boom_attention_tricks.md`` §8: per-sequence
``page_indices`` over non-contiguous pages). Two read paths share it:

* **gather** — a plain XLA gather (``pool[block_tables]``) + masked
  softmax. Correct on every backend; materializes each row's whole
  padded context, which is exactly the cost the kernel path removes.
  It stays as the backend-portable fallback and the parity oracle.
* **rpa** — the Ragged-Paged-Attention Pallas kernel
  (``ops/pallas/ragged_paged_attention.py``): the token-packed batch
  streams each sequence's KV page by page with online softmax, only
  the real ``context_len`` worth of pages, no dense score tensor.

``PADDLE_TPU_PAGED_ATTN_IMPL={rpa,gather,auto}`` picks the path
(``auto``, the default: rpa on TPU, gather elsewhere);
:func:`impl_override` pins it programmatically (the engine's
``attn_impl=`` knob, and how parity tests compare both). The serving
engine feeds the ragged token-packed form (:class:`RaggedLayerCache`);
the per-row ``[B, S]`` form (:class:`PagedLayerCache`) remains for
non-engine callers.
"""
from __future__ import annotations

import contextlib
import math
import os
import threading
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["PagedLayerCache", "RaggedLayerCache", "write_to_pool",
           "write_tokens_to_pool", "gather_pool", "paged_attention_step",
           "ragged_gather_attention", "ragged_paged_attention_step",
           "paged_attention_impl", "impl_override", "mesh_override",
           "quantize_kv_slots", "write_kv_scales_to_pool"]


class PagedLayerCache(NamedTuple):
    """One layer's view of the paged KV state.

    Threaded through ``LlamaModel.forward(caches=[...])`` exactly like
    the ``(k, v)`` / ``(k_buf, v_buf, pos)`` cache forms; the attention
    layer dispatches on this type. ``block_tables`` / ``context_lens`` /
    ``new_lens`` are shared across layers (one table per sequence), the
    pools are per-layer.
    """
    k_pool: object        # [num_blocks + 1, block_size, n_kv, hd]
    v_pool: object        # [num_blocks + 1, block_size, n_kv, hd]
    block_tables: object  # [B, max_blocks_per_seq] int32
    context_lens: object  # [B] int32
    new_lens: object      # [B] int32


def _scatter_indices(block_tables, positions, valid, block_size):
    """(phys_block [B,S], slot [B,S]) for logical ``positions`` [B,S];
    invalid tokens are redirected to (null block 0, slot 0)."""
    nblk = block_tables.shape[1]
    blk = jnp.clip(positions // block_size, 0, nblk - 1)
    phys = jnp.take_along_axis(block_tables, blk, axis=1)
    slot = positions % block_size
    phys = jnp.where(valid, phys, 0)
    slot = jnp.where(valid, slot, 0)
    return phys, slot


def write_to_pool(pool, new, block_tables, positions, valid):
    """Scatter ``new`` [B, S, n_kv, hd] into ``pool`` at logical
    ``positions`` [B, S] through ``block_tables``; tokens with
    ``valid == False`` land in the null block."""
    phys, slot = _scatter_indices(block_tables, positions, valid,
                                  pool.shape[1])
    return pool.at[phys, slot].set(new.astype(pool.dtype))


def gather_pool(pool, block_tables):
    """[B, max_blocks_per_seq * block_size, n_kv, hd] contiguous view of
    each row's paged context (the XLA-gather read path)."""
    g = pool[block_tables]  # [B, nblk, bs, n_kv, hd]
    B, nblk, bs = g.shape[0], g.shape[1], g.shape[2]
    return g.reshape(B, nblk * bs, *pool.shape[2:])


def paged_attention_step(q, k, v, k_pool, v_pool, block_tables,
                         context_lens, new_lens, *, scale=None):
    """One attention step over a block-paged cache.

    ``q`` [B, S, n_heads, hd] and ``k``/``v`` [B, S, n_kv, hd] are the
    (already position-encoded) projections of this call's ``S`` input
    tokens per row — ``S`` is the prefill chunk length, or 1 in decode.
    Writes the new K/V into the pools (invalid tokens to the null
    block), gathers each row's whole paged context, and runs masked
    GQA attention: key at logical position ``l`` is visible to row
    ``b``'s query ``i`` iff ``l <= context_lens[b] + i`` — that one
    bound covers prior context, in-chunk causality, and (together with
    null-block redirection) keeps padding invisible.

    Returns ``(out [B, S, n_heads*hd], k_pool', v_pool')``. Outputs at
    padded query positions (``i >= new_lens[b]``) are garbage by
    construction and must be discarded by the caller.
    """
    B, S, n_kv, hd = k.shape
    n_heads = q.shape[2]
    grp = n_heads // n_kv
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    pos = context_lens[:, None].astype(jnp.int32) + \
        jnp.arange(S, dtype=jnp.int32)[None, :]                 # [B, S]
    valid = jnp.arange(S, dtype=jnp.int32)[None, :] < \
        new_lens[:, None].astype(jnp.int32)
    k_pool = write_to_pool(k_pool, k, block_tables, pos, valid)
    v_pool = write_to_pool(v_pool, v, block_tables, pos, valid)
    keys = gather_pool(k_pool, block_tables)                    # [B, L, ...]
    vals = gather_pool(v_pool, block_tables)
    L = keys.shape[1]
    qg = q.reshape(B, S, n_kv, grp, hd)
    s = jnp.einsum("bskgh,blkh->bskgl", qg.astype(jnp.float32),
                   keys.astype(jnp.float32)) * scale
    visible = jnp.arange(L)[None, None, :] <= pos[:, :, None]   # [B, S, L]
    s = jnp.where(visible[:, :, None, None, :], s,
                  jnp.finfo(jnp.float32).min)
    w = jax.nn.softmax(s, axis=-1).astype(vals.dtype)
    out = jnp.einsum("bskgl,blkh->bskgh", w, vals)
    return out.reshape(B, S, n_heads * hd), k_pool, v_pool


# ===================== ragged token-packed form ==============================
class RaggedLayerCache(NamedTuple):
    """One layer's view of the paged KV state in the TOKEN-PACKED form
    the unified serving step uses (ISSUE 8): the step's input is a flat
    ``[1, total_tokens]`` axis holding every scheduled sequence's new
    tokens back to back — prefill chunks (S>1) and decode rows (S=1)
    together. ``block_tables`` carries an extra all-null sentinel row
    (index ``max_seqs``) that padding tokens resolve through; metadata
    rows beyond the live sequences point at it. The ``step_seq`` /
    ``step_blk`` work maps are built host-side per step
    (``ops.pallas.ragged_paged_attention.build_step_maps``) and are
    traced INPUTS — shapes never change, so the engine's one executable
    serves every batch mix."""
    k_pool: object        # [num_blocks + 1, block_size, n_kv, hd]
    v_pool: object        # [num_blocks + 1, block_size, n_kv, hd]
    block_tables: object  # [max_seqs + 1, max_blocks_per_seq] int32
    cu_seqlens: object    # [max_seqs + 2] int32 token-span prefix sums
    context_lens: object  # [max_seqs + 1] int32 cached tokens per seq
    seq_ids: object       # [T] int32 token -> sequence (max_seqs = pad)
    positions: object     # [T] int32 absolute position per token
    step_seq: object      # [num_q_tiles, max_steps] int32 kernel work map
    step_blk: object      # [num_q_tiles, max_steps] int32 kernel work map
    # int8-KV quantization (ISSUE 20): per-token-slot, per-head dequant
    # multipliers paged like the pools; None on unquantized engines
    k_scale: object = None  # [num_blocks + 1, block_size, n_kv] f32
    v_scale: object = None  # [num_blocks + 1, block_size, n_kv] f32


# thread-local: two engines may trace their unified steps concurrently
# on their background threads, each under its own attn_impl pin — a
# process-global would let one trace leak its impl into the other
_impl_local = threading.local()


def paged_attention_impl() -> str:
    """Resolve the paged read-path implementation: an
    :func:`impl_override` in effect on THIS thread, else
    ``PADDLE_TPU_PAGED_ATTN_IMPL`` (``rpa`` | ``gather`` | ``auto``),
    else auto — rpa on TPU, gather elsewhere. Read at TRACE time: a
    compiled serving step keeps whatever was resolved when it traced."""
    override = getattr(_impl_local, "value", None)
    if override is not None:
        return override
    v = os.environ.get("PADDLE_TPU_PAGED_ATTN_IMPL", "auto").lower()
    if v in ("rpa", "gather"):
        return v
    if v != "auto":
        raise ValueError(
            f"PADDLE_TPU_PAGED_ATTN_IMPL={v!r} (want rpa|gather|auto)")
    return "rpa" if jax.default_backend() == "tpu" else "gather"


@contextlib.contextmanager
def impl_override(value):
    """Pin the read-path impl for the calls traced inside the block on
    the current thread (``None`` = no-op). The engine wraps its unified
    step's trace in this so ``ServingEngine(attn_impl=...)`` wins over
    the env."""
    if value is not None and value not in ("rpa", "gather"):
        raise ValueError(f"attn impl {value!r} (want rpa|gather|None)")
    prev = getattr(_impl_local, "value", None)
    _impl_local.value = value
    try:
        yield
    finally:
        _impl_local.value = prev


@contextlib.contextmanager
def mesh_override(mesh):
    """Pin a tensor-parallel mesh for the ragged calls traced inside
    the block on this thread (``None`` = single-device, a no-op). The
    serving engine wraps its unified step's trace in this; the rpa
    branch of :func:`ragged_paged_attention_step` reads it to shard_map
    the Pallas kernel over the model-parallel axis (the kernel is
    opaque to GSPMD — the gather fallback needs nothing, XLA partitions
    it from the pool/projection shardings alone)."""
    prev = getattr(_impl_local, "mesh", None)
    _impl_local.mesh = mesh
    try:
        yield
    finally:
        _impl_local.mesh = prev


def _tp_mesh():
    """(mesh, mp_axis_name) when a tensor-parallel mesh with a >1
    model axis is pinned on this thread, else None."""
    mesh = getattr(_impl_local, "mesh", None)
    if mesh is None:
        return None
    for cand in ("mp", "model", "tp"):
        if cand in mesh.axis_names and mesh.shape[cand] > 1:
            return mesh, cand
    return None


def write_tokens_to_pool(pool, new, block_tables, seq_ids, positions):
    """Scatter ``new`` [T, n_kv, hd] into ``pool`` at each token's
    ``positions`` through its sequence's block-table row. Padding tokens
    (sentinel ``seq_ids`` → the all-null table row) land in the null
    block, exactly like the per-row form's invalid-token redirection."""
    bs, nblk = pool.shape[1], block_tables.shape[1]
    blk = jnp.clip(positions.astype(jnp.int32) // bs, 0, nblk - 1)
    phys = block_tables[seq_ids, blk]
    slot = jnp.where(phys == 0, 0, positions.astype(jnp.int32) % bs)
    return pool.at[phys, slot].set(new.astype(pool.dtype))


def quantize_kv_slots(x):
    """Symmetric per-token, per-head int8 quantization of KV rows:
    ``x [..., n_kv, hd]`` → ``(q int8 [..., n_kv, hd], scale f32
    [..., n_kv])`` with scale = absmax/127 (the dequant multiplier).
    The granularity matches the paged scale pools — one scalar per
    ``(token slot, kv head)`` — so dequantization is a broadcast
    multiply XLA fuses into the attention reads."""
    f = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(f), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(f / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def write_kv_scales_to_pool(scale_pool, scales, block_tables, seq_ids,
                            positions):
    """Scatter per-token dequant ``scales`` [T, n_kv] into the paged
    scale pool at the same (physical block, slot) the quantized values
    landed in — padding redirects to the null block like the values."""
    bs, nblk = scale_pool.shape[1], block_tables.shape[1]
    blk = jnp.clip(positions.astype(jnp.int32) // bs, 0, nblk - 1)
    phys = block_tables[seq_ids, blk]
    slot = jnp.where(phys == 0, 0, positions.astype(jnp.int32) % bs)
    return scale_pool.at[phys, slot].set(scales.astype(scale_pool.dtype))


def ragged_gather_attention(q, k_pool, v_pool, block_tables, seq_ids,
                            positions, *, scale, k_scale=None,
                            v_scale=None):
    """Token-packed GQA attention via the XLA-gather fallback: gather
    every sequence's whole padded context, pick each token's row, dense
    masked softmax. Semantically identical to the rpa kernel (the parity
    oracle); costs the [T, L_max] materialization the kernel removes."""
    T, n_heads, hd = q.shape
    n_kv = k_pool.shape[2]
    grp = n_heads // n_kv
    keys = gather_pool(k_pool, block_tables)   # [max_seqs+1, L, n_kv, hd]
    vals = gather_pool(v_pool, block_tables)
    kt = keys[seq_ids]                         # [T, L, n_kv, hd]
    vt = vals[seq_ids]
    if k_scale is not None:
        # int8 pools: dequantize the gathered context in f32 (the
        # scale pools page/gather identically to the value pools)
        ksc = gather_pool(k_scale, block_tables)[seq_ids]  # [T, L, n_kv]
        vsc = gather_pool(v_scale, block_tables)[seq_ids]
        kt = kt.astype(jnp.float32) * ksc[..., None]
        vt = vt.astype(jnp.float32) * vsc[..., None]
    L = kt.shape[1]
    qg = q.reshape(T, n_kv, grp, hd)
    s = jnp.einsum("tkgh,tlkh->tkgl", qg.astype(jnp.float32),
                   kt.astype(jnp.float32)) * scale
    visible = jnp.arange(L, dtype=jnp.int32)[None, :] <= \
        positions.astype(jnp.int32)[:, None]            # [T, L]
    s = jnp.where(visible[:, None, None, :], s,
                  jnp.finfo(jnp.float32).min)
    w = jax.nn.softmax(s, axis=-1).astype(vt.dtype)
    out = jnp.einsum("tkgl,tlkh->tkgh", w, vt)
    return out.reshape(T, n_heads, hd)


def ragged_paged_attention_step(q, k, v, k_pool, v_pool, block_tables,
                                cu_seqlens, context_lens, seq_ids,
                                positions, step_seq, step_blk, *,
                                scale=None, k_scale=None, v_scale=None):
    """One unified serving step over the token-packed ragged layout.

    ``q`` [T, n_heads, hd] and ``k``/``v`` [T, n_kv, hd] are the
    (already position-encoded) projections of the step's flat tokens.
    Writes the new K/V into the pools (padding to the null block), then
    dispatches the read path on :func:`paged_attention_impl`: the
    Pallas RPA kernel (page-streamed, online softmax) or the gather
    fallback. Returns ``(out [T, n_heads*hd], k_pool', v_pool')``;
    outputs at padding tokens are garbage (gather) or 0 (rpa) and must
    be discarded by the caller either way.

    With int8-KV pools (``k_scale``/``v_scale`` scale pools given), the
    new K/V are quantized per (token, head) before the scatter and the
    read path dequantizes on the fly; the return grows to
    ``(out, k_pool', v_pool', k_scale', v_scale')``. Only the gather
    path reads quantized pools (the Pallas kernel streams raw pages —
    the engine forces ``gather`` for int8 KV).
    """
    T, n_heads, hd = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    if k_scale is not None:
        kq, ks = quantize_kv_slots(k)
        vq, vs = quantize_kv_slots(v)
        k_pool = write_tokens_to_pool(k_pool, kq, block_tables, seq_ids,
                                      positions)
        v_pool = write_tokens_to_pool(v_pool, vq, block_tables, seq_ids,
                                      positions)
        k_scale = write_kv_scales_to_pool(k_scale, ks, block_tables,
                                          seq_ids, positions)
        v_scale = write_kv_scales_to_pool(v_scale, vs, block_tables,
                                          seq_ids, positions)
        out = ragged_gather_attention(
            q, k_pool, v_pool, block_tables, seq_ids, positions,
            scale=scale, k_scale=k_scale, v_scale=v_scale)
        out = out.astype(q.dtype)
        return (out.reshape(T, n_heads * hd), k_pool, v_pool,
                k_scale, v_scale)
    k_pool = write_tokens_to_pool(k_pool, k, block_tables, seq_ids,
                                  positions)
    v_pool = write_tokens_to_pool(v_pool, v, block_tables, seq_ids,
                                  positions)
    if paged_attention_impl() == "rpa":
        from paddle_tpu.ops.pallas.ragged_paged_attention import \
            ragged_paged_attention
        tp = _tp_mesh()
        if tp is not None:
            # SPMD over the kernel's head dimension (ISSUE 15): Pallas
            # is opaque to GSPMD, so shard_map runs one kernel instance
            # per mp shard — q over n_heads, pools over n_kv (whole GQA
            # groups stay together because n_heads/n_kv shard by the
            # same factor), metadata replicated. Attention is
            # embarrassingly parallel across heads: no collective is
            # introduced here (the o_proj psum stays GSPMD's).
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            mesh, ax = tp
            heads = P(None, ax, None)
            pools = P(None, None, ax, None)
            rep = P()
            out = shard_map(
                lambda qa, kp, vp, bt, cu, ctx, ssq, sbk:
                    ragged_paged_attention(qa, kp, vp, bt, cu, ctx,
                                           ssq, sbk, sm_scale=scale),
                mesh=mesh,
                in_specs=(heads, pools, pools, rep, rep, rep, rep, rep),
                out_specs=heads, check_rep=False)(
                q, k_pool, v_pool, block_tables, cu_seqlens,
                context_lens, step_seq, step_blk)
        else:
            out = ragged_paged_attention(
                q, k_pool, v_pool, block_tables, cu_seqlens,
                context_lens, step_seq, step_blk, sm_scale=scale)
    else:
        out = ragged_gather_attention(
            q, k_pool, v_pool, block_tables, seq_ids, positions,
            scale=scale)
    return out.reshape(T, n_heads * hd), k_pool, v_pool
