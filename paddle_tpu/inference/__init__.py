"""paddle.inference parity — Config / create_predictor / Predictor.

Reference: ``python/paddle/inference/__init__.py`` binding
``paddle/fluid/inference/api/analysis_predictor.cc`` (AnalysisPredictor:
load saved program + params, run analysis passes, execute). TPU shape:
the saved artifact is already a compiled-serialized XLA program
(``jit.save`` StableHLO export), so "analysis passes + engine" collapse
into XLA AOT — the Predictor deserializes, places weights, and runs the
executable, keeping the reference's handle-based zero-copy API
(input/output handles are device arrays; ``copy_from_cpu`` is the H2D
boundary).
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

__all__ = ["Config", "create_predictor", "Predictor", "Tensor",
           "PrecisionType", "PlaceType"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    TPU = 1


class Config:
    """paddle.inference.Config parity (api/paddle_analysis_config.h
    surface, TPU-relevant subset)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._device = "tpu"
        self.set_model(prog_file, params_file)
        self._enable_memory_optim = True
        self._switch_ir_optim = True  # XLA owns optimization; kept for API

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        # only the model paths change; configured options stay (reference
        # AnalysisConfig::SetModel semantics)
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._model_dir = prog_file
        self._params_file = params_file

    def model_dir(self):
        return self._model_dir

    def enable_memory_optim(self, flag: bool = True):
        self._enable_memory_optim = flag

    def switch_ir_optim(self, flag: bool = True):
        self._switch_ir_optim = flag

    def enable_xpu(self, *a, **k):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def summary(self) -> str:
        return (f"Config(model={self._model_dir}, device={self._device}, "
                f"memory_optim={self._enable_memory_optim})")


class Tensor:
    """Predictor IO handle (reference: ``paddle_infer::Tensor`` —
    zero-copy views into executor memory)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        assert self._is_input, "copy_from_cpu on an output handle"
        self._owner._feed[self.name] = np.ascontiguousarray(arr)

    def reshape(self, shape):
        pass  # shapes are static in the exported XLA program

    def copy_to_cpu(self) -> np.ndarray:
        assert not self._is_input, "copy_to_cpu on an input handle"
        return np.asarray(self._owner._fetch[self.name])

    def shape(self):
        if self._is_input:
            a = self._owner._feed.get(self.name)
            return list(a.shape) if a is not None else None
        return list(np.asarray(self._owner._fetch[self.name]).shape)


class Predictor:
    """Runs a ``jit.save`` artifact (reference AnalysisPredictor::Run)."""

    def __init__(self, config: Config):
        import paddle_tpu as pt

        self._config = config
        path = config.model_dir()
        if path is None or not os.path.exists(path + ".pdmodel"):
            raise FileNotFoundError(
                f"no inference model at {path}.pdmodel; export one with "
                "paddle_tpu.jit.save(layer, path, input_spec=...)")
        self._layer = pt.jit.load(path)
        n_in = len(self._layer._exported.in_avals)
        self._input_names = [f"x{i}" for i in range(n_in)]
        self._feed = {}
        self._fetch = {}
        self._output_names: List[str] = []

    # -- handle API (reference: get_input_handle/get_output_handle) ----------
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return Tensor(name, self, is_input=True)

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, is_input=False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute. Either pass arrays positionally (newer paddle
        ``predictor.run([x])``) or pre-fill input handles."""
        if inputs is not None:
            if len(inputs) != len(self._input_names):
                raise ValueError(
                    f"model expects {len(self._input_names)} inputs, got "
                    f"{len(inputs)}")
            for name, arr in zip(self._input_names, inputs):
                self._feed[name] = np.ascontiguousarray(arr)
        missing = [n for n in self._input_names if n not in self._feed]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        args = [self._feed[n] for n in self._input_names]
        out = self._layer._exported.call(*args)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._fetch = dict(zip(self._output_names, outs))
        return [np.asarray(o) for o in outs]

    def try_shrink_memory(self):
        pass

    def clear_intermediate_tensor(self):
        self._feed.clear()
        self._fetch.clear()


def create_predictor(config: Config) -> Predictor:
    """paddle.inference.create_predictor parity."""
    return Predictor(config)
