"""Live elastic resharding — a membership change is a *resize*, not a
restart.

The classic recovery story for a mesh-membership change (host preempted,
capacity granted back) is kill → checkpoint-reshard on disk → relaunch:
every rank pays a full checkpoint round trip through the filesystem plus
process death and rebirth. This module fuses the pieces the repo already
has — cross-mesh bit-identical shard assembly (PR 3,
``checkpoint/reshard.py``), the consensus stop-step protocol (PR 4,
``resilience/preemption.py``), exactly-once data state (PR 5,
``data/pipeline.py``) and the goodput/heartbeat observability (PR 13) —
into an in-place resize, the membership-change discipline Pathways-style
single-controller and MegaScale-style fault-tolerant training loops ride
preemptions with (PAPERS.md):

1. **Notice** — a scale-down arrives through the preemption seam; a
   scale-up (or operator-driven downsize) through the elastic seam:
   ``PADDLE_TPU_ELASTIC_RESIZE=<new_world>`` (env),
   ``PADDLE_TPU_ELASTIC_RESIZE_FILE`` (a file whose *content* is the
   target world size), or the job-store key ``__elastic/…/target``.
2. **Consensus boundary** — the PR 4 claim pattern under ``__elastic``
   keys: the first rank to observe the notice wins ``store.add`` and
   publishes ``stop_at = its step + 1``; every rank steps to exactly
   that boundary, so the exchange sees ONE coherent state.
3. **In-memory exchange** — *no filesystem*: each old rank snapshots
   model+opt to host (``checkpoint.writer.snapshot``), publishes the
   shards it owns (the writer's ``plan_grid`` / ``owner = flat_pos %
   world`` rule, raw bytes + crc32) onto the job TCPStore; every
   new-world rank assembles full tensors through
   ``checkpoint.reshard.assemble_from`` — literally the same offset-
   pasting loop the file path runs, so the result is bit-identical to a
   checkpoint-reshard **by construction**. (The store transport is the
   CPU/test path; an all-gather over the accelerator fabric slots into
   the same ``fetch`` seam as the TPU follow-up.)
4. **Data remap** — old ranks publish their ``DataPipeline`` states;
   every new rank folds them through
   ``DataPipeline.reshard_state(states, new_world)`` (global sample
   order and packer carry preserved — exactly-once ledger digests
   unchanged) and loads its own remapped shard.
5. **Continue / depart / join** — survivors rebuild mesh/TrainStep and
   keep training; departing ranks retire their heartbeat lane
   (``fleet.depart`` → status ``departed``, never ``missing``) and exit
   :data:`RESIZE_EXIT_CODE` (83) — the launcher classifies that as a
   planned resize (``reshard`` goodput bin via
   ``PADDLE_TPU_GOODPUT_RESIZE_AT``), not a crash; joining ranks sync
   state from the same store keys a live peer published.

Rank mapping is deterministic: old ranks ``0..new_world-1`` survive (and
keep their index), old ranks ``>= new_world`` depart; at a scale-up new
ranks ``old_world..new_world-1`` join.
"""
from __future__ import annotations

import os
import pickle
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["RESIZE_EXIT_CODE", "ElasticResizeListener",
           "publish_state", "collect_state", "exchange_reshard",
           "publish_data_state", "collect_data_states", "perform_resize",
           "elastic_prefix"]

#: Exit status meaning "left the job at a consensus resize boundary; the
#: surviving ranks carry the full state". 83 sits next to (but distinct
#: from) the preemption contract's 79 — the launcher must NOT relaunch
#: this rank, just shrink the world and keep the survivors running.
RESIZE_EXIT_CODE = 83

STORE_KEY = "__elastic"

NOTICE_ENV = "PADDLE_TPU_ELASTIC_RESIZE"
NOTICE_FILE_ENV = "PADDLE_TPU_ELASTIC_RESIZE_FILE"


def _epoch() -> str:
    return os.environ.get("PADDLE_RESTART_EPOCH", "0")


def elastic_prefix(gen: int, epoch: Optional[str] = None) -> str:
    """Store-key prefix for resize generation ``gen`` — namespaced by the
    launcher restart epoch (like ``__preempt``) so a relaunched attempt
    never consumes a previous attempt's stale verdict, and by ``gen`` so
    several in-place resizes within one incarnation stay disjoint."""
    return f"{STORE_KEY}/{epoch if epoch is not None else _epoch()}/g{gen}"


class ElasticResizeListener:
    """Consensus resize observer — the PR 4 stop-step protocol pointed at
    membership changes. Poll :meth:`should_resize` at step boundaries;
    it returns True for every rank at the SAME step, after which
    :attr:`target_world` holds the agreed new world size.

    Channels: ``PADDLE_TPU_ELASTIC_RESIZE=<M>`` (env), a notice file
    whose content is ``<M>`` (``PADDLE_TPU_ELASTIC_RESIZE_FILE``), the
    store key ``{prefix}/target`` (operator/launcher seam), or the
    programmatic :meth:`request`. Without a job store a locally observed
    notice resizes at the next boundary (single-process drills).
    """

    def __init__(self, store=None, notice_file: Optional[str] = None,
                 check_interval: float = 0.0):
        self._store = store
        self._store_failed = False
        self._notice_file = notice_file
        self._check_interval = float(check_interval)
        self._last_poll = 0.0
        self._flagged = False
        self._broadcast_done = False
        self._decided = False
        self.target_world: Optional[int] = None
        self.reason: Optional[str] = None
        self.boundary_step: Optional[int] = None
        self.generation = 0

    # -- channels ----------------------------------------------------------
    def request(self, new_world: int, reason: str = "request"):
        """Programmatic resize notice (chaos drills, operator tooling)."""
        if not self._flagged:
            self._flagged = True
            self.target_world = int(new_world)
            self.reason = reason

    def _poll_notice(self):
        raw = os.environ.get(NOTICE_ENV, "").strip()
        if raw and raw != "0":
            try:
                self.request(int(raw), "notice_env")
            except ValueError:
                pass
        path = self._notice_file or os.environ.get(NOTICE_FILE_ENV)
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self.request(int(f.read().strip()), "notice_file")
            except (OSError, ValueError):
                pass

    def _job_store(self):
        if self._store is not None or self._store_failed:
            return self._store
        if not os.environ.get("PADDLE_MASTER"):
            self._store_failed = True
            return None
        try:
            from paddle_tpu.distributed.tcp_store import job_store
            self._store = job_store()
        except Exception:
            self._store_failed = True
        return self._store

    def _gen_key(self) -> str:
        return f"{STORE_KEY}/{_epoch()}/gen"

    def _refresh_generation(self, store) -> str:
        try:
            raw = store.get(self._gen_key())
            self.generation = int(raw) if raw else 0
        except Exception:
            pass
        return elastic_prefix(self.generation)

    # -- the step-boundary query ------------------------------------------
    def should_resize(self, step: Optional[int] = None) -> bool:
        """True once the cluster-agreed resize boundary is reached — all
        ranks return True at the SAME step (see PreemptionListener: the
        first observer claims ``{prefix}/armed`` and publishes
        ``stop_at:new_world:reason`` at ``{prefix}/stop``)."""
        if self._decided:
            return True
        now = time.monotonic()
        if now - self._last_poll >= self._check_interval:
            self._last_poll = now
            self._poll_notice()
        store = self._job_store()
        if store is None:
            if self._flagged:
                self._decided = True
                self.boundary_step = step
            return self._decided
        try:
            prefix = self._refresh_generation(store)
            if not self._flagged:
                raw = store.get(f"{prefix}/target")
                if raw:
                    t, _, r = raw.decode(
                        errors="replace").partition(":")
                    try:
                        self.request(int(t), f"store:{r or 'target'}")
                    except ValueError:
                        pass
            if self._flagged and not self._broadcast_done:
                if int(store.add(f"{prefix}/armed", 1)) == 1:
                    stop_at = 0 if step is None else int(step) + 1
                    store.set(
                        f"{prefix}/stop",
                        f"{stop_at}:{self.target_world}:"
                        f"{self.reason or '?'}".encode())
                self._broadcast_done = True
            v = store.get(f"{prefix}/stop")
            if v is None:
                return False
            stop_s, _, rest = v.decode(errors="replace").partition(":")
            world_s, _, reason = rest.partition(":")
            if not self._flagged:
                self._flagged = True
                self.reason = f"store:{reason}"
            self.target_world = int(world_s)
            stop_at = int(stop_s)
            if step is None or stop_at == 0 or int(step) >= stop_at:
                self._decided = True
                self.boundary_step = stop_at if stop_at else step
            return self._decided
        except Exception:
            # control-plane death must never kill the training step
            self._store_failed = True
            if self._flagged:
                self._decided = True
                self.boundary_step = step
            return self._decided

    @property
    def resize_pending(self) -> bool:
        return self._flagged

    def reset(self):
        """Re-arm for the next resize (survivors call this after a
        completed in-place resize; the store generation was bumped so
        stale verdict keys are never re-read)."""
        self._flagged = False
        self._broadcast_done = False
        self._decided = False
        self.target_world = None
        self.reason = None
        self.boundary_step = None


# ---------------------------------------------------------------------------
# In-memory model+opt exchange over the job store — zero filesystem I/O.
# ---------------------------------------------------------------------------

def _shard_key(prefix: str, key: str, flat_pos: int) -> str:
    return f"{prefix}/t/{key}/{flat_pos:03d}"


def publish_state(store, prefix: str, state, world: int, rank: int,
                  nshards: Optional[int] = None) -> dict:
    """Host-snapshot ``state`` and publish this rank's owned shards.

    Mirrors ``checkpoint.writer.write_step`` exactly — same
    ``plan_grid``, same ``owner = flat_pos % world``, same raw C-order
    bytes + crc32 — except the bytes land on the job store instead of a
    step directory, so assembly is bit-identical to the file path. Rank
    0 additionally publishes the pickled manifest + state skeleton.
    Returns the manifest (every rank computes an identical one).
    """
    from paddle_tpu.checkpoint.layout import (crc32_of, iter_shards,
                                              plan_grid)
    from paddle_tpu.checkpoint.writer import snapshot

    nshards = max(int(nshards if nshards is not None else world), 1)
    snap = snapshot(state)
    tensors: Dict[str, dict] = {}
    for key in sorted(snap.tensors):
        arr, ref = snap.tensors[key]
        grid = plan_grid(arr.shape, nshards)
        entry = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                 "grid": grid, "kind": ref.kind, "shards": []}
        for flat_pos, offset, shard_shape, slices in iter_shards(
                arr.shape, grid):
            owner = flat_pos % world
            rec = {"offset": offset, "shape": shard_shape, "owner": owner,
                   "store_key": _shard_key(prefix, key, flat_pos)}
            if owner == rank:
                data = np.asarray(arr[slices]).tobytes()
                rec["crc32"] = crc32_of(data)
                rec["nbytes"] = len(data)
                store.set(rec["store_key"], data)
            entry["shards"].append(rec)
        tensors[key] = entry
    manifest = {"tensors": tensors, "world": int(world),
                "aux_crc": None}
    if rank == 0:
        manifest["aux_crc"] = crc32_of(snap.skeleton_bytes)
        store.set(f"{prefix}/aux", snap.skeleton_bytes)
        store.set(f"{prefix}/manifest", pickle.dumps(manifest, protocol=4))
    store.set(f"{prefix}/published/{rank}", b"1")
    return manifest


def collect_state(store, prefix: str, verify: bool = True, mesh=None,
                  timeout: Optional[float] = None):
    """Assemble the full state tree from a :func:`publish_state` round.

    Every shard's bytes are pulled through ``store.wait`` and pasted by
    ``checkpoint.reshard.assemble_from`` — the exact code path the
    checkpoint-file reshard runs, crc-verified against the manifest.
    With ``mesh``, tensors are placed onto it (``place_on_mesh``), the
    same largest-divisible-dim rule as the restore path.
    """
    from paddle_tpu.checkpoint.layout import (CheckpointIntegrityError,
                                              crc32_of, unflatten_state)
    from paddle_tpu.checkpoint.reshard import assemble_from, place_on_mesh

    manifest = pickle.loads(store.wait(f"{prefix}/manifest", timeout))
    skel_bytes = store.wait(f"{prefix}/aux", timeout)
    if verify and manifest.get("aux_crc") is not None and \
            crc32_of(skel_bytes) != manifest["aux_crc"]:
        raise CheckpointIntegrityError(
            "checksum mismatch on exchanged state skeleton")
    skeleton = pickle.loads(skel_bytes)

    def fetch(rec):
        return store.wait(rec["store_key"], timeout)

    arrays: Dict[str, np.ndarray] = {}
    for key, entry in manifest["tensors"].items():
        full = assemble_from(entry, fetch, verify=verify)
        if mesh is not None and entry.get("kind") != "ndarray":
            full = place_on_mesh(full, mesh)
        arrays[key] = full
    return unflatten_state(skeleton, arrays)


def exchange_reshard(store, prefix: str, state, world: int, rank: int,
                     new_world: int, verify: bool = True, mesh=None,
                     timeout: Optional[float] = None):
    """One full in-memory reshard round for one rank: publish this
    rank's shards, then (ranks surviving into the new world) assemble
    the full state. Departing ranks (``rank >= new_world``) return None
    after publishing — their shards are on the store, so they may exit.
    """
    publish_state(store, prefix, state, world, rank)
    if rank >= int(new_world):
        return None
    return collect_state(store, prefix, verify=verify, mesh=mesh,
                         timeout=timeout)


# ---------------------------------------------------------------------------
# Data-state exchange + remap.
# ---------------------------------------------------------------------------

def publish_data_state(store, prefix: str, data_state: dict, rank: int):
    """Publish one rank's ``DataPipeline.state_dict()`` (pickled — it
    carries numpy pending batches)."""
    store.set(f"{prefix}/data/{rank}",
              pickle.dumps(data_state, protocol=4))


def collect_data_states(store, prefix: str, world: int,
                        timeout: Optional[float] = None) -> List[dict]:
    """Gather every old rank's published pipeline state."""
    return [pickle.loads(store.wait(f"{prefix}/data/{r}", timeout))
            for r in range(int(world))]


# ---------------------------------------------------------------------------
# Orchestration.
# ---------------------------------------------------------------------------

def perform_resize(store, *, state, data_state: Optional[dict],
                   world: int, rank: int, new_world: int,
                   generation: Optional[int] = None,
                   mesh=None, verify: bool = True,
                   pad_id: int = 0, ignore_label: int = -100,
                   boundary_step: Optional[int] = None,
                   timeout: Optional[float] = None):
    """Run one rank's side of a consensus resize, end to end:

    publish model+opt shards and the data state → barrier on every old
    rank having published → departing ranks retire their heartbeat lane
    and return ``(None, None)`` (caller exits :data:`RESIZE_EXIT_CODE`);
    surviving ranks assemble the new-mesh state, remap the data order,
    bump the store generation (rank 0), record the resize wall into the
    goodput ``reshard`` bin and an ``elastic`` trace span, and return
    ``(state, data_state)`` for the caller to apply and continue with.

    No filesystem I/O happens anywhere on this path.
    """
    t0 = time.perf_counter()
    gen = generation if generation is not None else 0
    prefix = elastic_prefix(gen)
    world, new_world = int(world), int(new_world)

    publish_state(store, prefix, state, world, rank)
    if data_state is not None:
        publish_data_state(store, prefix, data_state, rank)
    # barrier: survivors must not assemble until every old rank (the
    # departing ones included — they own shards) has published
    for r in range(world):
        store.wait(f"{prefix}/published/{r}", timeout)

    departing = rank >= new_world
    if departing:
        try:
            from paddle_tpu.observability import fleet
            fleet.depart(int(boundary_step or 0), reason="resize")
        except Exception:
            pass
        return None, None

    new_state = collect_state(store, prefix, verify=verify, mesh=mesh,
                              timeout=timeout)
    new_data = None
    if data_state is not None:
        from paddle_tpu.data.pipeline import DataPipeline
        states = collect_data_states(store, prefix, world, timeout)
        new_data = DataPipeline.reshard_state(
            states, new_world, pad_id=pad_id,
            ignore_label=ignore_label)[rank]

    if rank == 0:
        # open the next generation so a later resize never re-reads
        # this round's verdict/shard keys
        try:
            store.set(f"{STORE_KEY}/{_epoch()}/gen", str(gen + 1).encode())
        except Exception:
            pass

    dt = time.perf_counter() - t0
    try:
        from paddle_tpu.observability import goodput, trace
        goodput.get_ledger().record("reshard", dt)
        now = time.perf_counter_ns()
        trace.span("elastic", f"elastic_resize_{world}to{new_world}",
                   now - int(dt * 1e9), now,
                   args={"world": world, "new_world": new_world,
                         "step": boundary_step, "reshard_s": round(dt, 6)})
    except Exception:
        pass
    return new_state, new_data
