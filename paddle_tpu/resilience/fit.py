"""FitResilience — the fault-tolerance layer's hapi front door.

One callback that composes the four resilience pieces around
``Model.fit`` (each is also usable standalone):

* **step checkpointing + resume** — an owned (or provided)
  :class:`~paddle_tpu.checkpoint.CheckpointManager`; ``save_every_steps``
  commits model+optimizer atomically as ONE step id (async — the loop
  pays only the snapshot); :meth:`restore` resumes from ``latest_step``
  on relaunch and keeps the global-step numbering monotonic.
* **preemption** — a :class:`~.preemption.PreemptionListener`
  (SIGTERM/SIGUSR1 + maintenance-notice seam + TCPStore broadcast).
  When it trips, every rank finishes the in-flight step, takes one final
  *blocking* synchronized save, and ``fit`` returns with
  ``exit_code == RESUMABLE_EXIT_CODE`` — call :meth:`exit_if_preempted`
  (or read ``.exit_code``) in the trainer script so the elastic launcher
  restarts from the committed step instead of counting a crash.
* **watchdog** — arms ``step_timeout`` around each train step and
  ``collective_timeout`` around every traced collective; escalation via
  ``watchdog_action`` (log → dump → kill).
* **NaN guard** — loss/grad finiteness + spike window with
  rollback-to-last-commit (see :class:`~.nan_guard.NaNGuard`).

Chaos seams (``PADDLE_TPU_CHAOS_*``) are refreshed on ``on_train_begin``
so launched workers pick up their injected faults.
"""
from __future__ import annotations

import sys
from typing import Optional

from paddle_tpu.hapi.model import Callback

from .nan_guard import NaNGuard, apply_restored_state
from .preemption import RESUMABLE_EXIT_CODE, PreemptionListener
from .watchdog import Watchdog

__all__ = ["FitResilience"]


class FitResilience(Callback):
    def __init__(self, checkpoint_dir: Optional[str] = None, manager=None,
                 save_every_steps: Optional[int] = None,
                 keep_last_k: Optional[int] = 3,
                 preemption: bool = True, listener=None,
                 step_timeout: Optional[float] = None,
                 collective_timeout: Optional[float] = None,
                 watchdog_action: str = "dump",
                 nan_guard: bool = False, max_rollbacks: int = 3,
                 spike_window: int = 0, spike_factor: float = 10.0,
                 registry=None, pipeline=None,
                 elastic: bool = False, elastic_listener=None):
        """``pipeline``: a ``paddle_tpu.data.DataPipeline`` (or anything
        with ``state_dict``/``load_state_dict``) whose iterator state is
        committed under the ``"data"`` key of EVERY save — atomically in
        the same checkpoint step as model+optimizer — and restored by
        :meth:`restore`, so a relaunch resumes the exact sample order
        (exactly-once data, docs/DATA.md). NaN-guard rollbacks restore
        weights only: the data stream keeps moving forward (replaying
        consumed batches into a rolled-back model would double-train
        them; see docs/RESILIENCE.md)."""
        if manager is None and checkpoint_dir is not None:
            from paddle_tpu.checkpoint import CheckpointManager
            manager = CheckpointManager(checkpoint_dir,
                                        keep_last_k=keep_last_k,
                                        registry=registry)
        self.manager = manager
        self.save_every_steps = save_every_steps
        self._want_preemption = preemption
        self.listener = listener
        self.watchdog: Optional[Watchdog] = None
        self._step_timeout = step_timeout
        self._collective_timeout = collective_timeout
        self._watchdog_action = watchdog_action
        self.nan_guard: Optional[NaNGuard] = None
        if nan_guard:
            self.nan_guard = NaNGuard(manager=self.manager,
                                      max_rollbacks=max_rollbacks,
                                      spike_window=spike_window,
                                      spike_factor=spike_factor,
                                      registry=registry)
        self._registry = registry
        self.pipeline = pipeline
        self._want_elastic = elastic
        self.elastic_listener = elastic_listener
        self.preempted = False
        self.resized = False
        self.resize_target: Optional[int] = None
        self.resize_boundary_step: Optional[int] = None
        self.final_step: Optional[int] = None
        self._step0 = 0          # global-step offset after a resume
        self._cur_step = 0
        self._wd_token = None
        self._installed_listener = False

    # -- resume ------------------------------------------------------------
    def restore(self, model) -> Optional[int]:
        """Resume ``model`` (network + optimizer) from the manager's
        latest committed step; returns the step or None. Call before
        ``fit`` in a relaunched trainer. Global-step numbering continues
        from the restored step, so subsequent saves never collide with a
        *different* committed step's id."""
        if self.manager is None or self.manager.latest_step() is None:
            return None
        state = self.manager.restore()
        apply_restored_state(model, state)
        if self.pipeline is not None and isinstance(state, dict) and \
                "data" in state:
            # same committed step as model+opt: the restored iterator
            # resumes at exactly the batch after the last trained one
            self.pipeline.load_state_dict(state["data"])
        if isinstance(state, dict) and "numerics" in state:
            # resume the calibration sketches where the previous
            # incarnation left them (merge: sketches are additive)
            from paddle_tpu.observability import numerics
            try:
                numerics.get_observatory().load_summary(state["numerics"])
            except Exception:
                pass  # calibration is telemetry; never block a resume
        restored = self.manager.last_restored_step
        meta = self.manager.metadata(restored)
        self._step0 = int(meta.get("global_step", restored))
        return restored

    @property
    def global_step(self) -> int:
        return self._cur_step

    # -- hooks -------------------------------------------------------------
    def set_model(self, model):
        super().set_model(model)
        if self.nan_guard is not None:
            self.nan_guard.set_model(model)

    def on_train_begin(self, logs=None):
        from . import chaos
        if chaos.enabled():
            chaos.refresh()
        if self._want_preemption and self.listener is None:
            self.listener = PreemptionListener(registry=self._registry)
        if self._want_elastic and self.elastic_listener is None:
            from .elastic import ElasticResizeListener
            self.elastic_listener = ElasticResizeListener()
        if self.listener is not None and not self._installed_listener:
            self.listener.install()
            self._installed_listener = True
        if self._step_timeout is not None or \
                self._collective_timeout is not None:
            self.watchdog = Watchdog(
                default_timeout=self._step_timeout or 300.0,
                action=self._watchdog_action, registry=self._registry)
            if self._collective_timeout is not None:
                self.watchdog.watch_collectives(self._collective_timeout)

    def on_train_batch_begin(self, step, logs=None):
        self._cur_step = self._step0 + step
        if self.watchdog is not None and self._step_timeout is not None:
            self._wd_token = self.watchdog.arm(
                "train_step", self._step_timeout, step=self._cur_step)

    def on_train_batch_end(self, step, logs=None):
        if self._wd_token is not None:
            self.watchdog.disarm(self._wd_token)
            self._wd_token = None
        gs = self._cur_step
        if self.nan_guard is not None:
            logs = logs or {}
            self.nan_guard.check(gs, logs.get("loss"),
                                 logs.get("grad_norm"))
        if self.manager is not None and self.save_every_steps and \
                gs % self.save_every_steps == 0:
            self.manager.save(gs, self._state(),
                              metadata={"global_step": gs},
                              overwrite=True)
        if self.listener is not None and not self.preempted and \
                self.listener.should_stop(step=gs):
            self._final_save(gs)
        if self.elastic_listener is not None and not self.preempted and \
                not self.resized and \
                self.elastic_listener.should_resize(step=gs):
            self._resize_stop(gs)

    def on_train_end(self, logs=None):
        if self.manager is not None:
            self.manager.wait_all()
        if self.watchdog is not None:
            self.watchdog.stop()
        if self._installed_listener:
            self.listener.uninstall()
            self._installed_listener = False

    # -- preemption stop ---------------------------------------------------
    def _state(self) -> dict:
        state = {"model": self.model.network.state_dict()}
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and hasattr(opt, "state_dict"):
            state["optimizer"] = opt.state_dict()
        if self.pipeline is not None:
            state["data"] = self.pipeline.state_dict()
        from paddle_tpu.observability import numerics
        if numerics.armed():
            # calibration aux state (docs/OBSERVABILITY.md#numerics):
            # per-tap activation-range sketches accumulated over every
            # instrumented sample — committed with the weights so a
            # resumed run (see restore()) keeps accumulating, and the
            # quantized-serving calibration pass reads them offline.
            # apply_restored_state ignores unknown keys, so rollback
            # paths are untouched.
            summary = numerics.get_observatory().calibration_summary()
            if summary["taps"]:
                state["numerics"] = summary
        return state

    def _final_save(self, gs: int):
        """The preemption commit: blocking (the process is about to exit —
        an async save could be torn by the platform's hard kill), rank-
        synchronized by the writer's commit barrier, overwriting a
        periodic save of the same id if one landed this step."""
        self.preempted = True
        self.final_step = gs
        if self.manager is not None:
            self.manager.save(
                gs, self._state(), async_=False, overwrite=True,
                metadata={"global_step": gs, "preempted": True,
                          "reason": getattr(self.listener, "reason", None)})
        self.model._stop_training = True

    def _resize_stop(self, gs: int):
        """The elastic boundary: the cluster agreed to resize at this
        step, so break out of fit WITHOUT a checkpoint — the state stays
        live in memory and ``elastic.perform_resize`` reshards it over
        the store (the whole point: no filesystem round trip). Survivors
        refit after the in-place resize; departing ranks exit
        :data:`~.elastic.RESIZE_EXIT_CODE`."""
        self.resized = True
        self.resize_target = self.elastic_listener.target_world
        self.resize_boundary_step = gs
        try:
            from paddle_tpu.observability import trace
            trace.mark("elastic", "resize_boundary",
                       args={"step": gs, "target": self.resize_target,
                             "reason": self.elastic_listener.reason})
        except Exception:
            pass
        self.model._stop_training = True

    @property
    def exit_code(self) -> int:
        return RESUMABLE_EXIT_CODE if self.preempted else 0

    def exit_if_preempted(self):
        """Trainer-script epilogue: exit with the launcher's resumable
        contract when fit stopped on a preemption."""
        if self.preempted:
            sys.exit(RESUMABLE_EXIT_CODE)
