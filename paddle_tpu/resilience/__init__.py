"""Fault-tolerance layer (docs/RESILIENCE.md).

Four cooperating pieces, each usable standalone and composed by
:class:`FitResilience` for ``Model.fit``:

* :mod:`~paddle_tpu.resilience.preemption` — SIGTERM/notice listener,
  coordinated final checkpoint, :data:`RESUMABLE_EXIT_CODE` contract
  with the elastic launcher.
* :mod:`~paddle_tpu.resilience.watchdog` — monotonic-deadline hang
  watchdog over train steps and traced collectives, with postmortem
  dumps and a log → dump → kill escalation ladder.
* :mod:`~paddle_tpu.resilience.nan_guard` — numeric guard with
  rollback-to-last-committed-checkpoint.
* :mod:`~paddle_tpu.resilience.chaos` — env-driven fault injection
  (kill-at-step, hang-collective, poison-batch, corrupt-loss) proving
  mean-time-to-recovery end to end.
* :mod:`~paddle_tpu.resilience.elastic` — live elastic resharding: a
  membership change is an in-place *resize* (consensus boundary +
  in-memory shard exchange + data-order remap), not a restart;
  departing ranks exit :data:`RESIZE_EXIT_CODE`.
"""
from .counters import record_nonfinite  # noqa: F401
from .preemption import RESUMABLE_EXIT_CODE, PreemptionListener  # noqa: F401
from .elastic import RESIZE_EXIT_CODE, ElasticResizeListener  # noqa: F401
from .watchdog import Watchdog, WatchdogExpired  # noqa: F401
from .nan_guard import NaNGuard, NumericError  # noqa: F401
from .fit import FitResilience  # noqa: F401
from . import chaos  # noqa: F401

__all__ = ["RESUMABLE_EXIT_CODE", "PreemptionListener",
           "RESIZE_EXIT_CODE", "ElasticResizeListener", "Watchdog",
           "WatchdogExpired", "NaNGuard", "NumericError", "FitResilience",
           "record_nonfinite", "chaos"]
