"""Chaos-injection harness — env-driven fault seams for proving recovery.

Every fault the resilience layer claims to survive can be *induced* here,
so multiprocess integration tests and ``bench.py --chaos`` measure real
mean-time-to-recovery instead of trusting unit tests. All seams are env
vars (they must cross the launcher's ``subprocess`` boundary) and cost one
dict lookup per step when unset:

* ``PADDLE_TPU_CHAOS_KILL_AT_STEP=N`` — SIGKILL this process right after
  fit step ``N`` completes (simulates a hard preemption / host loss).
* ``PADDLE_TPU_CHAOS_HANG_COLLECTIVE=op[:seconds]`` — the first traced
  collective whose op name matches sleeps ``seconds`` (default 3600)
  inside its comm span (simulates a wedged all-reduce; the watchdog's
  collective deadline should fire first).
* ``PADDLE_TPU_CHAOS_POISON_BATCH=N[,N...]`` — NaN-fill the input batch
  of those fit steps (simulates a corrupt shard reaching the device).
* ``PADDLE_TPU_CHAOS_CORRUPT_LOSS=N[,N...]`` — replace those steps'
  losses with NaN after the train step (simulates a bf16 blow-up).
* ``PADDLE_TPU_CHAOS_MARK_DIR=/path`` — fire each event at most once per
  *job*: a marker file is written before the fault fires, so the
  relaunched worker that replays the same step numbers does not re-die.

Step numbers are the fit loop's 1-based batch counter. ``refresh()``
re-reads the env (tests mutate ``os.environ`` in-process); the hapi fit
loop calls it automatically when any ``PADDLE_TPU_CHAOS_*`` var is set.
"""
from __future__ import annotations

import os
import signal
import sys
import time
from typing import Optional

import numpy as np

__all__ = ["refresh", "enabled", "kill_at_step", "poison_batch",
           "corrupt_loss", "active_config"]

ENV_KILL = "PADDLE_TPU_CHAOS_KILL_AT_STEP"
ENV_HANG = "PADDLE_TPU_CHAOS_HANG_COLLECTIVE"
ENV_POISON = "PADDLE_TPU_CHAOS_POISON_BATCH"
ENV_CORRUPT = "PADDLE_TPU_CHAOS_CORRUPT_LOSS"
ENV_MARK_DIR = "PADDLE_TPU_CHAOS_MARK_DIR"

_cfg: dict = {"kill": None, "hang": None, "poison": frozenset(),
              "corrupt": frozenset(), "mark_dir": None}


def _steps(val: Optional[str]) -> frozenset:
    if not val:
        return frozenset()
    return frozenset(int(s) for s in val.split(",") if s.strip())


def refresh() -> dict:
    """Re-read the chaos env; (un)install the collective hang hook."""
    _poison_loss_steps.clear()
    env = os.environ
    kill = env.get(ENV_KILL)
    _cfg["kill"] = int(kill) if kill else None
    _cfg["poison"] = _steps(env.get(ENV_POISON))
    _cfg["corrupt"] = _steps(env.get(ENV_CORRUPT))
    _cfg["mark_dir"] = env.get(ENV_MARK_DIR) or None
    hang = env.get(ENV_HANG)
    if hang:
        op, _, secs = hang.partition(":")
        _cfg["hang"] = (op, float(secs) if secs else 3600.0)
    else:
        _cfg["hang"] = None
    from paddle_tpu.observability import comm
    comm._chaos_hook = _hang_hook if _cfg["hang"] else None
    return dict(_cfg)


def active_config() -> dict:
    return dict(_cfg)


def enabled() -> bool:
    return any(k.startswith("PADDLE_TPU_CHAOS_") and v
               for k, v in os.environ.items())


def _fire_once(event: str) -> bool:
    """True if ``event`` should fire now; with a mark dir, each event
    fires at most once per job (the marker survives the process)."""
    d = _cfg["mark_dir"]
    if d is None:
        return True
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"chaos_{event}")
    if os.path.exists(path):
        return False
    with open(path, "w") as f:
        f.write(f"{os.getpid()} {time.time()}\n")
        f.flush()
        os.fsync(f.fileno())
    return True


# -- seams (called from the fit loop / comm_scope) -------------------------

def kill_at_step(step: int):
    """SIGKILL — no atexit, no finally, no flushed buffers: exactly what a
    preempted host looks like to the launcher."""
    if _cfg["kill"] is not None and step == _cfg["kill"] \
            and _fire_once(f"kill_step{step}"):
        print(f"[chaos] SIGKILL at step {step}", file=sys.stderr, flush=True)
        os.kill(os.getpid(), signal.SIGKILL)


def poison_batch(step: int, x):
    """NaN-fill float leaves of the batch for a poisoned step. Packed-
    pipeline batches are all-int (token ids / segment ids / positions) —
    int32 can't hold a NaN, so for a batch with no float leaf the fault
    escalates to corrupting this step's loss instead of silently not
    firing (the NaN guard must still see a fault to prove recovery)."""
    if step not in _cfg["poison"] or not _fire_once(f"poison_step{step}"):
        return x
    hit = [False]
    out = _poison_tree(x, hit)
    if not hit[0]:
        print(f"[chaos] poison at step {step}: batch has no float "
              "leaves (packed int batch) — corrupting the step's loss "
              "instead", file=sys.stderr, flush=True)
        _poison_loss_steps.add(step)
    return out


def _poison_tree(x, hit):
    if isinstance(x, dict):  # packed-pipeline batches are dicts
        return {k: _poison_tree(v, hit) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return type(x)(_poison_tree(e, hit) for e in x)
    arr = np.asarray(getattr(x, "data", x)
                     if not isinstance(x, np.ndarray) else x)
    if np.issubdtype(arr.dtype, np.floating):
        hit[0] = True
        return np.full_like(arr, np.nan)
    return x


# poison steps whose batch had no float leaf: corrupt_loss picks them up
# in the same fit iteration (poison_batch runs before the train step,
# corrupt_loss after)
_poison_loss_steps: set = set()


def corrupt_loss(step: int, loss: float) -> float:
    if step in _poison_loss_steps:
        _poison_loss_steps.discard(step)
        return float("nan")
    if step in _cfg["corrupt"] and _fire_once(f"corrupt_step{step}"):
        return float("nan")
    return loss


def _hang_hook(op: str, axes_label: str):
    """Installed into ``observability.comm._chaos_hook`` by refresh()."""
    hang = _cfg["hang"]
    if hang is None or hang[0] != op:
        return
    if not _fire_once(f"hang_{op}"):
        return
    print(f"[chaos] hanging collective {op}@{axes_label} for {hang[1]}s",
          file=sys.stderr, flush=True)
    time.sleep(hang[1])
