"""Shared resilience metric families.

One module with no heavy imports so every producer — ``NaNGuard`` in the
fit loop, ``amp.GradScaler``'s found-inf path, the watchdog, the
preemption listener — can bump the same counters without pulling in hapi
or jax. All families are documented in docs/RESILIENCE.md:

* ``resilience_nonfinite_total{kind}`` — nonfinite events by source
  (``loss_nan``, ``loss_spike``, ``grad_nan``, ``grad_scaler``).
* ``resilience_rollbacks_total`` — checkpoint rollbacks taken by NaNGuard.
* ``resilience_preemptions_total{reason}`` — preemption requests observed
  (``SIGTERM``, ``SIGUSR1``, ``notice_env``, ``notice_file``, ``store``).
* ``resilience_watchdog_expired_total{span}`` /
  ``resilience_watchdog_dumps_total`` / ``resilience_watchdog_armed`` —
  the hang watchdog family.
"""
from __future__ import annotations

__all__ = ["nonfinite_counter", "record_nonfinite", "rollback_counter",
           "preemption_counter", "watchdog_metrics"]


def _registry(registry=None):
    if registry is not None:
        return registry
    from paddle_tpu.observability.metrics import get_registry
    return get_registry()


def nonfinite_counter(registry=None):
    return _registry(registry).counter(
        "resilience_nonfinite_total",
        "nonfinite numeric events by source kind")


def record_nonfinite(kind: str, n: int = 1, registry=None):
    """The one funnel for every nonfinite detection in the framework —
    GradScaler skipped-scale steps and NaNGuard trips land in the same
    ``resilience_nonfinite_total`` family, split by ``kind``."""
    nonfinite_counter(registry).inc(n, kind=kind)


def rollback_counter(registry=None):
    return _registry(registry).counter(
        "resilience_rollbacks_total",
        "checkpoint rollbacks taken by NaNGuard")


def preemption_counter(registry=None):
    return _registry(registry).counter(
        "resilience_preemptions_total",
        "preemption requests observed, by delivery channel")


def watchdog_metrics(registry=None) -> dict:
    reg = _registry(registry)
    return {
        "expired": reg.counter(
            "resilience_watchdog_expired_total",
            "watchdog deadlines blown, by span name"),
        "dumps": reg.counter(
            "resilience_watchdog_dumps_total",
            "watchdog postmortem dumps written"),
        "armed": reg.gauge(
            "resilience_watchdog_armed",
            "spans currently under a watchdog deadline"),
    }
