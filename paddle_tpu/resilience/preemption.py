"""Preemption-aware training — catch the eviction notice, checkpoint, exit
resumable.

TPU pods get preempted with a SIGTERM and (on Cloud) an advance
"maintenance notice". This module turns those into a *graceful* stop:

* :class:`PreemptionListener` installs SIGTERM/SIGUSR1 handlers that only
  set a flag — the fit loop finishes the in-flight step, takes one final
  synchronized blocking ``CheckpointManager.save`` and stops cleanly
  (wired by :class:`~paddle_tpu.resilience.fit.FitResilience`).
* A file/env "maintenance notice" seam (``PADDLE_TPU_PREEMPTION_FILE`` /
  ``PADDLE_TPU_PREEMPTION_NOTICE``) stands in for the cloud metadata
  server: touch the file (or set the env) and every rank that polls
  ``should_stop()`` sees the notice without any signal delivery.
* Multi-rank coordination rides the job TCPStore (the elastic launcher's
  rendezvous) with a *consensus stop step*: signal/notice delivery is
  per-rank and per-step polls race, so the first rank to observe one
  wins an atomic claim (``store.add``) and publishes ``stop_at = its
  step + 1``; every rank (the announcer included) keeps stepping until
  its own step reaches ``stop_at`` and stops exactly there. Lockstep
  SPMD ranks are never a full step apart (each step's collectives
  synchronize them), so all ranks reach the same boundary and the final
  save's commit barrier can complete instead of deadlocking on
  mismatched step ids.
* :data:`RESUMABLE_EXIT_CODE` is the contract with the elastic launcher:
  a trainer exiting with it was preempted *after* committing a resumable
  checkpoint — the launcher relaunches without consuming the crash
  budget and the trainer resumes from ``latest_step``.

The listener deliberately does NOT chain SIGTERM to a previously
installed handler: the flight recorder's default SIGTERM behavior is
dump-then-die, which would kill the process before the graceful save. A
flight-recorder event is recorded instead, and a recorder enabled *after*
the listener chains to us on its own.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Optional

__all__ = ["RESUMABLE_EXIT_CODE", "PreemptionListener",
           "preempt_stop_key"]

#: Exit status meaning "preempted, checkpoint committed, restart me from
#: latest_step". 79 sits just past the sysexits.h range (64-78) and far
#: from the signal-death codes (128+n / negative Popen returncodes), so it
#: can never be confused with a crash.
RESUMABLE_EXIT_CODE = 79

#: Store key prefix the first preempted rank broadcasts under (namespaced
#: by the launcher's restart epoch so a resumed attempt never consumes a
#: previous attempt's stale notice).
STORE_KEY = "__preempt"

NOTICE_ENV = "PADDLE_TPU_PREEMPTION_NOTICE"
NOTICE_FILE_ENV = "PADDLE_TPU_PREEMPTION_FILE"


def _store_key() -> str:
    epoch = os.environ.get("PADDLE_RESTART_EPOCH", "0")
    return f"{STORE_KEY}/{epoch}"


def preempt_stop_key(epoch) -> str:
    """The consensus-verdict key for ``epoch`` — shared with the elastic
    launcher, which probes it to classify a peer-driven epoch bump as a
    preemption resume rather than a crash. Single source for the layout:
    the listener publishes ``{_store_key()}/stop`` and ``_store_key()``
    is ``{STORE_KEY}/{PADDLE_RESTART_EPOCH}``."""
    return f"{STORE_KEY}/{epoch}/stop"


class PreemptionListener:
    """Flag-setting preemption observer; poll :meth:`should_stop` at step
    boundaries.

    ``signals``: handled signal numbers (default SIGTERM + SIGUSR1;
    handlers install only on the main thread).
    ``notice_file``: path whose *existence* is the maintenance notice
    (default: ``$PADDLE_TPU_PREEMPTION_FILE``).
    ``use_store``: coordinate through the job TCPStore when the launcher
    env (``PADDLE_MASTER``) is present (default: auto).
    ``check_interval``: minimum seconds between notice env/file polls
    inside ``should_stop`` — 0 checks every call. The store poll is NOT
    throttled: consensus needs every rank to read the stop step every
    step (a localhost round trip is ~100µs).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGUSR1),
                 notice_file: Optional[str] = None,
                 use_store: Optional[bool] = None,
                 check_interval: float = 0.0,
                 registry=None):
        self._signals = tuple(signals)
        self._notice_file = notice_file
        self._use_store = use_store
        self._check_interval = float(check_interval)
        self._registry = registry
        # plain bools, not an Event: these are written from signal
        # context, where taking ANY lock (an Event's condition, the
        # metrics registry) can deadlock against the interrupted main
        # thread holding it. GIL-atomic attribute writes are enough —
        # readers only poll.
        self._flagged = False
        self._note_pending = False
        self.reason: Optional[str] = None
        self._prev_handlers: dict = {}
        self._installed = False
        self._store = None
        self._store_failed = False
        self._last_poll = 0.0
        self._broadcast_done = False
        self._stop_decided = False

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> "PreemptionListener":
        """Install signal handlers (idempotent; main thread only — off the
        main thread only the notice/store channels are active)."""
        if self._installed:
            return self
        if threading.current_thread() is threading.main_thread():
            for sn in self._signals:
                try:
                    self._prev_handlers[sn] = signal.signal(sn, self._handler)
                except (ValueError, OSError):
                    pass
        self._installed = True
        return self

    def uninstall(self):
        for sn, prev in self._prev_handlers.items():
            try:
                signal.signal(sn, prev)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionListener":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()

    # -- channels ----------------------------------------------------------
    def _handler(self, sn, frame):
        # SIGNAL CONTEXT: plain attribute writes only. The metric bump
        # and flight-recorder event are deferred to the next
        # ``should_stop`` poll (_note), like the store broadcast — a
        # handler that takes the registry lock deadlocks when the signal
        # interrupts a main thread already holding it (step telemetry,
        # loader counters, GradScaler all inc every few steps).
        if not self._flagged:
            self.reason = signal.Signals(sn).name
            self._note_pending = True
            self._flagged = True

    def request(self, reason: str, broadcast: bool = True):
        """Mark this process preempted (the programmatic seam chaos and
        tests use; real signals go through the attribute-only handler).
        The store broadcast is deferred to the next ``should_stop``."""
        if not self._flagged:
            self.reason = reason
            self._note_pending = True
            self._flagged = True
        if not broadcast:
            self._broadcast_done = True
        self._note()

    def _note(self):
        """Record the preemption into metrics + flight recorder — called
        only from ordinary (non-signal) context."""
        if not self._note_pending:
            return
        self._note_pending = False
        try:
            from .counters import preemption_counter
            preemption_counter(self._registry).inc(reason=self.reason)
        except Exception:
            pass
        try:
            from paddle_tpu.observability import flight_recorder as fr
            t = time.perf_counter_ns()
            fr.record(fr.KIND_USER, f"preempt:{self.reason}", t, t)
        except Exception:
            pass

    def _job_store(self):
        if self._store is not None or self._store_failed:
            return self._store
        use = self._use_store
        if use is None:
            use = bool(os.environ.get("PADDLE_MASTER"))
        if not use:
            self._store_failed = True
            return None
        try:
            from paddle_tpu.distributed.tcp_store import job_store
            self._store = job_store()
        except Exception:
            self._store_failed = True
        return self._store

    def _poll_notice(self):
        """Maintenance-notice env/file channels (signal channels set the
        flag directly from the handler)."""
        if os.environ.get(NOTICE_ENV, "").strip() not in ("", "0"):
            self.request("notice_env")
        path = self._notice_file or os.environ.get(NOTICE_FILE_ENV)
        if path and os.path.exists(path):
            self.request("notice_file")

    # -- the step-boundary query ------------------------------------------
    def should_stop(self, step: Optional[int] = None) -> bool:
        """Poll at a step boundary. ``step`` (the caller's current global
        step) activates the consensus protocol: with a job store, True
        only once the cluster-agreed stop step is reached — all ranks
        return True at the SAME boundary. Without a store (or with
        ``step=None``) a locally observed preemption stops immediately.
        """
        if self._stop_decided:
            return True
        self._note()  # metrics/FR for a signal observed since last poll
        now = time.monotonic()
        if now - self._last_poll >= self._check_interval:
            self._last_poll = now
            self._poll_notice()
        store = self._job_store()
        if store is None:
            self._stop_decided = self._flagged
            return self._stop_decided
        try:
            key = _store_key()
            if self._flagged and not self._broadcast_done:
                # exactly one rank (atomic claim) publishes the stop
                # step: one PAST its own, so lockstep peers still inside
                # this step learn it before reaching that boundary
                if int(store.add(key + "/armed", 1)) == 1:
                    stop_at = 0 if step is None else int(step) + 1
                    store.set(key + "/stop",
                              f"{stop_at}:{self.reason or '?'}".encode())
                self._broadcast_done = True
            v = store.get(key + "/stop")
            if v is None:
                return False
            stop_s, _, reason = v.decode(errors="replace").partition(":")
            if not self._flagged:
                self.request(f"store:{reason}", broadcast=False)
            stop_at = int(stop_s)
            if step is None or stop_at == 0 or int(step) >= stop_at:
                self._stop_decided = True
            return self._stop_decided
        except Exception:
            # the control plane dying must never kill the training step;
            # fall back to local-only semantics
            self._store_failed = True
            self._stop_decided = self._flagged
            return self._stop_decided

    @property
    def preempted(self) -> bool:
        return self._flagged

    def exit_resumable(self):
        """Terminate with the launcher's resumable contract."""
        sys.exit(RESUMABLE_EXIT_CODE)
