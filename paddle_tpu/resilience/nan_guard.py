"""NaNGuard — numeric-blowup detection with checkpoint rollback.

A NaN loss does not crash a training run; it silently poisons every
subsequent optimizer update, and the next checkpoint commits the poison.
NaNGuard is a fit-loop callback that:

* checks each step's loss (and ``grad_norm`` when present in the logs)
  for finiteness, plus an optional loss-*spike* window (loss >
  ``spike_factor`` × the median of the last ``spike_window`` finite
  losses);
* on a trip, bumps ``resilience_nonfinite_total{kind}`` (the same family
  ``amp.GradScaler`` feeds for skipped-scale steps) and **rolls back**:
  the last committed checkpoint is restored onto the current mesh —
  model *and* optimizer state — undoing the poisoned update(s); the
  offending batch window is effectively skipped because training resumes
  with the loader's next batches;
* suppresses spike detection for ``cooldown`` steps after a rollback
  (the window statistics are stale) and counts rollbacks —
  ``max_rollbacks`` exceeded raises loudly instead of looping forever.

Without a checkpoint manager (or before the first commit) a trip cannot
roll back; it still counts, warns, and fails after ``max_rollbacks``.
"""
from __future__ import annotations

import math
import warnings
from collections import deque
from typing import Optional

from paddle_tpu.hapi.model import Callback

from .counters import record_nonfinite, rollback_counter

__all__ = ["NaNGuard", "NumericError"]


class NumericError(RuntimeError):
    """Raised when NaNGuard exhausts its rollback budget."""


def apply_restored_state(model, state):
    """Apply a CheckpointManager state tree to a hapi model: the
    ``{"model", "optimizer"}`` pair restores both; a flat dict restores
    model weights only. Shared by NaNGuard rollback and
    FitResilience.restore so the two paths can never drift."""
    if isinstance(state, dict) and isinstance(state.get("model"), dict):
        model.network.set_state_dict(state["model"])
        opt = getattr(model, "_optimizer", None)
        if opt is not None and isinstance(state.get("optimizer"), dict):
            opt.set_state_dict(state["optimizer"])
    elif isinstance(state, dict):
        model.network.set_state_dict(state)


class NaNGuard(Callback):
    def __init__(self, manager=None, max_rollbacks: int = 3,
                 spike_window: int = 0, spike_factor: float = 10.0,
                 cooldown: Optional[int] = None, registry=None):
        self.manager = manager
        self.max_rollbacks = int(max_rollbacks)
        self.spike_window = int(spike_window)
        self.spike_factor = float(spike_factor)
        self.cooldown = (self.spike_window if cooldown is None
                         else int(cooldown))
        self.registry = registry
        self.rollbacks = 0
        self.trips: list = []
        self._window: deque = deque(maxlen=max(self.spike_window, 1))
        self._cool = 0

    # -- detection ---------------------------------------------------------
    def _spike(self, loss: float) -> bool:
        if not self.spike_window or self._cool > 0 \
                or len(self._window) < self.spike_window:
            return False
        med = sorted(self._window)[len(self._window) // 2]
        return abs(loss) > self.spike_factor * max(abs(med), 1e-12)

    def check(self, step: int, loss: Optional[float],
              grad_norm: Optional[float] = None) -> Optional[str]:
        """Returns the trip kind (or None); rolls back on a trip."""
        kind = None
        if loss is not None and not math.isfinite(loss):
            kind = "loss_nan"
        elif grad_norm is not None and not math.isfinite(grad_norm):
            kind = "grad_nan"
        elif loss is not None and self._spike(loss):
            kind = "loss_spike"
        if self._cool > 0:
            self._cool -= 1
        if kind is None:
            if loss is not None and math.isfinite(loss):
                self._window.append(loss)
            return None
        record_nonfinite(kind, registry=self.registry)
        self.trips.append({"step": step, "kind": kind, "loss": loss})
        self._rollback(step, kind)
        return kind

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        self.check(step, logs.get("loss"), logs.get("grad_norm"))

    # -- remedy ------------------------------------------------------------
    def _rollback(self, step: int, kind: str):
        self.rollbacks += 1
        if self.rollbacks > self.max_rollbacks:
            raise NumericError(
                f"NaNGuard tripped {self.rollbacks} times (last: {kind} at "
                f"step {step}) — rollback budget ({self.max_rollbacks}) "
                "exhausted; the run is numerically unstable")
        restored = self._restore_last_commit()
        rollback_counter(self.registry).inc()
        provenance = self._write_provenance(step, kind)
        if restored is not None and step > restored:
            # the steps between the restored commit and the trip were
            # just thrown away — reclassify their ledger seconds from
            # productive to rollback_discarded badput
            from paddle_tpu.observability import goodput
            try:
                goodput.discard_recent_steps(step - restored)
            except Exception:
                pass  # accounting must never block the rollback itself
        self._window.clear()
        self._cool = self.cooldown
        warnings.warn(
            f"[nan_guard] {kind} at step {step}: " +
            (f"rolled back to committed step {restored}"
             if restored is not None else
             "no committed checkpoint to roll back to — continuing with "
             "current (possibly poisoned) parameters") +
            f" (rollback {self.rollbacks}/{self.max_rollbacks})" +
            (f"; NaN provenance: {provenance}" if provenance else ""),
            RuntimeWarning, stacklevel=2)

    def _write_provenance(self, step: int, kind: str) -> Optional[str]:
        """NaN provenance (docs/OBSERVABILITY.md#numerics): instrumented
        replay of the batch that tripped us against the just-restored
        state, naming the first non-finite tap/bucket in topological
        order in ``nan_provenance_rank<r>_<pid>.json`` + a flight-recorder
        event. Needs ``PADDLE_TPU_NUMERICS`` (or _PROVENANCE) armed and a
        compiled-TrainStep model (the batch stash lives there); any
        failure is swallowed — provenance is evidence, not a remedy, and
        must never break the rollback that just saved the run."""
        from paddle_tpu.observability import numerics
        if not numerics.provenance_enabled():
            return None
        train_step = getattr(getattr(self, "model", None),
                             "_train_step", None)
        if train_step is None:
            return None
        try:
            return numerics.write_provenance(train_step, step, kind)
        except Exception:
            warnings.warn("[nan_guard] provenance replay failed",
                          RuntimeWarning, stacklevel=2)
            return None

    def _restore_last_commit(self) -> Optional[int]:
        mgr = self.manager
        if mgr is None:
            return None
        try:
            # drain in-flight async saves first: they were snapshotted at
            # pre-trip step boundaries, so the freshest (closest) rollback
            # point may not have committed yet — without this, a trip in
            # the first steps of a run sees "nothing committed" and the
            # poison survives another step
            mgr.wait_all()
        except Exception:
            pass  # a failed background save: restore whatever committed
        if mgr.latest_step() is None:
            return None
        state = mgr.restore()  # latest committed, crc-verified, onto the
        #                        CURRENT mesh (reshard handles topology)
        model = getattr(self, "model", None)
        if model is not None:
            apply_restored_state(model, state)
        return mgr.last_restored_step
