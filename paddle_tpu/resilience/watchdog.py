"""Hang/straggler watchdog — monotonic deadlines around steps and
collectives.

A hung all-reduce (one host wedged, a stuck DMA, a dead peer) stalls every
rank *silently*: the step never returns, no exception fires, the job burns
its reservation until an operator notices. The watchdog turns that into a
bounded, observable event:

* :meth:`Watchdog.arm`/:meth:`disarm` (or the :meth:`watch` context
  manager) put a monotonic deadline around any region. The fit loop arms
  around each train step; :meth:`watch_collectives` hooks every traced
  collective span in ``observability.comm`` (the PR 1 spans) with its own
  — typically much shorter — deadline.
* On expiry the watchdog escalates along a configurable ladder
  (``action``): ``"log"`` → loud warning + metrics; ``"dump"`` → also a
  postmortem JSON naming the stuck span, rank, step and carrying the
  flight recorder's recent events; ``"kill"`` → also ``os._exit`` with
  :data:`~paddle_tpu.resilience.preemption.RESUMABLE_EXIT_CODE` so the
  elastic launcher restarts the job from the last committed checkpoint
  instead of letting it hang forever.
* Metrics: ``resilience_watchdog_expired_total{span}``,
  ``resilience_watchdog_dumps_total``, ``resilience_watchdog_armed``.

The monitor thread is a daemon that sleeps until the nearest deadline;
arming/disarming is a dict insert/pop under a lock — cheap enough for
per-collective use.
"""
from __future__ import annotations

import json
import os
import threading
import time
import warnings
from typing import Optional

from .counters import watchdog_metrics
from .preemption import RESUMABLE_EXIT_CODE

__all__ = ["Watchdog", "WatchdogExpired"]

_ACTIONS = ("log", "dump", "kill")


class WatchdogExpired(RuntimeWarning):
    """Category for watchdog expiry warnings (filterable in tests)."""


class Watchdog:
    """``action`` picks the escalation rung (each includes the previous):
    ``"log"``, ``"dump"`` (default), ``"kill"``. ``kill_exit_code``
    defaults to the resumable contract; set 1 to make a hang a plain
    failure. ``on_expire(span_dict)`` is an observer hook (tests, custom
    paging) that runs before the action."""

    def __init__(self, default_timeout: float = 300.0, action: str = "dump",
                 registry=None, kill_exit_code: int = RESUMABLE_EXIT_CODE,
                 trace_dir: Optional[str] = None, on_expire=None):
        if action not in _ACTIONS:
            raise ValueError(f"action must be one of {_ACTIONS}")
        self.default_timeout = float(default_timeout)
        self.action = action
        self.kill_exit_code = int(kill_exit_code)
        self.trace_dir = trace_dir
        self.on_expire = on_expire
        self.collective_timeout: Optional[float] = None
        self._m = watchdog_metrics(registry)
        self._lock = threading.Lock()
        self._spans: dict = {}          # token -> span dict
        self._next_token = 0
        self._wake = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.expired: list = []         # expired span dicts (introspection)
        self.last_dump: Optional[str] = None

    # -- arming ------------------------------------------------------------
    def arm(self, name: str, timeout: Optional[float] = None,
            **context) -> int:
        """Start a deadline for ``name``; returns a token for
        :meth:`disarm`. ``context`` (step, rank, ...) lands in the
        postmortem."""
        timeout = self.default_timeout if timeout is None else float(timeout)
        span = {"name": name, "deadline": time.monotonic() + timeout,
                "timeout_s": timeout, "armed_unix": time.time(),
                "context": context, "fired": False}
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._spans[token] = span
            self._ensure_thread()
        self._m["armed"].set(len(self._spans))
        self._wake.set()
        return token

    def disarm(self, token: int):
        with self._lock:
            self._spans.pop(token, None)
        self._m["armed"].set(len(self._spans))
        self._wake.set()

    def watch(self, name: str, timeout: Optional[float] = None, **context):
        """``with wd.watch("phase"): ...`` — arm/disarm around a block."""
        return _WatchScope(self, name, timeout, context)

    def watch_collectives(self, timeout: Optional[float] = None):
        """Arm every traced collective span (``observability.comm``) with
        ``timeout`` (default: the watchdog's default). The hook is a
        module-global read in ``comm_scope`` — zero cost for processes
        that never call this."""
        self.collective_timeout = (self.default_timeout if timeout is None
                                   else float(timeout))
        from paddle_tpu.observability import comm
        comm._collective_watchdog = self
        return self

    # -- lifecycle ---------------------------------------------------------
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._stop = False
            self._thread = threading.Thread(
                target=self._run, name="pt-watchdog", daemon=True)
            self._thread.start()

    def stop(self):
        """Stop the monitor and detach from the collective hook."""
        self._stop = True
        self._wake.set()
        from paddle_tpu.observability import comm
        if getattr(comm, "_collective_watchdog", None) is self:
            comm._collective_watchdog = None
        t = self._thread
        if t is not None:
            t.join(timeout=2)
            self._thread = None
        with self._lock:
            self._spans.clear()
        self._m["armed"].set(0)

    # -- monitor -----------------------------------------------------------
    def _run(self):
        while not self._stop:
            # clear BEFORE reading the span table: an arm() landing after
            # the read re-sets the event and the wait below returns
            # immediately — clearing after the read could eat that signal
            # and sleep forever past a fresh deadline
            self._wake.clear()
            now = time.monotonic()
            fire = []
            nearest = None
            with self._lock:
                for span in self._spans.values():
                    if span["fired"]:
                        continue
                    if span["deadline"] <= now:
                        span["fired"] = True
                        fire.append(dict(span))
                    elif nearest is None or span["deadline"] < nearest:
                        nearest = span["deadline"]
            for span in fire:
                try:
                    self._expire(span)
                except Exception:
                    pass  # the monitor must survive a failed dump
            timeout = None if nearest is None else max(nearest - now, 0.0)
            self._wake.wait(timeout)

    def _expire(self, span: dict):
        span["elapsed_s"] = round(
            span["timeout_s"] + (time.monotonic() - span["deadline"]), 3)
        self.expired.append(span)
        self._m["expired"].inc(span=span["name"])
        info = self._rank_info()
        where = f"rank {info.get('rank', 0)}"
        step = span["context"].get("step")
        at = f" at step {step}" if step is not None else ""
        warnings.warn(
            f"[watchdog] span {span['name']!r} on {where}{at} blew its "
            f"{span['timeout_s']}s deadline (action={self.action})",
            WatchdogExpired, stacklevel=2)
        if self.on_expire is not None:
            self.on_expire(span)
        if self.action in ("dump", "kill"):
            self.last_dump = self._dump(span, info)
            self._m["dumps"].inc()
        if self.action == "kill":
            # a hung process cannot run cleanup; die hard with the
            # resumable code so the launcher restarts from latest_step
            os._exit(self.kill_exit_code)

    # -- postmortem --------------------------------------------------------
    @staticmethod
    def _rank_info() -> dict:
        from paddle_tpu.observability.flight_recorder import _rank_topology
        return _rank_topology()

    def _dump(self, span: dict, info: dict) -> str:
        from paddle_tpu.observability import flight_recorder
        d = self.trace_dir or os.environ.get("PADDLE_TPU_TRACE_DIR",
                                             "/tmp/paddle_tpu_trace")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"watchdog_rank{info.get('rank', 0)}_{os.getpid()}.json")
        rec = flight_recorder.active()
        doc = {"reason": "watchdog", "unix_time": time.time(), **info,
               "stuck_span": {k: span[k] for k in
                              ("name", "timeout_s", "elapsed_s",
                               "armed_unix", "context")},
               "action": self.action,
               "events": rec.events() if rec is not None else [],
               # goodput ledger + last heartbeats: a hung-job postmortem
               # should name the rank that stalled first (compare each
               # lane's last step id / timestamp across rank dumps)
               **flight_recorder._ledger_appendix()}
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        return path


class _WatchScope:
    def __init__(self, wd, name, timeout, context):
        self._wd, self._name, self._timeout = wd, name, timeout
        self._context = context
        self._token = None

    def __enter__(self):
        self._token = self._wd.arm(self._name, self._timeout,
                                   **self._context)
        return self

    def __exit__(self, *exc):
        self._wd.disarm(self._token)
