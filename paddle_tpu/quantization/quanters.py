"""Fake quanters (reference: ``python/paddle/quantization/quanters/abs_max.py``
FakeQuanterWithAbsMaxObserver — moving-average abs-max scale, simulated
int-k round-trip with a straight-through gradient)."""
from __future__ import annotations

import numpy as np

import paddle_tpu as pt
from paddle_tpu.core.autograd import apply_op

from .base import BaseQuanter
from .factory import QuanterFactory

__all__ = ["FakeQuanterWithAbsMaxObserver",
           "FakeQuanterWithAbsMaxObserverLayer"]


def fake_quant_ste(x, scale, bits):
    """round(clip(x/s)) * s with the straight-through estimator: the
    backward is identity (``x + stop_grad(q - x)``), matching the
    reference's fake_quantize_dequantize kernels."""
    bound = float(2 ** (bits - 1) - 1)

    def fn(xv):
        import jax
        import jax.numpy as jnp
        s = jnp.maximum(scale, 1e-9)
        q = jnp.clip(jnp.round(xv / s * bound), -bound, bound) * s / bound
        return xv + jax.lax.stop_gradient(q - xv)
    return apply_op(fn, x, op_name="fake_quantize_dequantize")


class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    """QAT activation/weight quanter: tracks a moving-average abs-max and
    fake-quantizes through it (abs_max.py:FakeQuanterWithAbsMaxObserverLayer)."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 dtype: str = "float32", name=None, quant_on_weight=False):
        super().__init__()
        self._moving_rate = float(moving_rate)
        self._quant_bits = int(bit_length)
        self.register_buffer("_scale",
                             pt.to_tensor(np.zeros((), np.float32)))
        self.register_buffer("_state",
                             pt.to_tensor(np.zeros((), np.float32)))

    def forward(self, x):
        if self.training:
            cur = float(np.abs(np.asarray(x.data)).max()) if x.data.size \
                else 0.0
            state = float(self._state.numpy())
            scale = float(self._scale.numpy())
            r = self._moving_rate
            new_state = r * state + 1.0
            new_scale = (r * scale * state + cur) / new_state if state > 0 \
                else cur
            import jax.numpy as jnp
            self._state.data = jnp.float32(new_state)
            self._scale.data = jnp.float32(new_scale)
        scale = float(self._scale.numpy())
        if scale <= 0:
            return x
        return fake_quant_ste(x, scale, self._quant_bits)

    def scales(self):
        return self._scale

    def bit_length(self):
        return self._quant_bits


# public name is a factory, so config authors write
# FakeQuanterWithAbsMaxObserver(moving_rate=0.9) (reference @quanter deco)
FakeQuanterWithAbsMaxObserver = QuanterFactory(
    FakeQuanterWithAbsMaxObserverLayer)
