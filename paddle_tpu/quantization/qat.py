"""QAT — quantization-aware training (reference:
``python/paddle/quantization/qat.py`` + ``quantize.py`` Quantization base:
walk the model, replace configured layers with their quanted wrappers;
``convert`` bakes the learned scales into plain layers)."""
from __future__ import annotations

import copy

import numpy as np

from paddle_tpu.nn import Layer

from .config import QuantConfig
from .wrapper import _QuantedBase

__all__ = ["QAT"]


class Quantization:
    def __init__(self, config: QuantConfig):
        self._config = config

    def _walk_replace(self, model: Layer, make, orig=None, prefix=""):
        """Walk ``model`` (possibly a deepcopy) in lockstep with ``orig``
        (the user's original) so id-based add_layer_config still resolves,
        matching names by full dotted path."""
        orig = orig if orig is not None else model
        for name, child in list(model._sub_layers.items()):
            ochild = orig._sub_layers.get(name, child)
            path = f"{prefix}.{name}" if prefix else name
            if self._config._is_quantifiable(child, path,
                                             orig_layer=ochild):
                cfg = self._config._get_config_by_layer(
                    child, path, orig_layer=ochild)
                model._sub_layers[name] = make(child, cfg)
            else:
                self._walk_replace(child, make, ochild, path)
        return model


class QAT(Quantization):
    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        orig = model
        if not inplace:
            model = copy.deepcopy(model)
        mapping = self._config.qat_layer_mappings

        def make(child, cfg):
            return mapping[type(child)](child, cfg)
        return self._walk_replace(model, make, orig)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Bake fake-quantized weights back into the plain layers for
        deployment (the reference's onnx-format convert collapses
        quant/dequant pairs the same way)."""
        if not inplace:
            model = copy.deepcopy(model)
        _convert_in_place(model)
        return model


def _convert_in_place(model: Layer):
    for name, child in list(model._sub_layers.items()):
        if isinstance(child, _QuantedBase):
            plain = child._layer
            wq = child.weight_quanter
            if wq is not None and wq.scales() is not None:
                # scalar (per-tensor) or [channels] vector (per-channel,
                # broadcast along the quanter's channel axis)
                scale = np.asarray(wq.scales().numpy(), np.float32)
                bits = wq.bit_length()
                if (scale > 0).any():
                    from .base import bcast_shape, channel_axis_of
                    w = np.asarray(plain.weight.data)
                    if scale.ndim:
                        axis = channel_axis_of(wq, "weight quanter")
                        scale = scale.reshape(bcast_shape(w.ndim, axis))
                    scale = np.maximum(scale, 1e-9)
                    bound = float(2 ** (bits - 1) - 1)
                    q = np.clip(np.round(w / scale * bound), -bound,
                                bound) * scale / bound
                    plain.weight.data = q.astype(w.dtype)
            model._sub_layers[name] = plain
        else:
            _convert_in_place(child)
