"""PTQ observers (reference:
``python/paddle/quantization/observers/abs_max.py`` AbsmaxObserver —
identity forward that records the running abs-max for calibration)."""
from __future__ import annotations

import numpy as np

import paddle_tpu as pt

from .base import BaseObserver
from .factory import QuanterFactory

__all__ = ["AbsmaxObserver", "AbsmaxObserverLayer",
           "PerChannelAbsmaxObserver", "PerChannelAbsmaxObserverLayer"]


class AbsmaxObserverLayer(BaseObserver):
    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self._quant_bits = int(quant_bits)
        self.register_buffer("_scale",
                             pt.to_tensor(np.zeros((), np.float32)))

    def forward(self, x):
        cur = float(np.abs(np.asarray(x.data)).max()) if x.data.size else 0.0
        if cur > float(self._scale.numpy()):
            import jax.numpy as jnp
            self._scale.data = jnp.float32(cur)
        return x

    def scales(self):
        return self._scale

    def bit_length(self):
        return self._quant_bits


AbsmaxObserver = QuanterFactory(AbsmaxObserverLayer)


class PerChannelAbsmaxObserverLayer(BaseObserver):
    """Per-channel weight observer (reference:
    ``python/paddle/quantization/imperative/ptq_quantizer.py:137``
    PerChannelAbsmaxQuantizer — the reference's DEFAULT PTQ weight
    quantizer): one abs-max scale per output channel instead of one for
    the whole tensor. For conv stacks per-tensor weight scales cost real
    accuracy — a single hot filter inflates every other filter's grid.

    The channel axis follows the weight layout of the wrapped layer
    (passed by ``QuanterFactory._instance``): Conv2D weights are OIHW so
    the output-channel axis is 0; Linear weights are [in, out] so it is
    the last axis. ``quant_axis=...`` overrides.

    Forward fake-quantizes through the per-channel grid (broadcast scale)
    with a straight-through gradient, so the same class serves as a QAT
    weight quanter; in eval mode scales stay frozen."""

    _wants_layer = True

    def __init__(self, quant_bits: int = 8, quant_axis=None, layer=None):
        super().__init__()
        self._quant_bits = int(quant_bits)
        if quant_axis is None:
            from paddle_tpu import nn
            if layer is not None and isinstance(
                    layer, (nn.Conv1D, nn.Conv2D, nn.Conv3D)):
                quant_axis = 0
            else:
                quant_axis = -1
        self._quant_axis = int(quant_axis)
        # concrete zero buffer when the channel count is known (from the
        # wrapped layer's weight): a None buffer would vanish from
        # state_dict and silently break checkpoint round-trips
        n_ch = 0
        if layer is not None and hasattr(layer, "weight"):
            wshape = tuple(layer.weight.shape)
            n_ch = int(wshape[self._quant_axis % len(wshape)])
        self.register_buffer(
            "_scale", pt.to_tensor(np.zeros(n_ch, np.float32))
            if n_ch else None)

    def forward(self, x):
        from .quanters import fake_quant_ste
        axis = self._quant_axis % x.data.ndim
        if self.training and x.data.size:
            arr = np.abs(np.asarray(x.data))
            reduce_axes = tuple(i for i in range(arr.ndim) if i != axis)
            cur = arr.max(axis=reduce_axes) if reduce_axes \
                else arr.astype(np.float32)
            if self._scale is not None and \
                    self._scale.data.size == cur.size:
                cur = np.maximum(cur, np.asarray(self._scale.numpy()))
            elif self._scale is not None and self._scale.data.size and \
                    np.asarray(self._scale.numpy()).any():
                raise ValueError(
                    f"per-channel observer saw {cur.size} channels after "
                    f"calibrating {self._scale.data.size} — the observed "
                    "tensor's channel axis changed")
            # else: the zeros buffer was sized from the layer's WEIGHT;
            # when observing an activation instead, adopt its channel count
            self._scale = pt.to_tensor(cur.astype(np.float32))
        if self._scale is None or \
                not np.asarray(self._scale.numpy()).any():
            return x  # uncalibrated: identity (same as the scalar observer)
        from .base import bcast_shape
        import jax.numpy as jnp
        bcast = jnp.reshape(self._scale.data,
                            bcast_shape(x.data.ndim, axis))
        return fake_quant_ste(x, bcast, self._quant_bits)

    def scales(self):
        return self._scale

    def quant_axis(self):
        return self._quant_axis

    def bit_length(self):
        return self._quant_bits


PerChannelAbsmaxObserver = QuanterFactory(PerChannelAbsmaxObserverLayer)
