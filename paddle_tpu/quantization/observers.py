"""PTQ observers (reference:
``python/paddle/quantization/observers/abs_max.py`` AbsmaxObserver —
identity forward that records the running abs-max for calibration)."""
from __future__ import annotations

import numpy as np

import paddle_tpu as pt

from .base import BaseObserver
from .factory import QuanterFactory

__all__ = ["AbsmaxObserver", "AbsmaxObserverLayer"]


class AbsmaxObserverLayer(BaseObserver):
    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self._quant_bits = int(quant_bits)
        self.register_buffer("_scale",
                             pt.to_tensor(np.zeros((), np.float32)))

    def forward(self, x):
        cur = float(np.abs(np.asarray(x.data)).max()) if x.data.size else 0.0
        if cur > float(self._scale.numpy()):
            import jax.numpy as jnp
            self._scale.data = jnp.float32(cur)
        return x

    def scales(self):
        return self._scale

    def bit_length(self):
        return self._quant_bits


AbsmaxObserver = QuanterFactory(AbsmaxObserverLayer)
