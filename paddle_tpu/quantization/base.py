"""Quanter/observer base classes (reference:
``python/paddle/quantization/base_quanter.py``, ``base_observer.py``)."""
from __future__ import annotations

import abc

from paddle_tpu.nn import Layer

__all__ = ["BaseQuanter", "BaseObserver"]


class BaseQuanter(Layer, metaclass=abc.ABCMeta):
    """A Layer that simulates quantization in forward; exposes the learned
    scale/zero-point so ``convert`` can bake real quantized weights."""

    @abc.abstractmethod
    def scales(self):
        ...

    def zero_points(self):
        return None

    def quant_axis(self):
        return None

    def bit_length(self):
        return getattr(self, "_quant_bits", 8)


class BaseObserver(BaseQuanter, metaclass=abc.ABCMeta):
    """PTQ observer: watches activations during calibration (forward is
    identity), then reports scales."""
