"""Quanter/observer base classes (reference:
``python/paddle/quantization/base_quanter.py``, ``base_observer.py``)."""
from __future__ import annotations

import abc

from paddle_tpu.nn import Layer

__all__ = ["BaseQuanter", "BaseObserver", "bcast_shape", "channel_axis_of"]


def bcast_shape(ndim: int, axis: int) -> list:
    """Broadcast shape for a per-channel scale vector along ``axis`` of an
    ``ndim``-d tensor — the ONE definition shared by the fake-quant
    simulation, the observers, and the int8 execution path (drift between
    them would desynchronize simulation from execution)."""
    shape = [1] * ndim
    shape[axis % ndim] = -1
    return shape


def channel_axis_of(quanter, what: str = "quanter") -> int:
    """The channel axis of a quanter with 1-D scales; raises when the
    quanter returns a vector but never declared its axis (a custom
    @quanter extension bug that would otherwise mis-broadcast silently)."""
    axis = quanter.quant_axis()
    if axis is None:
        raise ValueError(
            f"{what} returned per-channel (1-D) scales but its "
            "quant_axis() is None — override quant_axis() to name the "
            "channel axis of the weight")
    return int(axis)


class BaseQuanter(Layer, metaclass=abc.ABCMeta):
    """A Layer that simulates quantization in forward; exposes the learned
    scale/zero-point so ``convert`` can bake real quantized weights."""

    @abc.abstractmethod
    def scales(self):
        ...

    def zero_points(self):
        return None

    def quant_axis(self):
        return None

    def bit_length(self):
        return getattr(self, "_quant_bits", 8)


class BaseObserver(BaseQuanter, metaclass=abc.ABCMeta):
    """PTQ observer: watches activations during calibration (forward is
    identity), then reports scales."""
