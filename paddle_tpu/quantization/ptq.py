"""PTQ — post-training quantization (reference:
``python/paddle/quantization/ptq.py``): insert observers, run calibration
batches, then ``convert`` to quanted layers using the observed scales."""
from __future__ import annotations

import copy

from paddle_tpu.nn import Layer

from .config import SingleLayerConfig
from .qat import Quantization
from .quanters import FakeQuanterWithAbsMaxObserverLayer
from .wrapper import ObserveWrapper

__all__ = ["PTQ"]


class PTQ(Quantization):
    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        orig = model
        if not inplace:
            model = copy.deepcopy(model)

        def make(child, cfg):
            obs = cfg.activation._instance(child) \
                if cfg.activation is not None else None
            return ObserveWrapper(obs, child, cfg)
        return self._walk_replace(model, make, orig)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """Replace observed layers with quanted layers whose activation
        quanter is frozen at the observed scale."""
        if not inplace:
            model = copy.deepcopy(model)
        mapping = self._config.qat_layer_mappings
        self._convert_walk(model, mapping)
        model.eval()  # deployment form: quanter scales stay frozen
        return model

    def _convert_walk(self, model: Layer, mapping):
        for name, child in list(model._sub_layers.items()):
            if isinstance(child, ObserveWrapper):
                observed = child._observed
                cfg = child._q_config  # resolved at quantize time
                # weight quanter from the config; activation quanter is a
                # fake-quanter FROZEN at the observed calibration scale
                quanted = mapping[type(observed)](
                    observed, SingleLayerConfig(None, cfg.weight))
                if quanted.weight_quanter is not None:
                    # calibrate the weight scale from the weights now (PTQ
                    # never trains, so the quanter would otherwise stay at
                    # scale 0 = no-op)
                    quanted.weight_quanter.train()
                    quanted.weight_quanter(observed.weight)
                    quanted.weight_quanter.eval()
                if child._observer is not None:
                    obs_scale = child._observer.scales()
                    if obs_scale is not None and obs_scale.data.size > 1:
                        raise ValueError(
                            "PTQ activation observers must be per-tensor "
                            f"(got {obs_scale.data.size} scales); "
                            "per-channel quantization applies to weights "
                            "(pass it as the weight= config)")
                    fq = FakeQuanterWithAbsMaxObserverLayer(
                        bit_length=child._observer.bit_length())
                    fq._scale.data = obs_scale.data
                    fq.eval()
                    quanted.activation_quanter = fq
                model._sub_layers[name] = quanted
            else:
                self._convert_walk(child, mapping)
