"""Quanted layer wrappers (reference: ``python/paddle/nn/quant/qat/``
QuantedLinear/QuantedConv2D and ``quantization/wrapper.py``): the original
layer's compute with fake-quant applied to activation and weight."""
from __future__ import annotations

import paddle_tpu.nn.functional as F
from paddle_tpu.nn import Layer

__all__ = ["QuantedLinear", "QuantedConv2D", "ObserveWrapper"]


class _QuantedBase(Layer):
    def __init__(self, layer: Layer, q_config):
        super().__init__()
        self._layer = layer
        self.activation_quanter = None
        self.weight_quanter = None
        if q_config.activation is not None:
            self.activation_quanter = q_config.activation._instance(layer)
        if q_config.weight is not None:
            self.weight_quanter = q_config.weight._instance(layer)

    # the wrapped layer's params are reached through _layer (a sublayer);
    # re-registering them here would duplicate them in parameters()
    @property
    def weight(self):
        return self._layer.weight

    @property
    def bias(self):
        return getattr(self._layer, "bias", None)

    def _quant_inputs(self, x):
        w = self.weight
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return x, w


class QuantedLinear(_QuantedBase):
    def forward(self, x):
        x, w = self._quant_inputs(x)
        return F.linear(x, w, self.bias)


class QuantedConv2D(_QuantedBase):
    def forward(self, x):
        x, w = self._quant_inputs(x)
        lyr = self._layer
        return F.conv2d(x, w, self.bias, lyr._stride, lyr._padding,
                        lyr._dilation, lyr._groups, lyr._data_format)


class ObserveWrapper(Layer):
    """PTQ wrapper: observe the input, then run the original layer
    unchanged (reference wrapper.py:ObserveWrapper). Carries the resolved
    quant config so ``PTQ.convert`` needs no re-resolution (which would
    miss per-layer ids across the quantize deepcopy)."""

    def __init__(self, observer, observed: Layer, q_config=None):
        super().__init__()
        self._observer = observer
        self._observed = observed
        self._q_config = q_config

    def forward(self, *args, **kwargs):
        if self._observer is not None and args:
            args = (self._observer(args[0]),) + args[1:]
        return self._observed(*args, **kwargs)
