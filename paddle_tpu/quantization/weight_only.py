"""Weight-only PTQ for serving (ISSUE 20): ``(values, scales)`` leaves.

The QAT/PTQ framework in this package rewrites *layers* (fake-quant
wrappers, ``convert_to_int8``). Serving wants something orthogonal: the
``ServingEngine`` threads a flat functional state dict through ONE
compiled step, so quantization has to happen at the *leaf* level —
replace a selected weight leaf with a :class:`QuantizedLeaf` pytree node
holding ``(int8 values, f32 per-channel scales)`` and dequantize INSIDE
the traced step, right before ``swap_state`` hands the weights to the
unmodified model. XLA fuses the dequant multiply into the consuming
matmul, the model code never changes, and the Megatron sharding specs
keep working (``values`` shard exactly like the original 2-D weight,
the 1-D scales like its channel axis).

Calibration comes from the numerics observatory's per-tap range
sketches (PR 14): a training checkpoint's ``"numerics"`` aux key, or a
one-batch :func:`calibrate` pass when no checkpoint exists. Sketches
gate *sensitivity*: a layer whose activation absmax/p99 ratio exceeds
``PADDLE_TPU_QUANT_OUTLIER_RATIO`` keeps its original dtype (outlier-
heavy activations are where weight-only quantization bites hardest).

Modes (``WEIGHT_MODES``): ``int8_wo`` — symmetric per-channel int8,
scale = absmax/127; ``fp8_wo`` — ``float8_e4m3fn`` storage, scale =
absmax/448 (gated on the running jax exposing the dtype).
"""
from __future__ import annotations

import os
import re
from typing import Dict, Optional

import jax
import jax.numpy as jnp

__all__ = ["QuantizedLeaf", "WEIGHT_MODES", "quantize_state",
           "quantized_bytes", "calibrate", "calibration_from_checkpoint",
           "sensitive_params", "quantization_metrics"]

#: mode -> (storage dtype name, max representable magnitude of the grid)
WEIGHT_MODES = {
    "int8_wo": ("int8", 127.0),
    "fp8_wo": ("float8_e4m3fn", 448.0),
}

#: projection weights quantized by default (Llama-family); everything
#: else (norms, embeddings, adapters) keeps its dtype
_DEFAULT_TARGET_SUFFIXES = (
    "q_proj.weight", "k_proj.weight", "v_proj.weight", "o_proj.weight",
    "gate_proj.weight", "up_proj.weight", "down_proj.weight",
    "lm_head.weight",
)


@jax.tree_util.register_pytree_node_class
class QuantizedLeaf:
    """A quantized weight living where a float leaf used to.

    Registered as a pytree node, so ``jax.jit`` flattens it into its
    ``(values, scale)`` arrays transparently — the engine's state dict
    keeps its keys, ``tree_bytes`` counts the real storage, and
    ``device_put`` per child lets values and scales shard differently.
    ``axis`` is the channel axis the per-channel scales vary along;
    ``orig_dtype`` is the logical dtype :meth:`dequantize` restores.
    """

    __slots__ = ("q", "scale", "axis", "orig_dtype")

    def __init__(self, q, scale, axis: int, orig_dtype: str):
        self.q = q
        self.scale = scale
        self.axis = int(axis)
        self.orig_dtype = str(orig_dtype)

    def tree_flatten(self):
        return (self.q, self.scale), (self.axis, self.orig_dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, aux[0], aux[1])

    # logical view: code that sniffs a state leaf's shape/dtype (the
    # load_weights dtype guard, stats()) sees the pre-quantization tensor
    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return jnp.dtype(self.orig_dtype)

    @property
    def storage_dtype(self):
        return jnp.dtype(self.q.dtype)

    @property
    def nbytes(self) -> int:
        return int(self.q.nbytes) + int(self.scale.nbytes)

    def dequantize(self):
        """``values * scales`` back in ``orig_dtype`` — called inside
        the compiled step, where XLA fuses it into the consumer."""
        bshape = [1] * self.q.ndim
        bshape[self.axis] = -1
        w = self.q.astype(jnp.float32) * self.scale.reshape(bshape)
        return w.astype(self.orig_dtype)

    def __repr__(self):
        return (f"QuantizedLeaf(shape={tuple(self.q.shape)}, "
                f"storage={self.q.dtype}, axis={self.axis}, "
                f"orig={self.orig_dtype})")


def _storage_dtype(mode: str):
    name, bound = WEIGHT_MODES[mode]
    dt = getattr(jnp, name, None) if name.startswith("float8") else \
        jnp.dtype(name)
    if dt is None:
        raise RuntimeError(
            f"weight mode {mode!r} needs jnp.{name}, which this jax "
            f"does not provide — use int8_wo")
    return jnp.dtype(dt), bound


def quantize_leaf(arr, mode: str, axis: int = 1) -> QuantizedLeaf:
    """Symmetric per-channel quantization of one 2-D weight: absmax
    grid along ``axis`` (the output-channel axis of an ``[in, out]``
    projection), computed in f32."""
    dt, bound = _storage_dtype(mode)
    f = jnp.asarray(arr).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(f), axis=tuple(
        i for i in range(f.ndim) if i != axis))
    scale = jnp.maximum(absmax, 1e-12) / bound
    bshape = [1] * f.ndim
    bshape[axis] = -1
    g = f / scale.reshape(bshape)
    if dt == jnp.dtype(jnp.int8):
        q = jnp.clip(jnp.round(g), -bound, bound).astype(jnp.int8)
    else:
        q = g.astype(dt)
    return QuantizedLeaf(q, scale.astype(jnp.float32), axis,
                         str(jnp.asarray(arr).dtype))


# -- calibration --------------------------------------------------------------

def _tap_for_param(name: str) -> Optional[str]:
    """Map a qualified param name to the numerics tap whose range sketch
    judges its layer's activation health (``layers.{i}.attn`` for the
    attention projections, ``layers.{i}.mlp_act`` for the MLP)."""
    m = re.search(r"layers\.(\d+)\.", name)
    if m is None:
        return None
    i = m.group(1)
    leaf = name.rsplit(".", 2)[-2] if name.endswith(".weight") else ""
    if leaf in ("q_proj", "k_proj", "v_proj", "o_proj"):
        return f"layers.{i}.attn"
    if leaf in ("gate_proj", "up_proj", "down_proj"):
        return f"layers.{i}.mlp_act"
    return None


def _outlier_ratio_limit() -> float:
    try:
        return float(os.environ.get(
            "PADDLE_TPU_QUANT_OUTLIER_RATIO", "32.0"))
    except ValueError:
        return 32.0


def sensitive_params(names, calibration: Optional[dict],
                     ratio: Optional[float] = None) -> set:
    """Param names whose layer's calibration sketch shows outlier-heavy
    activations (absmax/p99 past the ratio) — left unquantized.
    ``calibration`` is a ``{"version": 1, "taps": {...}}`` summary
    (checkpoint ``"numerics"`` aux / :func:`calibrate`); None gates
    nothing."""
    if not calibration:
        return set()
    taps = calibration.get("taps") or {}
    limit = _outlier_ratio_limit() if ratio is None else float(ratio)
    out = set()
    for name in names:
        tap = _tap_for_param(name)
        sk = taps.get(tap) if tap else None
        if not sk:
            continue
        p99 = float(sk.get("p99") or 0.0)
        absmax = float(sk.get("absmax") or 0.0)
        if p99 > 0.0 and absmax / p99 > limit:
            out.add(name)
    return out


def calibrate(model, input_ids) -> dict:
    """One-batch calibration fallback when no training checkpoint's
    ``"numerics"`` aux exists: run a single eager forward under the
    numerics collector and shape the tap abs-maxes like the
    observatory's sketch summary (a single sample has no distribution,
    so p50/p99 collapse to the absmax)."""
    from paddle_tpu.core.autograd import no_grad
    from paddle_tpu.observability import numerics

    with no_grad(), numerics.collect(True) as col:
        model(input_ids)
    taps = {}
    for name, st in col.taps.items():
        absmax = float(jax.device_get(st[0]))
        taps[name] = {"n": 1, "absmax": absmax, "p50": absmax,
                      "p99": absmax, "buckets": {}}
    return {"version": 1, "taps": taps}


def calibration_from_checkpoint(path: str,
                                step: Optional[int] = None
                                ) -> Optional[dict]:
    """The ``"numerics"`` aux a training run committed alongside its
    weights (``FitResilience`` exports the observatory's sketches every
    checkpoint) — or None when the checkpoint predates the observatory."""
    import os as _os

    from paddle_tpu.checkpoint import load_state_dir
    if not _os.path.isdir(path):
        from paddle_tpu.framework.io import load
        state = load(path)
    else:
        state = load_state_dir(path, step=step)
    if isinstance(state, dict):
        doc = state.get("numerics")
        if isinstance(doc, dict) and doc.get("taps"):
            return doc
    return None


# -- state-dict quantization --------------------------------------------------

def default_target(name: str, arr) -> bool:
    """The default quantization surface: 2-D matmul projection weights.
    Embeddings stay (they are a gather, and the decode paths sniff their
    dtype); norms/biases/adapters stay (tiny, range-critical)."""
    if getattr(arr, "ndim", 0) != 2:
        return False
    return name.endswith(_DEFAULT_TARGET_SUFFIXES)


def quantize_state(state: Dict[str, object], mode: str, *,
                   calibration: Optional[dict] = None,
                   targets=None, axis: int = 1) -> Dict[str, object]:
    """Quantize the targeted leaves of a functional state dict.

    Returns a NEW dict whose selected leaves are :class:`QuantizedLeaf`
    (keys unchanged — ``swap_state`` name validation still holds).
    ``targets`` overrides the default name/shape predicate;
    ``calibration`` applies the sketch-based sensitivity gate."""
    if mode not in WEIGHT_MODES:
        raise ValueError(
            f"quantize mode {mode!r} (want one of "
            f"{sorted(WEIGHT_MODES)})")
    pred = targets or default_target
    picked = [k for k, v in state.items()
              if not isinstance(v, QuantizedLeaf) and pred(k, v)]
    skip = sensitive_params(picked, calibration)
    out = dict(state)
    for k in picked:
        if k in skip:
            continue
        out[k] = quantize_leaf(state[k], mode, axis=axis)
    m = quantization_metrics()
    m["weight_leaves"].set(sum(
        1 for v in out.values() if isinstance(v, QuantizedLeaf)))
    m["skipped_leaves"].set(len(skip))
    m["weight_bytes"].set(quantized_bytes(out))
    return out


def quantized_bytes(state: Dict[str, object]) -> int:
    """Bytes of quantized weight storage (values + scales) in a state."""
    return sum(v.nbytes for v in state.values()
               if isinstance(v, QuantizedLeaf))


def shard_quantized(leaf: QuantizedLeaf, mesh, spec):
    """Tensor-parallel placement of one quantized leaf: values carry the
    original weight's PartitionSpec, the 1-D scales the spec's entry at
    the channel axis (column-parallel → sharded scales, row-parallel →
    replicated — dequant stays collective-free either way)."""
    from jax.sharding import NamedSharding, PartitionSpec
    parts = tuple(spec) if spec is not None else ()
    scale_part = parts[leaf.axis] if leaf.axis < len(parts) else None
    q = jax.device_put(leaf.q, NamedSharding(
        mesh, spec if spec is not None else PartitionSpec()))
    s = jax.device_put(leaf.scale, NamedSharding(
        mesh, PartitionSpec(scale_part)))
    return QuantizedLeaf(q, s, leaf.axis, leaf.orig_dtype)


# -- metrics ------------------------------------------------------------------

_quant_metrics_cache = None


def quantization_metrics(registry=None) -> dict:
    """The ``quantization_*`` metric families (created on first use) —
    published by :func:`quantize_state` and the serving engine's KV
    quantization; names documented in docs/QUANTIZATION.md."""
    global _quant_metrics_cache
    if registry is None and _quant_metrics_cache is not None:
        return _quant_metrics_cache
    from paddle_tpu.observability import get_registry
    reg = registry if registry is not None else get_registry()
    d = {
        "weight_leaves": reg.gauge(
            "quantization_weight_leaves",
            "model state leaves stored as (values, scales) pairs"),
        "skipped_leaves": reg.gauge(
            "quantization_skipped_leaves",
            "target leaves left unquantized by the calibration "
            "sensitivity gate (activation absmax/p99 past the ratio)"),
        "weight_bytes": reg.gauge(
            "quantization_weight_bytes",
            "bytes of quantized weight storage, values + scales"),
        "kv_scale_bytes": reg.gauge(
            "quantization_kv_scale_bytes",
            "bytes of per-slot KV-cache dequantization scales"),
    }
    if registry is None:
        _quant_metrics_cache = d
    return d
