"""QuanterFactory (reference: ``python/paddle/quantization/factory.py``):
a deferred constructor so one QuantConfig instantiates fresh quanter
layers per wrapped layer."""
from __future__ import annotations

__all__ = ["QuanterFactory", "quanter"]


class QuanterFactory:
    def __init__(self, cls, *args, **kwargs):
        self._cls = cls
        self._args = args
        self._kwargs = kwargs

    def _instance(self, layer=None):
        # per-channel quanters need the wrapped layer to infer the channel
        # axis from the weight layout (Conv2D OIHW -> 0, Linear [in,out]
        # -> 1); classes opt in via _wants_layer
        if getattr(self._cls, "_wants_layer", False):
            return self._cls(*self._args, layer=layer, **self._kwargs)
        return self._cls(*self._args, **self._kwargs)

    def __call__(self, *args, **kwargs):
        return QuanterFactory(self._cls, *args, **kwargs)


def quanter(name=None):
    """Class decorator turning a quanter Layer class into a factory
    constructor (reference factory.py:quanter): ``MyQuanter(bits=8)``
    then yields a QuanterFactory for QuantConfig, instantiated fresh per
    wrapped layer."""
    def deco(cls):
        return QuanterFactory(cls)
    return deco
