"""TRUE int8 execution — quantized compute, not simulation.

The QAT/PTQ pipeline (reference: ``python/paddle/quantization``) produces
layers that FAKE-quantize in f32; the reference then executes real int8
in its inference engines (``paddle/fluid/inference/tensorrt/`` calibration
+ int8 kernels). The TPU answer is XLA's native s8×s8→s32 dot: v5e's MXU
runs int8 matmuls at 2× the bf16 rate (394 TOPS), and
``lax.dot_general(..., preferred_element_type=int32)`` lowers straight to
it. ``convert_to_int8`` rewrites a converted QAT/PTQ model's quanted
layers into :class:`Int8Linear`/:class:`Int8Conv2D`: weights are stored
AS int8 (4× smaller than f32 in HBM), activations quantize on entry with
the calibrated scale, the accumulator stays int32, and one f32 rescale
(s_x·s_w/bound²) finishes the op.

Numerics match the fake-quant simulation bit-for-bit while the int32
accumulator image fits f32 (small K); at depth they agree to the f32
rounding of the simulation — the INT path is the better-defined one.
"""
from __future__ import annotations

import copy

import numpy as np

from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import Layer

from .wrapper import QuantedConv2D, QuantedLinear

__all__ = ["Int8Linear", "Int8Conv2D", "convert_to_int8", "quantize_arr"]


def quantize_arr(x, scale, bits: int = 8, axis=None):
    """f32 array -> (int8 array) with the fake-quant grid:
    q = clip(round(x/s·bound), ±bound), dequant step s/bound. The
    expression ASSOCIATES exactly like quanters.fake_quant_ste
    (round(x / s * bound)) — a pre-divided bound/s factor can flip
    round() by one step near .5 boundaries and break bit-identity with
    the simulation. ``scale`` may be a per-channel vector along ``axis``
    (broadcast against ``x``); scalar when ``axis`` is None."""
    import jax.numpy as jnp
    from .base import bcast_shape
    bound = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(jnp.asarray(scale, jnp.float32), 1e-9)
    if axis is not None and s.ndim == 1:
        s = s.reshape(bcast_shape(x.ndim, axis))
    return jnp.clip(jnp.round(x / s * bound), -bound,
                    bound).astype(jnp.int8)


class _Int8Base(Layer):
    def __init__(self, w_q, w_scale, x_scale: float, bias,
                 x_bits: int = 8, w_bits: int = 8, w_axis=None):
        """``w_scale`` is a scalar (per-tensor) or a 1-D per-output-channel
        vector with ``w_axis`` naming the weight's channel axis (reference
        default PTQ weight quantizer is per-channel —
        ``quantization/imperative/ptq_quantizer.py:137``); activation
        scales are per-tensor always."""
        super().__init__()
        import jax.numpy as jnp
        w_scale = np.asarray(w_scale, np.float32)
        # per-channel: an individual zero scale is a legitimately pruned
        # (all-zero) channel — clamp it like fake_quant_ste does; only a
        # FULLY non-positive scale set means calibration never ran
        if x_scale <= 0 or not (w_scale > 0).any():
            raise ValueError(
                "int8 conversion needs calibrated positive scales; run "
                "PTQ calibration (or QAT) before convert_to_int8")
        w_scale = np.maximum(w_scale, 1e-9)
        # separate activation/weight bit widths: a 4-bit weight grid still
        # STORES as int8 (values in [-7, 7]) but dequantizes with its own
        # bound, matching the fake-quant simulation exactly
        self.x_bits = int(x_bits)
        self.w_bits = int(w_bits)
        self._x_bound = float(2 ** (x_bits - 1) - 1)
        self._w_bound = float(2 ** (w_bits - 1) - 1)
        self.w_scale = float(w_scale) if w_scale.ndim == 0 else w_scale
        self.w_axis = None if w_axis is None else int(w_axis)
        self.x_scale = float(x_scale)
        # int8 weights live as a BUFFER: frozen deployment artifact, 4x
        # smaller than f32 in HBM and checkpoints
        self.register_buffer("w_q", Tensor(jnp.asarray(w_q, jnp.int8)))
        self.register_buffer(
            "bias", None if bias is None else
            Tensor(jnp.asarray(bias.data if hasattr(bias, "data")
                               else bias)))

    def _quant_in(self, x):
        return quantize_arr(x, self.x_scale, self.x_bits)

    @property
    def _rescale(self):
        """Scalar, or a per-output-channel vector the forward broadcasts
        along the output's channel axis."""
        return (self.x_scale / self._x_bound) * \
            (self.w_scale / self._w_bound)


class Int8Linear(_Int8Base):
    """y = dequant(s8(x) @ s8(w) -> s32) + bias, one f32 rescale
    (per-channel: the rescale vector broadcasts over the output axis)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.w_axis is not None and self.w_axis not in (1, -1):
            raise ValueError(
                "Int8Linear per-channel scales must be along the OUTPUT "
                f"axis of the [in, out] weight (axis 1), got {self.w_axis}")

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        w = self.w_q.data
        rescale = self._rescale
        bias = None if self.bias is None else self.bias.data

        def f(xa):
            xq = self._quant_in(xa)
            acc = jax.lax.dot_general(
                xq, w, (((xa.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * rescale
            if bias is not None:
                y = y + bias
            return y.astype(xa.dtype)

        return apply_op(f, x, op_name="int8_linear")


def _norm2(v):
    return (int(v), int(v)) if isinstance(v, int) else tuple(
        int(i) for i in v)


def _norm_pad(padding):
    """Conv2D padding forms -> lax padding: 'SAME'/'VALID' pass through
    (lax accepts them), int, [h, w], flat [h_lo, h_hi, w_lo, w_hi] (same
    rules as F.conv2d's _conv_nd)."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * 2
    p = [int(i) for i in padding]
    if len(p) == 2:
        return [(p[0], p[0]), (p[1], p[1])]
    if len(p) == 4:
        return [(p[0], p[1]), (p[2], p[3])]
    raise ValueError(f"unsupported Conv2D padding for int8: {padding!r}")


class Int8Conv2D(_Int8Base):
    """int8 conv with an s32 accumulator (XLA integer conv); weights stay
    in paddle's OIHW layout, the data layout follows the source layer."""

    def __init__(self, w_q, w_scale, x_scale, bias, stride, padding,
                 dilation, groups, data_format: str = "NCHW",
                 x_bits: int = 8, w_bits: int = 8, w_axis=None):
        super().__init__(w_q, w_scale, x_scale, bias, x_bits, w_bits,
                         w_axis)
        if self.w_axis is not None and self.w_axis not in (0, -4):
            raise ValueError(
                "Int8Conv2D per-channel scales must be along the OUTPUT "
                f"axis of the OIHW weight (axis 0), got {self.w_axis}")
        self.stride = _norm2(stride)
        self.padding = _norm_pad(padding)
        self.dilation = _norm2(dilation)
        self.groups = int(groups)
        if data_format not in ("NCHW", "NHWC"):
            raise ValueError(f"unsupported data_format {data_format!r}")
        self.data_format = data_format

    def forward(self, x):
        import jax
        import jax.numpy as jnp
        w = self.w_q.data
        rescale = self._rescale
        bias = None if self.bias is None else self.bias.data
        stride, padding = self.stride, self.padding
        dilation, groups = self.dilation, self.groups
        fmt = self.data_format

        def f(xa):
            xq = self._quant_in(xa)
            acc = jax.lax.conv_general_dilated(
                xq, w, window_strides=stride, padding=padding,
                rhs_dilation=dilation, feature_group_count=groups,
                dimension_numbers=(fmt, "OIHW", fmt),
                preferred_element_type=jnp.int32)
            shape = (1, -1, 1, 1) if fmt == "NCHW" else (1, 1, 1, -1)
            rs = rescale if np.ndim(rescale) == 0 \
                else jnp.reshape(jnp.asarray(rescale), shape)
            y = acc.astype(jnp.float32) * rs
            if bias is not None:
                y = y + bias.reshape(shape)
            return y.astype(xa.dtype)

        return apply_op(f, x, op_name="int8_conv2d")


def _scales_of(quanted) -> tuple:
    aq, wq = quanted.activation_quanter, quanted.weight_quanter
    if aq is None or wq is None:
        raise ValueError(
            "convert_to_int8 needs BOTH activation and weight quanters "
            "(calibrated PTQ.convert / QAT.convert output)")
    a_s = np.asarray(aq.scales().numpy(), np.float32)
    if a_s.size != 1:
        raise ValueError(
            "convert_to_int8 supports per-tensor ACTIVATION quanters "
            f"only (got {a_s.size} activation scales); per-channel "
            "quantization applies to weights")
    from .base import channel_axis_of
    w_s = np.asarray(wq.scales().numpy(), np.float32)
    w_axis = channel_axis_of(wq, "weight quanter") if w_s.ndim else None
    return (float(a_s.reshape(())), w_s if w_s.ndim else float(w_s),
            aq.bit_length(), wq.bit_length(), w_axis)


def convert_to_int8(model: Layer, inplace: bool = False) -> Layer:
    """Rewrite a converted QAT/PTQ model for real int8 execution.

    Every :class:`QuantedLinear`/:class:`QuantedConv2D` (fake-quant
    simulation) becomes :class:`Int8Linear`/:class:`Int8Conv2D` with
    pre-quantized int8 weights and the calibrated activation scale frozen
    in. The reference reaches this form through its TensorRT calibration
    + int8 engine build; here it is a Layer-tree rewrite and XLA does the
    rest."""
    if not inplace:
        model = copy.deepcopy(model)
    _walk(model)
    model.eval()
    return model


def _walk(model: Layer):
    for name, child in list(model._sub_layers.items()):
        if isinstance(child, QuantedLinear):
            s_x, s_w, x_bits, w_bits, w_axis = _scales_of(child)
            w_q = quantize_arr(child.weight.data, s_w, w_bits, w_axis)
            model._sub_layers[name] = Int8Linear(
                w_q, s_w, s_x, child.bias, x_bits, w_bits, w_axis)
        elif isinstance(child, QuantedConv2D):
            s_x, s_w, x_bits, w_bits, w_axis = _scales_of(child)
            lyr = child._layer
            w_q = quantize_arr(child.weight.data, s_w, w_bits, w_axis)
            model._sub_layers[name] = Int8Conv2D(
                w_q, s_w, s_x, child.bias, lyr._stride, lyr._padding,
                lyr._dilation, lyr._groups,
                getattr(lyr, "_data_format", "NCHW"),
                x_bits, w_bits, w_axis)
        else:
            _walk(child)
