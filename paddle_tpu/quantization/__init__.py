"""paddle.quantization parity — new-style QAT/PTQ framework.

Reference: ``python/paddle/quantization/`` (``config.py`` QuantConfig,
``qat.py`` QAT, ``ptq.py`` PTQ, ``quanters/abs_max.py``,
``observers/abs_max.py``, ``wrapper.py``).

TPU notes: fake-quant is a pure elementwise jnp composition (XLA fuses it
into the surrounding matmul), and the straight-through estimator is the
classic ``x + stop_gradient(q - x)`` identity — no custom kernel needed.
"""
from .config import QuantConfig, SingleLayerConfig  # noqa: F401
from .factory import QuanterFactory, quanter  # noqa: F401
from .base import BaseQuanter, BaseObserver  # noqa: F401
from .quanters import FakeQuanterWithAbsMaxObserver  # noqa: F401
from .observers import AbsmaxObserver, PerChannelAbsmaxObserver  # noqa: F401
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .wrapper import QuantedLinear, QuantedConv2D  # noqa: F401
from .int8 import (  # noqa: F401
    Int8Linear, Int8Conv2D, convert_to_int8,
)
from .weight_only import (  # noqa: F401
    QuantizedLeaf, WEIGHT_MODES, quantize_state, quantized_bytes,
    calibrate, calibration_from_checkpoint, quantization_metrics,
)

__all__ = ["QuantConfig", "SingleLayerConfig", "QuanterFactory", "quanter",
           "BaseQuanter", "BaseObserver", "FakeQuanterWithAbsMaxObserver",
           "AbsmaxObserver", "PerChannelAbsmaxObserver", "QAT", "PTQ",
           "QuantedLinear",
           "QuantedConv2D", "Int8Linear", "Int8Conv2D", "convert_to_int8",
           "QuantizedLeaf", "WEIGHT_MODES", "quantize_state",
           "quantized_bytes", "calibrate", "calibration_from_checkpoint",
           "quantization_metrics"]
