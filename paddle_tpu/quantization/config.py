"""QuantConfig (reference: ``python/paddle/quantization/config.py``) —
maps layers / names / types to (activation, weight) quanter factories,
with per-layer overrides taking priority over per-name over per-type."""
from __future__ import annotations

from typing import Optional

from paddle_tpu.nn import Layer

from .factory import QuanterFactory

__all__ = ["SingleLayerConfig", "QuantConfig"]


class SingleLayerConfig:
    def __init__(self, activation: Optional[QuanterFactory],
                 weight: Optional[QuanterFactory]):
        self._activation = activation
        self._weight = weight

    @property
    def activation(self):
        return self._activation

    @property
    def weight(self):
        return self._weight

    def __str__(self):
        return f"activation: {self._activation}\nweight: {self._weight}"


class QuantConfig:
    def __init__(self, activation: Optional[QuanterFactory] = None,
                 weight: Optional[QuanterFactory] = None):
        if activation is None and weight is None:
            self._global_config = None
        else:
            self._global_config = SingleLayerConfig(activation, weight)
        self._layer2config = {}   # id(layer) -> config
        self._name2config = {}
        self._type2config = {}
        self._qat_layer_mapping = dict(_default_qat_mapping())
        self._customized_leaves = []

    # -- configuration surface (config.py:96,140,183) -------------------------
    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for lyr in layers:
            self._layer2config[id(lyr)] = SingleLayerConfig(activation,
                                                            weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) \
            else [layer_name]
        for n in names:
            self._name2config[n] = SingleLayerConfig(activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type2config[t] = SingleLayerConfig(activation, weight)

    def add_qat_layer_mapping(self, source: type, target: type):
        assert issubclass(source, Layer)
        self._qat_layer_mapping[source] = target

    def add_customized_leaf(self, layer_type: type):
        self._customized_leaves.append(layer_type)

    @property
    def qat_layer_mappings(self):
        return self._qat_layer_mapping

    @property
    def customized_leaves(self):
        return self._customized_leaves

    # -- resolution ------------------------------------------------------------
    def _get_config_by_layer(self, layer, name: str = "",
                             orig_layer=None) -> Optional[SingleLayerConfig]:
        """``name`` is the FULL dotted path (the reference matches
        full_name()); ``orig_layer`` is the pre-deepcopy layer so
        add_layer_config identities survive quantize(inplace=False)."""
        for key in (id(layer), id(orig_layer) if orig_layer is not None
                    else None):
            if key is not None and key in self._layer2config:
                return self._layer2config[key]
        if name in self._name2config:
            return self._name2config[name]
        for t, cfg in self._type2config.items():
            if isinstance(layer, t):
                return cfg
        if type(layer) in self._qat_layer_mapping:
            return self._global_config
        return None

    def _is_quantifiable(self, layer, name: str = "",
                         orig_layer=None) -> bool:
        return self._get_config_by_layer(layer, name, orig_layer) \
            is not None and type(layer) in self._qat_layer_mapping


def _default_qat_mapping():
    from paddle_tpu import nn
    from .wrapper import QuantedConv2D, QuantedLinear
    return {nn.Linear: QuantedLinear, nn.Conv2D: QuantedConv2D}
