"""Checkpoint resharding across parallel plans (reference:
``python/paddle/distributed/auto_parallel/converter.py`` Converter — merge
per-rank shards under the previous distributed attributes, re-slice under
the current ones; SURVEY.md §5 names this "the piece a TPU build must own
well").

Dist-attr schema matches the reference: ``{"process_shape": [..],
"process_group": [ranks..], "dims_mapping": [mesh-dim per tensor-dim,
-1 = replicated]}``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Converter", "pipeline_state_to_spmd", "spmd_state_to_pipeline",
           "uniform_chunk_bounds"]


def _rank_coord(rank_pos: int, process_shape: Sequence[int]) -> List[int]:
    coord = []
    rem = rank_pos
    for s in reversed(process_shape):
        coord.append(rem % s)
        rem //= s
    return list(reversed(coord))


def _shard_slices(full_shape, dims_mapping, process_shape, rank_pos):
    coord = _rank_coord(rank_pos, process_shape)
    slices = []
    for dim, size in enumerate(full_shape):
        m = dims_mapping[dim] if dim < len(dims_mapping) else -1
        if m == -1:
            slices.append(slice(None))
        else:
            parts = process_shape[m]
            if size % parts != 0:
                raise ValueError(
                    f"dim {dim} of size {size} not divisible by mesh dim "
                    f"{m} ({parts} parts)")
            step = size // parts
            start = coord[m] * step
            slices.append(slice(start, start + step))
    return tuple(slices)


class Converter:
    """``convert()`` turns per-rank shard lists saved under ``pre_strategy``
    into the shards required by ``cur_strategy`` (reference surface:
    converter.py Converter.__init__/convert)."""

    def __init__(self, tensors_dict: Dict[str, List[np.ndarray]],
                 pre_strategy: Dict[str, dict],
                 cur_strategy: Dict[str, dict]):
        if not tensors_dict:
            raise ValueError("tensors_dict is empty")
        if not pre_strategy:
            raise ValueError("pre_strategy is empty")
        if not cur_strategy:
            raise ValueError("cur_strategy is empty")
        self._tensors_dict = tensors_dict
        self._pre_strategy = pre_strategy
        self._cur_strategy = cur_strategy

    # -- merge: shards + old dist attr -> full tensor ------------------------
    @staticmethod
    def merge_with_dist_attr(shards: List[np.ndarray],
                             dist_attr: dict) -> np.ndarray:
        process_shape = dist_attr["process_shape"]
        group = dist_attr["process_group"]
        dims_mapping = dist_attr["dims_mapping"]
        if len(shards) != len(group):
            raise ValueError(
                f"{len(shards)} shards for a process group of {len(group)}")
        s0 = np.asarray(shards[0])
        full_shape = list(s0.shape)
        for dim, m in enumerate(dims_mapping):
            if m != -1:
                full_shape[dim] = s0.shape[dim] * process_shape[m]
        full = np.empty(full_shape, s0.dtype)
        for pos, shard in enumerate(shards):
            full[_shard_slices(full_shape, dims_mapping, process_shape,
                               pos)] = np.asarray(shard)
        return full

    # -- slice: full tensor + new dist attr -> this rank's shard -------------
    @staticmethod
    def slice_with_dist_attr(tensor: np.ndarray, dist_attr: dict,
                             rank: int) -> np.ndarray:
        process_shape = dist_attr["process_shape"]
        group = dist_attr["process_group"]
        dims_mapping = dist_attr["dims_mapping"]
        if rank not in group:
            raise ValueError(f"rank {rank} not in process group {group}")
        pos = group.index(rank)
        return np.ascontiguousarray(
            tensor[_shard_slices(tensor.shape, dims_mapping, process_shape,
                                 pos)])

    def convert(self, rank: int = 0,
                strict: bool = True) -> Dict[str, np.ndarray]:
        """Merge every tensor under pre_strategy and slice it for ``rank``
        under cur_strategy. With ``strict=False`` tensors missing from
        either strategy pass through unchanged (reference
        convert_with_prefix_match relaxation)."""
        out = {}
        for name, shards in self._tensors_dict.items():
            pre = self._pre_strategy.get(name)
            cur = self._cur_strategy.get(name)
            if pre is None or cur is None:
                if strict:
                    raise ValueError(
                        f"tensor '{name}' missing from "
                        f"{'pre' if pre is None else 'cur'}_strategy")
                out[name] = np.asarray(shards[0])
                continue
            full = self.merge_with_dist_attr(shards, pre)
            out[name] = self.slice_with_dist_attr(full, cur, rank)
        return out


# ===================== pipeline-layout conversion ============================
# The SPMD pipeline stores the trunk STACKED — one parameter per template
# name with leading [v, S] chunk axes (``fleet/spmd_pipeline.py``), keys
# mangled ``name.replace('.', '__')`` — while the host PipelineLayer (and a
# plain sequential trunk) keep per-layer entries ``layers.{i}.{param}``.
# These converters re-shape checkpoints between the three layouts so a pod
# training run (spmd) can resume/fine-tune/serve single-host (host engine
# or plain model) from the same artifact, completing the reference
# Converter surface (``auto_parallel/converter.py:25``) for the pipeline
# case. Chunk c = r*S + s sits at stacked index [r, s] (the Megatron
# round-robin placement both engines share).


def _to_np(v):
    if hasattr(v, "numpy"):
        return np.asarray(v.numpy())
    return np.asarray(v)


def uniform_chunk_bounds(n_layers: int, num_chunks: int) -> List[int]:
    """The host engine's default 'uniform' segmentation boundaries."""
    base, rem = divmod(n_layers, num_chunks)
    bounds = [0]
    for c in range(num_chunks):
        bounds.append(bounds[-1] + base + (1 if c < rem else 0))
    return bounds


def pipeline_state_to_spmd(state: Dict, num_stages: int,
                           num_virtual_stages: int = 1,
                           bounds: Optional[Sequence[int]] = None,
                           prefix: str = "layers.",
                           block_is_container: bool = True) -> Dict:
    """Host-PipelineLayer / plain-trunk state_dict -> SpmdPipelineLayer
    state_dict.

    ``prefix`` strips the per-layer key prefix (``"layers."`` for the host
    engine's LayerList; ``""`` for a bare Sequential trunk). ``bounds`` are
    the chunk segmentation boundaries (default: uniform). With
    ``block_is_container`` the spmd block_factory wraps each chunk's
    layers in a container (child j of chunk c = trunk layer
    ``bounds[c]+j``); otherwise chunks are single bare layers."""
    S, v = num_stages, num_virtual_stages
    num_chunks = S * v
    sub: Dict[int, Dict[str, np.ndarray]] = {}
    for key, val in state.items():
        if prefix:
            if not key.startswith(prefix):
                raise ValueError(
                    f"key {key!r} lacks trunk prefix {prefix!r} — pass the "
                    "trunk sub-dict (embedding/head live outside the "
                    "pipelined region)")
            key = key[len(prefix):]
        idx_str, rest = key.split(".", 1)
        sub.setdefault(int(idx_str), {})[rest] = _to_np(val)
    n_layers = max(sub) + 1
    bounds = list(bounds) if bounds is not None else \
        uniform_chunk_bounds(n_layers, num_chunks)
    if len(bounds) != num_chunks + 1 or bounds[-1] != n_layers:
        raise ValueError(
            f"bounds {bounds} do not segment {n_layers} layers into "
            f"{num_chunks} chunks")
    if not block_is_container and \
            any(bounds[c + 1] - bounds[c] > 1 for c in range(num_chunks)):
        raise ValueError(
            "block_is_container=False requires exactly one trunk layer "
            "per chunk (multi-layer chunks need a container block)")
    stacked: Dict[str, List[np.ndarray]] = {}
    for c in range(num_chunks):
        for j, i in enumerate(range(bounds[c], bounds[c + 1])):
            # index holes are parameter-less trunk layers (ReLU, Tanh):
            # they occupy a segment slot but contribute no state
            for rest, arr in sub.get(i, {}).items():
                name = f"{j}.{rest}" if block_is_container else rest
                skey = name.replace(".", "__")
                stacked.setdefault(skey, [None] * num_chunks)[c] = arr
    out = {}
    for skey, chunks in stacked.items():
        missing = [c for c, a in enumerate(chunks) if a is None]
        if missing:
            raise ValueError(
                f"param {skey!r} missing from chunks {missing} — the "
                "spmd trunk must be homogeneous")
        arr = np.stack(chunks)          # [v*S, ...]
        out[skey] = arr.reshape((v, S) + arr.shape[1:])
    return out


def spmd_state_to_pipeline(state: Dict, num_stages: int,
                           num_virtual_stages: int = 1,
                           bounds: Optional[Sequence[int]] = None,
                           prefix: str = "layers.",
                           block_is_container: bool = True) -> Dict:
    """SpmdPipelineLayer state_dict -> host-PipelineLayer / plain-trunk
    state_dict (the inverse of :func:`pipeline_state_to_spmd`)."""
    S, v = num_stages, num_virtual_stages
    num_chunks = S * v
    out: Dict[str, np.ndarray] = {}
    per_chunk = None
    for skey, val in state.items():
        arr = _to_np(val)
        if arr.ndim < 2 or arr.shape[:2] != (v, S):
            raise ValueError(
                f"param {skey!r} shape {arr.shape} does not lead with "
                f"[v={v}, S={S}] — not an spmd-pipeline checkpoint")
        name = skey.replace("__", ".")
        if block_is_container:
            j_str, rest = name.split(".", 1)
            j = int(j_str)
        else:
            j, rest = 0, name
        flat = arr.reshape((num_chunks,) + arr.shape[2:])
        if per_chunk is None:
            per_chunk = {}
        for c in range(num_chunks):
            per_chunk.setdefault(c, {})[(j, rest)] = flat[c]
    if per_chunk is None:
        raise ValueError("empty spmd state")
    layers_per_chunk = 1 + max(j for d in per_chunk.values() for j, _ in d)
    n_layers = num_chunks * layers_per_chunk if bounds is None else \
        bounds[-1]
    bounds = list(bounds) if bounds is not None else \
        uniform_chunk_bounds(n_layers, num_chunks)
    for c in range(num_chunks):
        width = bounds[c + 1] - bounds[c]
        for (j, rest), arr in per_chunk[c].items():
            if j >= width:
                raise ValueError(
                    f"chunk {c} child {j} exceeds its segment width "
                    f"{width} under bounds {bounds}")
            out[f"{prefix}{bounds[c] + j}.{rest}"] = arr
    return out
