"""Checkpoint resharding across parallel plans (reference:
``python/paddle/distributed/auto_parallel/converter.py`` Converter — merge
per-rank shards under the previous distributed attributes, re-slice under
the current ones; SURVEY.md §5 names this "the piece a TPU build must own
well").

Dist-attr schema matches the reference: ``{"process_shape": [..],
"process_group": [ranks..], "dims_mapping": [mesh-dim per tensor-dim,
-1 = replicated]}``.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

__all__ = ["Converter"]


def _rank_coord(rank_pos: int, process_shape: Sequence[int]) -> List[int]:
    coord = []
    rem = rank_pos
    for s in reversed(process_shape):
        coord.append(rem % s)
        rem //= s
    return list(reversed(coord))


def _shard_slices(full_shape, dims_mapping, process_shape, rank_pos):
    coord = _rank_coord(rank_pos, process_shape)
    slices = []
    for dim, size in enumerate(full_shape):
        m = dims_mapping[dim] if dim < len(dims_mapping) else -1
        if m == -1:
            slices.append(slice(None))
        else:
            parts = process_shape[m]
            if size % parts != 0:
                raise ValueError(
                    f"dim {dim} of size {size} not divisible by mesh dim "
                    f"{m} ({parts} parts)")
            step = size // parts
            start = coord[m] * step
            slices.append(slice(start, start + step))
    return tuple(slices)


class Converter:
    """``convert()`` turns per-rank shard lists saved under ``pre_strategy``
    into the shards required by ``cur_strategy`` (reference surface:
    converter.py Converter.__init__/convert)."""

    def __init__(self, tensors_dict: Dict[str, List[np.ndarray]],
                 pre_strategy: Dict[str, dict],
                 cur_strategy: Dict[str, dict]):
        if not tensors_dict:
            raise ValueError("tensors_dict is empty")
        if not pre_strategy:
            raise ValueError("pre_strategy is empty")
        if not cur_strategy:
            raise ValueError("cur_strategy is empty")
        self._tensors_dict = tensors_dict
        self._pre_strategy = pre_strategy
        self._cur_strategy = cur_strategy

    # -- merge: shards + old dist attr -> full tensor ------------------------
    @staticmethod
    def merge_with_dist_attr(shards: List[np.ndarray],
                             dist_attr: dict) -> np.ndarray:
        process_shape = dist_attr["process_shape"]
        group = dist_attr["process_group"]
        dims_mapping = dist_attr["dims_mapping"]
        if len(shards) != len(group):
            raise ValueError(
                f"{len(shards)} shards for a process group of {len(group)}")
        s0 = np.asarray(shards[0])
        full_shape = list(s0.shape)
        for dim, m in enumerate(dims_mapping):
            if m != -1:
                full_shape[dim] = s0.shape[dim] * process_shape[m]
        full = np.empty(full_shape, s0.dtype)
        for pos, shard in enumerate(shards):
            full[_shard_slices(full_shape, dims_mapping, process_shape,
                               pos)] = np.asarray(shard)
        return full

    # -- slice: full tensor + new dist attr -> this rank's shard -------------
    @staticmethod
    def slice_with_dist_attr(tensor: np.ndarray, dist_attr: dict,
                             rank: int) -> np.ndarray:
        process_shape = dist_attr["process_shape"]
        group = dist_attr["process_group"]
        dims_mapping = dist_attr["dims_mapping"]
        if rank not in group:
            raise ValueError(f"rank {rank} not in process group {group}")
        pos = group.index(rank)
        return np.ascontiguousarray(
            tensor[_shard_slices(tensor.shape, dims_mapping, process_shape,
                                 pos)])

    def convert(self, rank: int = 0,
                strict: bool = True) -> Dict[str, np.ndarray]:
        """Merge every tensor under pre_strategy and slice it for ``rank``
        under cur_strategy. With ``strict=False`` tensors missing from
        either strategy pass through unchanged (reference
        convert_with_prefix_match relaxation)."""
        out = {}
        for name, shards in self._tensors_dict.items():
            pre = self._pre_strategy.get(name)
            cur = self._cur_strategy.get(name)
            if pre is None or cur is None:
                if strict:
                    raise ValueError(
                        f"tensor '{name}' missing from "
                        f"{'pre' if pre is None else 'cur'}_strategy")
                out[name] = np.asarray(shards[0])
                continue
            full = self.merge_with_dist_attr(shards, pre)
            out[name] = self.slice_with_dist_attr(full, cur, rank)
        return out
