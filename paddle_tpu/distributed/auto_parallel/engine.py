"""auto_parallel Engine (reference:
``python/paddle/distributed/auto_parallel/engine.py:56`` — Engine drives
``_build:513 → _plan:670 → _parallel:698`` then fit/evaluate/predict).

Here _build+_plan+_parallel collapse into one SPMD ``TrainStep``
compilation: the mesh comes from the user (or defaults to pure DP over
all devices), parameter shardings come from ``shard_tensor`` annotations,
batch shardings from ``input_spec``, and XLA GSPMD performs the
completion/partition/reshard the reference implements in Python.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["Engine"]


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        from .strategy import Strategy

        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = list(metrics) if metrics is not None else []
        self._strategy = strategy or Strategy()
        self._mesh = None
        self._train_step = None
        self._predict_fn = None
        self.history = {"loss": []}

    # -- planning --------------------------------------------------------------
    def _ensure_mesh(self):
        import paddle_tpu.distributed as dist
        if self._mesh is None:
            self._mesh = dist.get_mesh() or dist.init_mesh()
        return self._mesh

    def prepare(self, mesh=None, input_spec=None, auto=False,
                n_devices=None, model_desc=None, cluster=None,
                batch_shape=None):
        """Fix the mesh (and batch sharding) ahead of fit; optional — fit
        defaults to sharding batch dim 0 over the mesh's first axis.

        ``auto=True`` runs the parallel-plan search instead (reference:
        ``planner_v2.py`` Planner / ``tuner/parallel_tuner.py``): the
        :class:`~.planner.Planner` enumerates mesh factorizations of
        ``n_devices`` (default: all visible devices), scores them with the
        cost model, and installs the winner — mesh, batch spec, generic
        mp weight shardings, and ZeRO wrapping if the plan says so. The
        batch shape comes from ``batch_shape`` now or from the first fit
        batch (generic models without a ``model_desc`` always defer to
        the first batch — measuring FLOPs needs real example inputs).
        ``model_desc`` (a :class:`~.planner.ModelDesc`) overrides the
        model introspection; ``cluster`` the hardware description."""
        import paddle_tpu.distributed as dist
        if auto:
            self._auto_cfg = {"n_devices": n_devices,
                              "model_desc": model_desc, "cluster": cluster}
            if batch_shape is not None:
                self._run_planner(tuple(batch_shape))
            return self
        if mesh is not None:
            self._mesh = mesh.to_jax() if hasattr(mesh, "to_jax") else mesh
            dist.set_mesh(self._mesh)
        self._input_spec = input_spec
        return self

    @property
    def plan(self):
        """The winning :class:`~.planner.ParallelPlan` (auto mode only)."""
        return getattr(self, "_plan", None)

    def _run_planner(self, batch_shape, example_batch=None):
        import jax

        from .planner import ModelDesc, Planner, auto_shard_params

        cfg = self._auto_cfg
        desc = cfg["model_desc"]
        if desc is None:
            model_cfg = getattr(self._model, "cfg", None)
            if model_cfg is not None and \
                    type(model_cfg).__name__ == "LlamaConfig":
                desc = ModelDesc.from_llama(model_cfg)
            elif example_batch is not None:
                desc = ModelDesc.from_model(self._model,
                                            example_args=example_batch,
                                            cluster=cfg["cluster"])
            else:
                # generic model, shape only: FLOPs need a real example
                # batch — defer planning to the first fit batch
                return None
        n = cfg["n_devices"] or jax.device_count()
        planner = Planner(desc, cluster=cfg["cluster"])
        plan = planner.plan(n, batch_shape)
        self._plan = plan
        self._planner = planner
        self._mesh = plan.build_mesh()
        self._input_spec = plan.input_spec
        if plan.mp > 1:
            auto_shard_params(self._model, self._mesh)
        if plan.zero:
            import paddle_tpu.distributed as dist
            self._model, self._optimizer, _ = dist.group_sharded_parallel(
                self._model, self._optimizer, level=plan.zero, axis="dp")
        return plan

    def _loss_fn(self):
        loss_layer = self._loss

        def fn(model, *batch):
            *inputs, label = batch
            out = model(*inputs)
            return loss_layer(out, label)
        return fn

    def _build_step(self):
        import paddle_tpu as pt
        from paddle_tpu.distributed import P

        mesh = self._ensure_mesh()
        spec = getattr(self, "_input_spec", None)
        if spec is None:
            spec = P(mesh.axis_names[0])
        self._train_step = pt.jit.TrainStep(
            self._model, self._loss_fn(), self._optimizer, mesh=mesh,
            input_spec=spec)
        return self._train_step

    def _loader(self, data, batch_size, drop_last=False):
        # drop_last only for the SPMD fit step (static batch shape);
        # evaluate/predict must see the tail samples
        from paddle_tpu.io import DataLoader, Dataset
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=False,
                              drop_last=drop_last)
        return data  # any iterable of batches

    @staticmethod
    def _to_tensors(batch):
        import paddle_tpu as pt
        from paddle_tpu.core.tensor import Tensor
        items = batch if isinstance(batch, (list, tuple)) else [batch]
        return [x if isinstance(x, Tensor) else pt.to_tensor(np.asarray(x))
                for x in items]

    # -- reference surface (engine.py fit:811 / evaluate / predict) ----------
    def fit(self, train_data, epochs: int = 1, batch_size: int = 1,
            steps_per_epoch: Optional[int] = None, log_freq: int = 10,
            verbose: int = 0):
        loader = self._loader(train_data, batch_size, drop_last=True)
        for epoch in range(epochs):
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                tensors = self._to_tensors(batch)
                if self._train_step is None:
                    if getattr(self, "_auto_cfg", None) is not None \
                            and self.plan is None:
                        inputs = tensors[:-1] if self._loss is not None \
                            and len(tensors) > 1 else tensors
                        self._run_planner(tuple(inputs[0].shape),
                                          example_batch=inputs)
                    self._build_step()
                loss = self._train_step(*tensors)
                val = float(loss.numpy())
                self.history["loss"].append(val)
                if verbose and step % log_freq == 0:
                    print(f"epoch {epoch} step {step}: loss {val:.5f}")
        return self.history

    def evaluate(self, valid_data, batch_size: int = 1, steps=None,
                 verbose: int = 0):
        import paddle_tpu as pt
        loader = self._loader(valid_data, batch_size)
        for m in self._metrics:
            m.reset()
        losses = []
        with pt.no_grad():
            for step, batch in enumerate(loader):
                if steps is not None and step >= steps:
                    break
                tensors = self._to_tensors(batch)
                *inputs, label = tensors
                out = self._model(*inputs)
                if self._loss is not None:
                    losses.append(float(self._loss(out, label).numpy()))
                for m in self._metrics:
                    c = m.compute(out, label)
                    m.update(*(c if isinstance(c, (tuple, list))
                               else (c,)))
        results = {"loss": float(np.mean(losses)) if losses else None}
        for m in self._metrics:
            name, acc = m.name(), m.accumulate()
            if isinstance(name, (list, tuple)):  # e.g. Accuracy(topk=(1,5))
                for n, a in zip(name, acc if isinstance(acc, (list, tuple))
                                else [acc]):
                    results[n] = a
            else:
                results[name] = acc
        return results

    def predict(self, test_data, batch_size: int = 1, steps=None):
        import paddle_tpu as pt
        loader = self._loader(test_data, batch_size)
        outs = []
        with pt.no_grad():
            for step, batch in enumerate(loader):
                if steps is not None and step >= steps:
                    break
                tensors = self._to_tensors(batch)
                # drop a trailing label only for engines configured with a
                # loss (fit/evaluate-style (inputs..., label) datasets);
                # loss-less engines are pure predictors — every element is
                # a model input (e.g. DiT's (x, t, y))
                if self._loss is not None and \
                        isinstance(batch, (list, tuple)) and len(tensors) > 1:
                    tensors = tensors[:-1]
                outs.append(self._model(*tensors).numpy())
        return outs

    def save(self, path: str):
        import paddle_tpu as pt
        pt.save(self._model.state_dict(), path + ".pdparams")
        if self._optimizer is not None and \
                hasattr(self._optimizer, "state_dict"):
            pt.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str):
        import paddle_tpu as pt
        self._model.set_state_dict(pt.load(path + ".pdparams"))
        import os
        if self._optimizer is not None and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(pt.load(path + ".pdopt"))
