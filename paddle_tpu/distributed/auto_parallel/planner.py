"""Automatic parallel-plan search — the framework picks the parallelism.

Reference surface: ``python/paddle/distributed/auto_parallel/planner_v2.py:21``
(``Planner`` — complete dist attrs, then search) and
``tuner/parallel_tuner.py:36`` (``ParallelTuner`` — enumerate candidate
dist-attr combinations over the cluster, score each with the cost model,
install the winner).

TPU-native redesign: XLA GSPMD already performs the per-op part of the
reference's search (the Completer/Partitioner/Resharder propagate any
consistent annotation), so the space that still needs SEARCH collapses to
the level where a user currently guesses by hand:

  * how to factor N devices into named mesh axes (``dp`` x ``mp``),
  * whether to ZeRO-shard optimizer state/grads/params over ``dp``,
  * where the batch dimension goes.

A plan is scored with the existing :class:`CostEstimator` machinery —
analytic compute/HBM roofline (XLA ``cost_analysis`` numbers or the model
family's closed-form FLOPs) plus the alpha-beta ring model for exactly the
collectives each axis implies:

  dp    -> one gradient all-reduce of the (mp-sharded) parameter bytes,
  zero  -> reduce-scatter + all-gather instead (same wire bytes, lower
           memory), plus a parameter all-gather each step for ``p_g_os``,
  mp    -> 4 activation all-reduces per layer per step (Megatron count:
           2 forward + 2 backward, column->row pairs),

and checked for HBM feasibility (weights + grads + optimizer state +
activation working set per device must fit).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .cost_model import Cluster, CommCost, CostEstimator

__all__ = ["ModelDesc", "ParallelPlan", "Planner", "auto_shard_params"]


@dataclass
class ModelDesc:
    """What the plan search needs to know about a model — either built
    from a zoo config (:meth:`from_llama`) or measured from any model
    (:meth:`from_model`, XLA ``cost_analysis`` via CostEstimator)."""

    param_bytes: float            # trainable parameter bytes (model dtype)
    flops_per_token: float        # forward FLOPs per token (2*MAC)
    num_layers: int               # trunk depth (mp collective count)
    hidden_size: int              # activation width at layer boundaries
    dtype_bytes: int = 2          # activation/param dtype width (bf16)
    max_mp: int = 1               # largest legal tensor-parallel degree
    act_multiplier: float = 8.0   # live activation copies per layer (rough;
    #                               ~2 with full recompute)
    seq_in_batch: bool = True     # inputs are [B, S, ...] (tokens = B*S)

    def tokens_of(self, batch_shape) -> int:
        """Token count of one global batch given the leading input's
        shape: [B, S, ...] for sequence models, [B, ...] otherwise."""
        if self.seq_in_batch and len(batch_shape) >= 2:
            return int(batch_shape[0]) * int(batch_shape[1])
        return int(batch_shape[0])

    def mp_legal(self, mp: int) -> bool:
        return mp <= self.max_mp and self.max_mp % mp == 0

    # -- constructors ---------------------------------------------------------
    @staticmethod
    def from_llama(cfg, dtype_bytes: int = 2) -> "ModelDesc":
        """Closed-form description of the zoo Llama family
        (``models/llama.py``); mp must divide heads, kv-heads, ffn and
        vocab (the mpu layers' shard dims)."""
        d, f, L = cfg.hidden_size, cfg.intermediate_size, \
            cfg.num_hidden_layers
        hd = d // cfg.num_attention_heads
        kv = cfg.num_key_value_heads * hd
        per_layer = d * (d + 2 * kv + d) + 3 * d * f + 2 * d
        n_params = L * per_layer + d + cfg.vocab_size * d
        if not cfg.tie_word_embeddings:
            n_params += cfg.vocab_size * d
        max_mp = 1
        while (cfg.num_attention_heads % (2 * max_mp) == 0
               and cfg.num_key_value_heads % (2 * max_mp) == 0
               and f % (2 * max_mp) == 0
               and cfg.vocab_size % (2 * max_mp) == 0):
            max_mp *= 2
        from paddle_tpu.models.llama import LlamaForCausalLM
        return ModelDesc(
            param_bytes=float(n_params) * dtype_bytes,
            flops_per_token=LlamaForCausalLM.flops_per_token(cfg),
            num_layers=L, hidden_size=d, dtype_bytes=dtype_bytes,
            max_mp=max_mp)

    @staticmethod
    def from_model(model, example_args=None, flops_per_token=None,
                   num_layers: Optional[int] = None,
                   hidden_size: Optional[int] = None,
                   max_mp: int = 1, seq_in_batch: bool = False,
                   cluster: Optional[Cluster] = None) -> "ModelDesc":
        """Generic description: parameter bytes from the model; forward
        FLOPs measured by compiling the model once single-device and
        reading XLA's own cost analysis (``CostEstimator.analyze`` — the
        round-2 leaf utility, now a planner input)."""
        import numpy as np

        params = list(model.parameters())
        if not params:
            raise ValueError("model has no trainable parameters to plan")
        dtype_bytes = int(np.dtype(str(params[0].data.dtype)).itemsize)
        param_bytes = float(sum(
            int(np.prod(p.shape)) * np.dtype(str(p.data.dtype)).itemsize
            for p in params))
        if flops_per_token is None:
            if example_args is None:
                raise ValueError(
                    "pass example_args (to measure forward FLOPs via XLA "
                    "cost_analysis) or flops_per_token")
            from paddle_tpu.jit.functional import functional_state, \
                swap_state
            from paddle_tpu.core.tensor import Tensor
            from paddle_tpu.core.autograd import no_grad

            train, frozen, buffers = functional_state(model)
            st = {**train, **frozen, **buffers}
            args = [a.data if isinstance(a, Tensor) else np.asarray(a)
                    for a in example_args]

            def fwd(stt, *xs):
                with no_grad(), swap_state(model, stt,
                                           collect_buffers=False):
                    out = model(*[Tensor(x) for x in xs])
                return out.data if isinstance(out, Tensor) else out

            est = CostEstimator(cluster)
            got = est.analyze(fwd, st, *args)
            shape = args[0].shape if args else (1,)
            n_tokens = int(shape[0]) * (int(shape[1])
                                        if seq_in_batch and len(shape) >= 2
                                        else 1)
            flops_per_token = got["flops"] / max(n_tokens, 1)
        if hidden_size is None:
            hidden_size = max(int(p.shape[-1]) for p in params)
        return ModelDesc(
            param_bytes=param_bytes, flops_per_token=float(flops_per_token),
            num_layers=num_layers or 1, hidden_size=int(hidden_size),
            dtype_bytes=dtype_bytes, max_mp=max_mp,
            seq_in_batch=seq_in_batch)


@dataclass
class ParallelPlan:
    """One point in the search space: a mesh factorization + ZeRO level
    (+ the batch axis), with its predicted cost after scoring."""

    mesh_shape: Dict[str, int]
    batch_axis: str = "dp"
    zero: Optional[str] = None          # None | "p_g_os"
    cost: Dict[str, float] = field(default_factory=dict)
    feasible: bool = True

    @property
    def dp(self) -> int:
        return self.mesh_shape.get("dp", 1)

    @property
    def mp(self) -> int:
        return self.mesh_shape.get("mp", 1)

    @property
    def input_spec(self):
        from jax.sharding import PartitionSpec
        return PartitionSpec(self.batch_axis)

    def build_mesh(self):
        """Install this plan's mesh as the process default (size-1 axes
        kept — the batch axis must exist even in a pure-mp plan). Plans
        smaller than the visible device count take a device-list prefix
        (planning for a sub-slice of the host)."""
        import jax
        import numpy as np

        import paddle_tpu.distributed as dist
        n = int(np.prod(list(self.mesh_shape.values())))
        devices = jax.devices()
        if n > len(devices):
            raise ValueError(
                f"plan needs {n} devices, {len(devices)} visible")
        return dist.init_mesh(dict(self.mesh_shape), devices=devices[:n])

    def describe(self) -> str:
        axes = "x".join(f"{k}{v}" for k, v in self.mesh_shape.items()
                        if v > 1) or "single"
        z = f"+zero({self.zero})" if self.zero else ""
        t = self.cost.get("seconds")
        cost = f" {t * 1e3:.3f}ms/step" if t is not None else ""
        feas = "" if self.feasible else " [OOM]"
        return f"{axes}{z}{cost}{feas}"


def auto_shard_params(model, mesh, mp_axis: str = "mp") -> int:
    """Generic weight-sharding rule for a chosen mp degree: annotate every
    still-unannotated >=2-D parameter with its LARGEST axis-divisible dim
    sharded over ``mp_axis`` (mpu layers that already annotated keep their
    placements). Sharding annotations never change semantics under GSPMD —
    they pick layouts and XLA inserts the collectives — so this is always
    correct; the planner's cost model decides when it is also fast.
    Returns the number of parameters annotated."""
    from jax.sharding import PartitionSpec

    from ..sharding_api import shard_tensor

    size = mesh.shape[mp_axis] if mp_axis in mesh.axis_names else 1
    if size <= 1:
        return 0
    count = 0
    for _, p in model.named_parameters():
        if getattr(p, "_sharding_spec", None) is not None \
                or len(p.shape) < 2:
            continue
        for dim in sorted(range(len(p.shape)), key=lambda i: -p.shape[i]):
            if p.shape[dim] % size == 0:
                spec = [None] * len(p.shape)
                spec[dim] = mp_axis
                shard_tensor(p, mesh, spec=PartitionSpec(*spec))
                count += 1
                break
    return count


class Planner:
    """Enumerate mesh factorizations, score each with the cost model,
    return them best-first (reference: ``planner_v2.py`` Planner +
    ``parallel_tuner.py`` ParallelTuner collapsed into one search over
    the GSPMD-era plan space)."""

    def __init__(self, desc: ModelDesc, cluster: Optional[Cluster] = None,
                 allow_zero: bool = True):
        self.desc = desc
        self.cluster = cluster or Cluster()
        self.comm = CommCost(self.cluster)
        self.allow_zero = allow_zero

    # -- plan space -----------------------------------------------------------
    def candidates(self, n_devices: int) -> List[ParallelPlan]:
        plans = []
        for mp in range(1, n_devices + 1):
            if n_devices % mp:
                continue
            if mp > 1 and not self.desc.mp_legal(mp):
                continue
            dp = n_devices // mp
            plans.append(ParallelPlan({"dp": dp, "mp": mp}))
            if self.allow_zero and dp > 1:
                plans.append(ParallelPlan({"dp": dp, "mp": mp},
                                          zero="p_g_os"))
        return plans

    # -- scoring --------------------------------------------------------------
    def estimate(self, plan: ParallelPlan, batch_shape) -> Dict[str, float]:
        """Predicted step time (seconds) and its terms for one global
        batch of ``batch_shape``; also fills HBM feasibility."""
        d = self.desc
        c = self.cluster
        tokens = d.tokens_of(batch_shape)
        dp, mp = plan.dp, plan.mp
        n = dp * mp

        # compute + HBM roofline: fwd + 2x bwd FLOPs; weights stream from
        # HBM ~3x per step (fwd, dgrad, wgrad)
        t_compute = 3.0 * d.flops_per_token * tokens / n / c.peak_flops
        t_hbm = 3.0 * (d.param_bytes / mp) / c.hbm_bandwidth
        # dp gradient sync: all-reduce of the local param shard's grads
        # (ZeRO: reduce-scatter + all-gather — same ring bytes — plus the
        # p_g_os parameter re-gather each step)
        grad_bytes = d.param_bytes / mp
        t_dp = self.comm.all_reduce(grad_bytes, dp)
        if plan.zero == "p_g_os":
            t_dp += self.comm.all_gather(grad_bytes, dp)
        # mp activation sync: Megatron count — 4 all-reduces per layer of
        # the per-dp-shard activation [tokens/dp, hidden]
        act_bytes = tokens / dp * d.hidden_size * d.dtype_bytes
        t_mp = 4 * d.num_layers * self.comm.all_reduce(act_bytes, mp) \
            if mp > 1 else 0.0
        seconds = max(t_compute, t_hbm) + t_dp + t_mp

        # feasibility: params + grads (model dtype) + f32 master+moments
        # (Adam-class: 3 f32 copies) + activation working set; p_g_os
        # shards ALL persistent state over dp (params re-gather per step)
        state_shards = dp if plan.zero == "p_g_os" else 1
        weight_bytes = d.param_bytes / mp / state_shards
        opt_bytes = (d.param_bytes / d.dtype_bytes) * 12 / mp / state_shards
        act_work = tokens / dp * d.hidden_size * d.dtype_bytes \
            * d.num_layers * d.act_multiplier / mp
        hbm_used = weight_bytes * 2 + opt_bytes + act_work
        cost = {
            "seconds": seconds, "compute_seconds": t_compute,
            "hbm_seconds": t_hbm, "dp_comm_seconds": t_dp,
            "mp_comm_seconds": t_mp, "tokens_per_second":
                tokens / max(seconds, 1e-12),
            "hbm_bytes_per_device": hbm_used,
        }
        plan.cost = cost
        plan.feasible = hbm_used <= c.hbm_capacity
        return cost

    def ranked(self, n_devices: int, batch_shape) -> List[ParallelPlan]:
        """All candidate plans, scored, feasible-first then fastest."""
        plans = self.candidates(n_devices)
        if not plans:
            raise ValueError(f"no legal plan for {n_devices} devices "
                             f"(max_mp={self.desc.max_mp})")
        for p in plans:
            self.estimate(p, batch_shape)
        plans.sort(key=lambda p: (not p.feasible, p.cost["seconds"]))
        return plans

    def plan(self, n_devices: int, batch_shape) -> ParallelPlan:
        """The winning plan. Raises if nothing fits in HBM — the honest
        answer is a bigger mesh, not a silently-OOM plan."""
        best = self.ranked(n_devices, batch_shape)[0]
        if not best.feasible:
            gb = best.cost["hbm_bytes_per_device"] / 1e9
            raise ValueError(
                f"no plan fits: best candidate ({best.describe()}) needs "
                f"{gb:.1f} GB/device vs {self.cluster.hbm_capacity / 1e9:.1f}"
                " GB HBM — add devices, enable recompute (lower "
                "act_multiplier), or shrink the batch")
        return best
