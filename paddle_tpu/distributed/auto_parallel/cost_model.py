"""Cost model for parallel-plan comparison (reference:
``python/paddle/distributed/auto_parallel/cost_model.py`` + ``cost/`` — the
reference replays a 2021 GPU op-benchmark JSON
(``python/paddle/cost_model/static_op_benchmark.json``) per op).

TPU-native redesign: XLA already knows the cost of a compiled program —
``jit(fn).lower(...).compile().cost_analysis()`` reports flops and bytes
accessed, so compute cost comes from the compiler instead of a stale
benchmark table. Collective cost uses the standard ring/bidirectional
ICI model (α-β: latency + size/bandwidth — the scaling-book recipe).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

__all__ = ["Cluster", "CommCost", "CostEstimator", "estimate_step_cost"]


@dataclass
class Cluster:
    """Per-chip hardware description (reference analog:
    ``auto_parallel/cluster.py``). Defaults are public TPU v5p numbers."""

    peak_flops: float = 459e12        # bf16 FLOP/s per chip
    hbm_bandwidth: float = 2765e9     # bytes/s
    hbm_capacity: float = 95e9        # bytes per chip (v5p)
    ici_bandwidth: float = 90e9       # bytes/s per link direction
    ici_latency: float = 1e-6         # seconds per hop
    dcn_bandwidth: float = 25e9       # bytes/s per host
    num_devices: int = 1


@dataclass
class CommCost:
    """α-β collective cost on a ring of ``n`` devices."""

    cluster: Cluster = field(default_factory=Cluster)

    def all_reduce(self, nbytes: float, n: int) -> float:
        if n <= 1:
            return 0.0
        c = self.cluster
        return 2 * (n - 1) / n * nbytes / c.ici_bandwidth \
            + 2 * (n - 1) * c.ici_latency

    def all_gather(self, nbytes: float, n: int) -> float:
        if n <= 1:
            return 0.0
        c = self.cluster
        return (n - 1) / n * nbytes / c.ici_bandwidth \
            + (n - 1) * c.ici_latency

    reduce_scatter = all_gather

    def all_to_all(self, nbytes: float, n: int) -> float:
        if n <= 1:
            return 0.0
        c = self.cluster
        # each device keeps 1/n locally; bisection-limited on a ring
        return (n - 1) / n * nbytes / c.ici_bandwidth / 2 \
            + (n - 1) * c.ici_latency

    def p2p(self, nbytes: float) -> float:
        c = self.cluster
        return nbytes / c.ici_bandwidth + c.ici_latency


class CostEstimator:
    """Estimate a jittable function's step cost from XLA's own analysis
    (the reference Engine consults its cost model the same way when
    choosing a plan, ``auto_parallel/engine.py`` _plan)."""

    def __init__(self, cluster: Optional[Cluster] = None):
        self.cluster = cluster or Cluster()
        self.comm = CommCost(self.cluster)

    def analyze(self, fn: Callable, *example_args) -> Dict[str, float]:
        """Compile ``fn`` and return {'flops', 'bytes_accessed',
        'compute_seconds', 'memory_seconds', 'seconds'} — seconds is the
        roofline max of the two."""
        import jax

        compiled = jax.jit(fn).lower(*example_args).compile()
        analyses = compiled.cost_analysis()
        ca = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
        flops = float(ca.get("flops", 0.0))
        nbytes = float(ca.get("bytes accessed", 0.0))
        t_compute = flops / self.cluster.peak_flops
        t_memory = nbytes / self.cluster.hbm_bandwidth
        return {
            "flops": flops,
            "bytes_accessed": nbytes,
            "compute_seconds": t_compute,
            "memory_seconds": t_memory,
            "seconds": max(t_compute, t_memory),
        }

    def compare(self, candidates: Dict[str, tuple]) -> str:
        """candidates: name -> (fn, args). Returns the cheapest name."""
        best, best_t = None, float("inf")
        for name, (fn, args) in candidates.items():
            t = self.analyze(fn, *args)["seconds"]
            if t < best_t:
                best, best_t = name, t
        return best


def estimate_step_cost(flops_per_token: float, tokens_per_step: int,
                       dp: int = 1, param_bytes: float = 0.0,
                       cluster: Optional[Cluster] = None) -> Dict[str, float]:
    """Analytic train-step estimate: 3x forward flops (fwd + 2x bwd) on the
    roofline plus a DP gradient all-reduce — the formula the bench harness
    and the planner share."""
    c = cluster or Cluster()
    comm = CommCost(c)
    t_compute = 3 * flops_per_token * tokens_per_step / c.peak_flops
    t_comm = comm.all_reduce(param_bytes, dp)
    return {"compute_seconds": t_compute, "comm_seconds": t_comm,
            "seconds": max(t_compute, t_comm),
            "tokens_per_second": tokens_per_step
            / max(t_compute, t_comm, 1e-12)}
