"""auto_parallel Strategy (reference:
``python/paddle/distributed/auto_parallel/strategy.py`` — a bag of
feature configs the planner consults: amp, recompute, sharding,
gradient_merge...)."""
from __future__ import annotations

__all__ = ["Strategy"]


class _Config:
    def __init__(self, **defaults):
        self.__dict__.update(defaults)

    def __repr__(self):
        return repr(self.__dict__)


class Strategy:
    """Feature toggles consulted by the Engine. Defaults mirror the
    reference's (everything off)."""

    def __init__(self):
        self.amp = _Config(enable=False, dtype="bfloat16", level="O1")
        self.recompute = _Config(enable=False, checkpoints=None)
        self.sharding = _Config(enable=False, stage=1, degree=1)
        self.gradient_merge = _Config(enable=False, k_steps=1, avg=True)
        self.pipeline = _Config(enable=False, schedule_mode="1F1B",
                                micro_batch_size=1)
        self.dataset = _Config(use_cache=False)

    def __repr__(self):
        return (f"Strategy(amp={self.amp}, recompute={self.recompute}, "
                f"sharding={self.sharding}, "
                f"gradient_merge={self.gradient_merge})")
