"""Semi-automatic SPMD parallelism (paddle.distributed.auto_parallel).

Reference: ``python/paddle/distributed/auto_parallel/`` — the static-graph
GSPMD-like planner: users mark tensors with ``ProcessMesh`` + shardings
(``interface.py:shard_tensor``), a ``Completer`` propagates dist attrs
through the graph (``completion.py:107``), a ``Partitioner`` splits the
program per rank (``partitioner.py:38``), a ``Resharder`` inserts comm ops
(``reshard.py:1006``), and an ``Engine`` drives fit/evaluate/predict
(``engine.py:56``).

TPU mapping (SURVEY.md §7 step 8): XLA's GSPMD pass IS the
Completer+Partitioner+Resharder — user annotations become
``NamedSharding`` constraints on a jitted program, the compiler propagates
shardings to every intermediate, partitions per device, and inserts the
collectives. What remains to build is the annotation surface (shard_tensor
/ reshard, re-exported from the dist API) and the Engine driver, which
compiles one SPMD train step over the mesh.
"""
from paddle_tpu.distributed.mesh import ProcessMesh  # noqa: F401
from paddle_tpu.distributed.sharding_api import (  # noqa: F401
    Shard, Replicate, Partial, shard_tensor, reshard,
)
from .strategy import Strategy  # noqa: F401
from .engine import Engine  # noqa: F401
from .converter import Converter  # noqa: F401
from .cost_model import Cluster, CommCost, CostEstimator  # noqa: F401
from .planner import (  # noqa: F401
    ModelDesc, ParallelPlan, Planner, auto_shard_params,
)

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "reshard", "Strategy", "Engine", "Converter", "Cluster",
           "CommCost", "CostEstimator", "ModelDesc", "ParallelPlan",
           "Planner", "auto_shard_params"]
