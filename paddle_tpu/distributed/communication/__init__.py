"""paddle.distributed.communication parity (reference:
``python/paddle/distributed/communication/`` — the sync collective API
plus ``stream/`` async variants).

The implementations live in :mod:`paddle_tpu.distributed.collective`
(GSPMD placements / shard_map collectives); this package is the
namespace the reference exposes them under, with the ``stream`` module's
task-object contract."""
from ..collective import (  # noqa: F401
    ReduceOp, all_gather, all_gather_object, all_reduce, all_to_all,
    barrier, broadcast, p2p_shift, recv, reduce, reduce_scatter, scatter,
    send,
)
from . import stream  # noqa: F401

__all__ = ["ReduceOp", "all_reduce", "all_gather", "all_gather_object",
           "all_to_all", "barrier", "broadcast", "reduce",
           "reduce_scatter", "scatter", "send", "recv", "p2p_shift",
           "stream"]
