"""paddle.distributed.communication.stream parity (reference:
``python/paddle/distributed/communication/stream/`` — collectives that
return a task with ``wait()``, optionally on the calc stream).

TPU mapping: XLA owns scheduling, so a collective issued inside a
compiled program is already asynchronous with respect to the host; the
task object exists for API parity and ``wait()`` blocks on the result
buffer (``use_calc_stream=True`` waits immediately, matching the
reference's synchronous-on-calc-stream semantics)."""
from __future__ import annotations

from .. import collective as C

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "broadcast",
           "all_to_all", "reduce", "scatter", "send", "recv"]


class _Task:
    def __init__(self, result):
        self._result = result

    def wait(self):
        import jax
        r = self._result
        if r is not None and hasattr(r, "data"):
            jax.block_until_ready(r.data)
        return self._result

    def is_completed(self) -> bool:
        return True


def _wrap(fn):
    def stream_variant(*args, sync_op=True, use_calc_stream=False,
                       **kwargs):
        out = fn(*args, **kwargs)
        task = _Task(out)
        if use_calc_stream:
            task.wait()
        return task
    stream_variant.__name__ = fn.__name__
    stream_variant.__doc__ = (f"stream.{fn.__name__}: returns a task with "
                              "wait() (reference stream API)")
    return stream_variant


all_reduce = _wrap(C.all_reduce)
all_gather = _wrap(C.all_gather)
reduce_scatter = _wrap(C.reduce_scatter)
broadcast = _wrap(C.broadcast)
all_to_all = _wrap(C.all_to_all)
reduce = _wrap(C.reduce)
scatter = _wrap(C.scatter)
send = _wrap(C.send)
recv = _wrap(C.recv)
