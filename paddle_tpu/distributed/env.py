"""Process/environment bootstrap.

Parity with the reference's ``init_parallel_env``
(``python/paddle/distributed/parallel.py:919``: read PADDLE_TRAINER_* env,
TCPStore rendezvous, default process group, barrier). On TPU the runtime
(jax.distributed / PJRT) owns rendezvous: multi-host jobs call
``jax.distributed.initialize`` with a coordinator address — the TCPStore
analog — after which every host sees the global device set and SPMD programs
span the slice. Single-process (incl. the 8-device CPU test mesh) needs no
rendezvous at all.
"""
from __future__ import annotations

import os
from typing import Optional

from .mesh import get_mesh, init_mesh

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv"]

_initialized = {"done": False}


def init_parallel_env(mesh_shape: Optional[dict] = None):
    """Bootstrap distributed state and the default mesh.

    Honors the reference's env-variable protocol where present
    (PADDLE_TRAINER_ID → process index, PADDLE_MASTER/MASTER_ADDR →
    coordinator) and maps it onto jax.distributed for multi-host TPU.
    """
    import jax

    if _initialized["done"]:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    n_proc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    proc_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and n_proc > 1 and jax.process_count() == 1:
        port = os.environ.get("MASTER_PORT", "8476")
        jax.distributed.initialize(
            coordinator_address=f"{coord}:{port}" if ":" not in coord
            else coord,
            num_processes=n_proc, process_id=proc_id)
    if get_mesh() is None:
        init_mesh(mesh_shape)
    _initialized["done"] = True
    return ParallelEnv()


def get_rank(group=None) -> int:
    """Host process rank (reference: paddle.distributed.get_rank).

    The launcher/spawn env contract wins when present (PADDLE_TRAINER_ID,
    exactly like the reference reads it); otherwise the PJRT process
    index. Under SPMD one process drives many devices; device-level rank
    only exists inside shard_map, via lax.axis_index.
    """
    import os
    env = os.environ.get("PADDLE_TRAINER_ID")
    if env is not None:
        return int(env)
    import jax
    return jax.process_index()


def get_world_size(group=None) -> int:
    """Total worker count: the launcher env contract (PADDLE_TRAINERS_NUM)
    when present, else the device count (paddle world-size semantics map
    to chips on TPU — each chip was a paddle "rank")."""
    if group is not None and hasattr(group, "nranks"):
        return group.nranks
    import os
    env = os.environ.get("PADDLE_TRAINERS_NUM")
    if env is not None:
        return int(env)
    import jax
    return jax.device_count()


class ParallelEnv:
    """Reference: ``python/paddle/fluid/dygraph/parallel.py`` ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()
