"""Process/environment bootstrap.

Parity with the reference's ``init_parallel_env``
(``python/paddle/distributed/parallel.py:919``: read PADDLE_TRAINER_* env,
TCPStore rendezvous, default process group, barrier). On TPU the runtime
(jax.distributed / PJRT) owns rendezvous: multi-host jobs call
``jax.distributed.initialize`` with a coordinator address — the TCPStore
analog — after which every host sees the global device set and SPMD programs
span the slice. Single-process (incl. the 8-device CPU test mesh) needs no
rendezvous at all.
"""
from __future__ import annotations

import os
from typing import Optional

from .mesh import get_mesh, init_mesh

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv"]

_initialized = {"done": False}


def _jax_distributed_active() -> bool:
    """Whether jax.distributed.initialize already ran. NOTE: probing via
    jax.process_count() would INITIALIZE the backend — exactly what must
    not happen before initialize — so peek at the (private) client state
    and fail open if jax reorganizes it."""
    try:
        from jax._src import distributed as _jd
        return getattr(_jd.global_state, "client", None) is not None
    except Exception:
        return False


def init_parallel_env(mesh_shape: Optional[dict] = None):
    """Bootstrap distributed state and the default mesh.

    Honors the reference's env-variable protocol where present
    (PADDLE_TRAINER_ID → process index, PADDLE_MASTER/MASTER_ADDR →
    coordinator) and maps it onto jax.distributed for multi-host TPU.
    """
    import jax

    if _initialized["done"]:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    n_proc = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    proc_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and n_proc > 1 and not _jax_distributed_active():
        explicit = os.environ.get("PADDLE_JAX_COORDINATOR")
        if explicit:
            addr = explicit
        elif os.environ.get("PADDLE_STORE_PORT"):
            # under the launcher PADDLE_MASTER is the TCPStore endpoint —
            # a DIFFERENT protocol than jax's gRPC coordinator. Negotiate
            # a separate coordinator port through the store, namespaced by
            # the elastic restart epoch (a relaunched attempt must never
            # read a dead coordinator's address).
            from .tcp_store import free_port, job_store
            store = job_store()
            host = coord.split(":")[0]
            epoch = os.environ.get("PADDLE_RESTART_EPOCH", "0")
            key = f"__jax_coordinator/{epoch}"
            if proc_id == 0:
                # the coordinator service runs INSIDE proc 0, so the
                # advertised host must be proc 0's reachable address. When
                # proc 0 owns the PADDLE_MASTER address (the common
                # single-node / master-on-rank-0 layout) advertise that;
                # otherwise (explicit --master on another node) advertise
                # this machine's hostname instead of crashing on the bind.
                try:
                    port = free_port(host)
                    adv = host
                except OSError:
                    # rank 0 doesn't own the master address: advertise the
                    # IP of the interface that reaches it (UDP connect
                    # sends nothing, just resolves routing) — a bare
                    # gethostname() is often unresolvable cluster-wide
                    import socket as _socket
                    s = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
                    try:
                        s.connect((host, 1))
                        adv = s.getsockname()[0]
                    except OSError:
                        adv = _socket.gethostname()
                    finally:
                        s.close()
                    port = free_port("")
                store.set(key, f"{adv}:{port}".encode())
            addr = store.wait(key).decode()
        else:
            port = os.environ.get("MASTER_PORT", "8476")
            addr = coord if ":" in coord else f"{coord}:{port}"
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=n_proc,
                                   process_id=proc_id)
    if get_mesh() is None:
        init_mesh(mesh_shape)
    _initialized["done"] = True
    return ParallelEnv()


def get_rank(group=None) -> int:
    """Host process rank (reference: paddle.distributed.get_rank).

    The launcher/spawn env contract wins when present (PADDLE_TRAINER_ID,
    exactly like the reference reads it); otherwise the PJRT process
    index. Under SPMD one process drives many devices; device-level rank
    only exists inside shard_map, via lax.axis_index.
    """
    import os
    env = os.environ.get("PADDLE_TRAINER_ID")
    if env is not None:
        return int(env)
    import jax
    return jax.process_index()


def get_world_size(group=None) -> int:
    """Total worker count: the launcher env contract (PADDLE_TRAINERS_NUM)
    when present, else the device count (paddle world-size semantics map
    to chips on TPU — each chip was a paddle "rank")."""
    if group is not None and hasattr(group, "nranks"):
        return group.nranks
    import os
    env = os.environ.get("PADDLE_TRAINERS_NUM")
    if env is not None:
        return int(env)
    import jax
    return jax.device_count()


class ParallelEnv:
    """Reference: ``python/paddle/fluid/dygraph/parallel.py`` ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()
