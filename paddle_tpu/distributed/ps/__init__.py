"""Parameter server (reference: ``paddle/fluid/distributed/ps/`` ~32K LoC
brpc client/server + table stack; Python driver
``python/paddle/distributed/ps/the_one_ps.py:1031``).

## Design doc — the TPU mapping (SURVEY.md §7 "what we do not rebuild")

The reference PS exists to train CTR models whose embedding tables exceed
single-host memory: dense compute runs on workers while sparse embedding
rows live in a brpc KV service with optimizers executed *inside* the table
(accessors), SSD spill, and GeoSGD async modes. On a TPU pod the dense
path is SPMD over the mesh, and the large-embedding problem is served
first by sharding the table across HBM (``VocabParallelEmbedding`` — ICI
lookup beats host RPC by orders of magnitude). The PS shape is still part
of the capability surface for beyond-HBM tables, so this module keeps the
reference's architecture at host level:

  * ``SparseTable`` / ``DenseTable`` — in-memory KV tables with
    in-table optimizers (SGD/Adagrad accessor analog,
    ref ``table/memory_sparse_table.cc``); lazy row init.
  * ``PSServer`` — hosts tables, serves pull/push via
    ``paddle_tpu.distributed.rpc`` (the brpc replacement).
  * ``PSClient`` — worker-side pull_sparse/push_sparse_grad/
    pull_dense/push_dense_grad.
  * ``fleet``-style lifecycle: ``init_server/run_server/init_worker/
    stop_worker`` free functions.

Not rebuilt (out of TPU scope, revisit on demand): SSD/rocksdb spill,
GeoSGD async replication, HeterPS GPU hash tables, FL coordinator.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

__all__ = ["SparseTable", "DenseTable", "PSServer", "PSClient",
           "init_server", "run_server", "init_worker", "stop_worker"]


class SparseTable:
    """id -> embedding row, rows created on first touch (reference:
    memory_sparse_table.cc); optimizer runs in-table on push (accessor
    analog)."""

    def __init__(self, dim: int, initializer: str = "uniform",
                 init_scale: float = 0.01, optimizer: str = "sgd",
                 lr: float = 0.01, seed: int = 0):
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        self._rows: Dict[int, np.ndarray] = {}
        self._accum: Dict[int, np.ndarray] = {}  # adagrad state
        self._rng = np.random.RandomState(seed)
        self._init_scale = init_scale
        self._initializer = initializer
        self._lock = threading.Lock()

    def _row(self, key: int) -> np.ndarray:
        r = self._rows.get(key)
        if r is None:
            if self._initializer == "zeros":
                r = np.zeros(self.dim, np.float32)
            else:
                r = self._rng.uniform(-self._init_scale, self._init_scale,
                                      self.dim).astype(np.float32)
            self._rows[key] = r
        return r

    def pull(self, keys) -> np.ndarray:
        with self._lock:
            return np.stack([self._row(int(k)) for k in np.asarray(keys)])

    def push(self, keys, grads) -> None:
        grads = np.asarray(grads, np.float32)
        with self._lock:
            for k, g in zip(np.asarray(keys), grads):
                k = int(k)
                row = self._row(k)
                if self.optimizer == "adagrad":
                    acc = self._accum.setdefault(
                        k, np.zeros(self.dim, np.float32))
                    acc += g * g
                    row -= self.lr * g / (np.sqrt(acc) + 1e-10)
                else:  # sgd
                    row -= self.lr * g

    def size(self) -> int:
        return len(self._rows)


class DenseTable:
    """Flat dense parameter block (reference: common dense table)."""

    def __init__(self, shape, lr: float = 0.01):
        self.param = np.zeros(shape, np.float32)
        self.lr = lr
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.param.copy()

    def push(self, grad) -> None:
        with self._lock:
            self.param -= self.lr * np.asarray(grad, np.float32)


class PSServer:
    """Hosts tables; request handlers are invoked via distributed.rpc."""

    _instance: Optional["PSServer"] = None

    def __init__(self):
        self.sparse: Dict[str, SparseTable] = {}
        self.dense: Dict[str, DenseTable] = {}
        PSServer._instance = self

    def add_sparse_table(self, name: str, dim: int, **kw):
        self.sparse[name] = SparseTable(dim, **kw)

    def add_dense_table(self, name: str, shape, **kw):
        self.dense[name] = DenseTable(shape, **kw)

    # rpc entry points (module-level fns resolve the singleton so they
    # pickle by reference)


def _srv() -> PSServer:
    if PSServer._instance is None:
        raise RuntimeError("PSServer not initialized on this rank")
    return PSServer._instance


def _pull_sparse(table: str, keys):
    return _srv().sparse[table].pull(keys)


def _push_sparse(table: str, keys, grads):
    _srv().sparse[table].push(keys, grads)
    return True


def _pull_dense(table: str):
    return _srv().dense[table].pull()


def _push_dense(table: str, grad):
    _srv().dense[table].push(grad)
    return True


class PSClient:
    """Worker-side API (reference: brpc_ps_client.cc surface)."""

    def __init__(self, server_name: str):
        self.server = server_name

    def pull_sparse(self, table: str, keys) -> np.ndarray:
        from .. import rpc
        return rpc.rpc_sync(self.server, _pull_sparse,
                            args=(table, np.asarray(keys)))

    def push_sparse_grad(self, table: str, keys, grads) -> None:
        from .. import rpc
        rpc.rpc_sync(self.server, _push_sparse,
                     args=(table, np.asarray(keys), np.asarray(grads)))

    def pull_dense(self, table: str) -> np.ndarray:
        from .. import rpc
        return rpc.rpc_sync(self.server, _pull_dense, args=(table,))

    def push_dense_grad(self, table: str, grad) -> None:
        from .. import rpc
        rpc.rpc_sync(self.server, _push_dense, args=(table, grad))


# -- fleet-style lifecycle (the_one_ps.py surface) ---------------------------
_runtime = {"server": None}


def init_server(**_kw) -> PSServer:
    _runtime["server"] = PSServer()
    return _runtime["server"]


def run_server():
    """The rpc service thread already serves requests; kept for surface
    parity with fleet.run_server()."""
    if _runtime["server"] is None:
        raise RuntimeError("call init_server() first")


def init_worker(server_name: str = "ps0") -> PSClient:
    return PSClient(server_name)


def stop_worker():
    from .. import rpc
    rpc.shutdown()
