"""paddle.distributed.rpc parity (reference:
``python/paddle/distributed/rpc/rpc.py`` — brpc-backed init_rpc/rpc_sync/
rpc_async/shutdown with a master-coordinated service-info exchange).

TPU-native redesign: the wire is a plain length-prefixed-pickle TCP
protocol (the brpc dependency buys nothing on a TPU pod's host network),
rendezvous reuses the framework's own TCPStore, and ``rpc_async`` returns a
``concurrent.futures.Future``. Worker identity model (name → WorkerInfo)
matches the reference surface.
"""
from __future__ import annotations

import pickle
import socket
import struct
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ..tcp_store import TCPStore

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]

_DEFAULT_RPC_TIMEOUT = 30.0


@dataclass
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


class _State:
    def __init__(self):
        self.store: Optional[TCPStore] = None
        self.server: Optional[socket.socket] = None
        self.server_thread: Optional[threading.Thread] = None
        self.pool: Optional[ThreadPoolExecutor] = None
        self.infos: Dict[str, WorkerInfo] = {}
        self.self_name: Optional[str] = None
        self.running = False
        self.token: bytes = b""


_state = _State()


def _recv_exact(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


def _send_msg(conn, obj):
    payload = pickle.dumps(obj)
    conn.sendall(struct.pack("!Q", len(payload)) + payload)


def _recv_msg(conn):
    (n,) = struct.unpack("!Q", _recv_exact(conn, 8))
    return pickle.loads(_recv_exact(conn, n))


def _serve(srv):
    while _state.running:
        try:
            conn, _ = srv.accept()
        except OSError:
            return
        threading.Thread(target=_handle, args=(conn,), daemon=True).start()


def _handle(conn):
    try:
        tok = _recv_exact(conn, 16)
        if tok != _state.token:  # reject before any pickle.loads
            conn.close()
            return
        fn, args, kwargs = _recv_msg(conn)
        try:
            result = ("ok", fn(*args, **kwargs))
        except Exception as e:  # ship the exception back, reference parity
            result = ("err", e)
        _send_msg(conn, result)
    except ConnectionError:
        pass
    finally:
        conn.close()


def _advertised_ip(master_host: str) -> str:
    """The IP other hosts should dial: PADDLE_LOCAL_IP override, else the
    interface that routes toward the master (UDP connect trick — no
    packets are sent), else loopback for single-host runs."""
    import os
    ip = os.environ.get("PADDLE_LOCAL_IP")
    if ip:
        return ip
    if master_host in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        probe.connect((master_host, 1))
        ip = probe.getsockname()[0]
        probe.close()
        return ip
    except OSError:
        return socket.gethostbyname(socket.gethostname())


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """Start this worker's RPC service and exchange worker infos
    (reference: rpc.py:73)."""
    import os
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    if master_endpoint is None:
        master_endpoint = os.environ.get("PADDLE_MASTER",
                                         "127.0.0.1:29531")
    host, port = master_endpoint.rsplit(":", 1)

    my_ip = _advertised_ip(host)
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    # bind the advertised interface only (not 0.0.0.0): the wire protocol
    # is pickle, so exposure is limited to the training network, and every
    # request must present the job token (below) before deserialization
    srv.bind((my_ip, 0))
    srv.listen(128)
    my_port = srv.getsockname()[1]

    _state.store = TCPStore(host=host, port=int(port), is_master=(rank == 0),
                            world_size=world_size)
    # per-job shared secret: rank 0 mints it, everyone reads it from the
    # store; requests without it are dropped before unpickling
    if rank == 0:
        import os as _os
        _state.store.set("rpc/token", _os.urandom(16))
    _state.token = _state.store.wait("rpc/token",
                                     timeout=_DEFAULT_RPC_TIMEOUT * 10)
    _state.server = srv
    _state.running = True
    _state.pool = ThreadPoolExecutor(max_workers=8)
    _state.self_name = name
    _state.server_thread = threading.Thread(target=_serve, args=(srv,),
                                            daemon=True)
    _state.server_thread.start()

    info = WorkerInfo(name, rank, my_ip, my_port)
    _state.store.set(f"rpc/worker/{rank}",
                     pickle.dumps((name, rank, info.ip, my_port)))
    for r in range(world_size):
        raw = _state.store.wait(f"rpc/worker/{r}",
                                timeout=_DEFAULT_RPC_TIMEOUT * 10)
        n, rk, ip, p = pickle.loads(raw)
        _state.infos[n] = WorkerInfo(n, rk, ip, p)


def get_worker_info(name: str) -> WorkerInfo:
    return _state.infos[name]


def get_all_worker_infos():
    return list(_state.infos.values())


def _invoke(to: str, fn, args, kwargs, timeout):
    info = _state.infos[to]
    with socket.create_connection((info.ip, info.port),
                                  timeout=timeout) as conn:
        conn.sendall(_state.token)
        _send_msg(conn, (fn, args or (), kwargs or {}))
        conn.settimeout(timeout)
        status, value = _recv_msg(conn)
    if status == "err":
        raise value
    return value


def rpc_sync(to: str, fn, args=None, kwargs=None,
             timeout=_DEFAULT_RPC_TIMEOUT):
    """Blocking remote call (reference: rpc.py:141)."""
    return _invoke(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout=_DEFAULT_RPC_TIMEOUT) -> Future:
    """Non-blocking remote call returning a Future with ``.wait()``
    (reference: rpc.py:179 returns a FutureWrapper)."""
    fut = _state.pool.submit(_invoke, to, fn, args, kwargs, timeout)
    if not hasattr(fut, "wait"):
        fut.wait = fut.result  # reference surface: fut.wait()
    return fut


def shutdown():
    """Barrier, then stop the local service (reference: rpc.py graceful
    shutdown)."""
    if not _state.running:
        return
    if _state.store is not None:
        from ..tcp_store import barrier_via_store
        try:
            barrier_via_store(_state.store, "rpc_shutdown",
                              len(_state.infos))
        except Exception:
            pass
    _state.running = False
    try:
        _state.server.close()
    except Exception:
        pass
    if _state.pool is not None:
        _state.pool.shutdown(wait=False)
    _state.infos.clear()
    _state.store = None
