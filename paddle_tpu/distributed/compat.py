"""Remaining paddle.distributed surface (reference:
``python/paddle/distributed/__init__.py`` exports) — process-group
queries, async p2p wrappers, object collectives, spawn.

Single-controller SPMD notes: under jax one host process drives every
local device, so single-process object collectives are identities. Raw
p2p (isend/irecv) keeps dist.send's honest contract — it has no XLA
analog outside an spmd region and raises, pointing at ``p2p_shift``.
"""
from __future__ import annotations

import pickle
from dataclasses import dataclass
from typing import Callable, List, Optional

from . import collective as C
from .env import get_rank, get_world_size

__all__ = ["is_initialized", "destroy_process_group", "get_backend",
           "wait", "gather", "isend", "irecv", "P2POp",
           "batch_isend_irecv", "broadcast_object_list",
           "scatter_object_list", "split", "spawn"]

def is_initialized() -> bool:
    """Reference: parallel.py is_initialized — True once
    init_parallel_env (or fleet.init) built the mesh."""
    from . import env
    from .mesh import get_mesh
    return env._initialized["done"] or get_mesh() is not None


def destroy_process_group(group=None):
    """Reference: parallel.py destroy_process_group — tears down the mesh
    AND resets init_parallel_env's guard so a later init rebuilds it."""
    from . import env
    from .mesh import set_mesh
    if group is not None:
        raise NotImplementedError(
            "per-group destruction is not supported; groups are mesh-axis "
            "views — destroy the whole process group (group=None)")
    set_mesh(None)
    env._initialized["done"] = False


def get_backend(group=None) -> str:
    """The communication backend name — XLA collectives over ICI/DCN
    (the NCCL/GLOO analog)."""
    return "XCCL"


def wait(tensor, group=None, use_calc_stream: bool = True):
    """Reference: communication/wait.py — block until ``tensor`` is
    materialized."""
    import jax
    if tensor is not None and hasattr(tensor, "data"):
        jax.block_until_ready(tensor.data)
    return tensor


def gather(tensor, gather_list: Optional[list] = None, dst: int = 0,
           group=None, sync_op: bool = True):
    """Reference: communication/gather.py — collect shards to ``dst``.
    Under SPMD every rank computes the gathered value (an all-gather);
    the reference contract of dst-only results is relaxed to
    everyone-gets-it, which is strictly more available."""
    parts: list = []
    C.all_gather(parts, tensor, group=group)  # list form: per-rank shards
    if gather_list is not None:
        gather_list.clear()
        gather_list.extend(parts)
    return parts


def isend(tensor, dst: int = 0, group=None):
    """Reference: communication/send.py isend. Raw p2p has no XLA analog
    outside an spmd region (same contract as dist.send): use
    ``dist.p2p_shift`` (collective_permute) — the PP engine does."""
    return C.send(tensor, dst=dst, group=group)


def irecv(tensor, src: int = 0, group=None):
    """Reference: communication/recv.py irecv (see :func:`isend`)."""
    return C.recv(tensor, src=src, group=group)


@dataclass
class P2POp:
    """Reference: communication/batch_isend_irecv.py P2POp."""
    op: Callable
    tensor: object
    peer: int
    group: object = None


def batch_isend_irecv(p2p_op_list: List[P2POp]) -> list:
    """Reference: batch_isend_irecv — issue a batch of p2p ops; XLA
    schedules them together inside the compiled program."""
    tasks = []
    for p in p2p_op_list:
        if p.op in (isend, C.send):
            tasks.append(isend(p.tensor, dst=p.peer, group=p.group))
        elif p.op in (irecv, C.recv):
            tasks.append(irecv(p.tensor, src=p.peer, group=p.group))
        else:
            raise ValueError(f"P2POp.op must be isend/irecv, got {p.op}")
    return tasks


def _single_process() -> bool:
    from .collective import _multi_host_world
    return _multi_host_world()[1] <= 1


def broadcast_object_list(object_list: list, src: int = 0, group=None):
    """Reference: communication/broadcast.py broadcast_object_list.
    Single process: the src host's objects already are everyone's objects.
    Multi-process (DCN): src publishes the pickled list to the job's
    TCPStore, everyone else replaces their list contents in place.

    Non-member contract: ranks OUTSIDE ``group`` return with
    ``object_list`` untouched (a no-op, matching the reference) — don't
    read the list on a non-member rank expecting broadcast contents."""
    if _single_process():
        return None
    import pickle
    from .collective import _group_members, _obj_key, _reaped_barrier
    from .tcp_store import job_store
    members, rank, tag = _group_members(group, "broadcast_object_list")
    if src not in members:
        raise ValueError(
            f"broadcast_object_list src {src} not in group {members}")
    if rank not in members or len(members) <= 1:
        return None
    store = job_store()
    key = _obj_key("bc", tag)
    if rank == src:
        store.set(key, pickle.dumps(list(object_list)))
    object_list[:] = pickle.loads(store.wait(key))
    _reaped_barrier(store, key + "/done", len(members))
    if rank == src:
        store.delete_key(key)
    return None


def scatter_object_list(out_object_list: list, in_object_list=None,
                        src: int = 0, group=None):
    """Reference: communication/scatter.py scatter_object_list. Src
    publishes one store entry per destination rank; each rank reads only
    its own."""
    if _single_process():
        rank = get_rank(group)
        out_object_list.clear()
        if in_object_list:
            out_object_list.append(in_object_list[rank
                                                  % len(in_object_list)])
        return None
    import pickle
    from .collective import _group_members, _obj_key, _reaped_barrier
    from .tcp_store import job_store
    members, rank, tag = _group_members(group, "scatter_object_list")
    if src not in members:
        raise ValueError(
            f"scatter_object_list src {src} not in group {members}")
    if rank not in members:
        return None
    store = job_store()
    key = _obj_key("sc", tag)
    if rank == src:
        if not in_object_list or len(in_object_list) != len(members):
            raise ValueError(
                f"scatter_object_list needs one object per group rank "
                f"({len(members)}), got "
                f"{0 if not in_object_list else len(in_object_list)}")
        for gi, r in enumerate(members):
            store.set(f"{key}/{r}", pickle.dumps(in_object_list[gi]))
    out_object_list.clear()
    out_object_list.append(pickle.loads(store.wait(f"{key}/{rank}")))
    _reaped_barrier(store, key + "/done", len(members))
    store.delete_key(f"{key}/{rank}")
    return None


def split(x, size, operation: str = "linear", axis: int = 0, num_partitions=1,
          gather_out: bool = True, weight_attr=None, bias_attr=None,
          name=None):
    """Reference: fleet/layers/mpu/mp_ops.py:653 paddle.distributed.split
    — build a row/column-parallel linear or vocab-parallel embedding from
    a plain op call. Delegates to the mpu layers (the dygraph analog).

    NOTE: like the reference's static-mode split, each call CREATES the
    parallel layer (fresh parameters). Call it once at model-build time
    and keep ``out._split_layer`` (register it on your Layer) so the
    parameters reach the optimizer; calling split per step would
    re-initialize weights every step."""
    from .fleet import (ColumnParallelLinear, RowParallelLinear,
                        VocabParallelEmbedding)
    if operation == "linear":
        in_f, out_f = size
        if axis == 0:
            layer = RowParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                      has_bias=bias_attr is not False)
        else:
            layer = ColumnParallelLinear(in_f, out_f,
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        out = layer(x)
        out._split_layer = layer  # keep params alive with the output
        return out
    if operation == "embedding":
        vocab, hidden = size
        layer = VocabParallelEmbedding(vocab, hidden,
                                       weight_attr=weight_attr)
        out = layer(x)
        out._split_layer = layer
        return out
    raise ValueError(f"unknown operation '{operation}'")


def _spawn_entry(func, rank, nprocs, args):
    import os
    # the reference launcher's env contract: workers discover their rank
    # through PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM (env.get_rank reads
    # these), then call func(*args) — paddle's spawn signature
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    func(*args)


def spawn(func: Callable, args=(), nprocs: int = -1, join: bool = True,
          **options):
    """Reference: spawn.py paddle.distributed.spawn — start ``nprocs``
    worker processes running ``func(*args)`` with per-worker
    PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM set (rank comes from
    ``dist.get_rank()``, matching the reference contract)."""
    import multiprocessing as mp
    if nprocs <= 0:
        import jax
        nprocs = jax.device_count()
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_spawn_entry,
                        args=(func, rank, nprocs, tuple(args)))
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode != 0]
        if bad:
            raise RuntimeError(f"spawned workers failed: exit {bad}")
    return procs
