"""Process launcher + elastic membership.

Parity with ``python -m paddle.distributed.launch`` (reference:
``python/paddle/distributed/launch/``: controllers build a node/pod model,
inject PADDLE_TRAINER_* env, watch logs; elastic in
``fleet/elastic/manager.py`` heartbeats etcd). TPU shape: one process per
HOST (each host drives its local chips; jax.distributed handles the device
mesh), rendezvous through the native TCPStore instead of etcd/HTTP, and a
heartbeat-based ElasticManager that detects dead trainers and triggers
relaunch.

CLI::

    python -m paddle_tpu.distributed.launch --nproc_per_node 2 train.py
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time
from typing import List, Optional

from .tcp_store import TCPStore

__all__ = ["launch", "ElasticManager", "main"]

#: Trainers exiting with this code were PREEMPTED and committed a final
#: checkpoint (resilience.preemption contract): the launcher relaunches
#: them — they resume from ``CheckpointManager.latest_step`` — without
#: consuming the ``max_restarts`` crash budget.
from paddle_tpu.resilience.preemption import (  # noqa: E402
    RESUMABLE_EXIT_CODE, preempt_stop_key)
#: Trainers exiting with this code left at a consensus RESIZE boundary
#: (resilience.elastic): the surviving ranks carry the full state in
#: memory and keep training — a membership change, never a crash.
from paddle_tpu.resilience.elastic import (  # noqa: E402
    RESIZE_EXIT_CODE, elastic_prefix)

_RESUME_GRACE = 60.0   # wait this long for peers' coordinated final saves
_RESIZE_GRACE = 5.0    # window to tell an in-place resize (survivors keep
                       # running) from a coordinated resize-relaunch (all
                       # ranks exit 83 together)


def _max_resumes(value: Optional[int]) -> int:
    if value is not None:
        return int(value)
    return int(os.environ.get("PADDLE_TPU_MAX_RESUMES", "8"))


def _max_resizes() -> int:
    return int(os.environ.get("PADDLE_TPU_MAX_RESIZES", "8"))


def _resize_target_world(store, epoch) -> Optional[int]:
    """The consensus resize verdict's agreed world size, if one was
    published for this restart epoch (``__elastic/{epoch}/g{gen}/stop``
    holds ``stop_at:new_world:reason``; survivors bump ``gen`` after an
    in-place resize, so check the current and previous generation)."""
    try:
        raw = store.get(f"__elastic/{epoch}/gen")
        gen = int(raw) if raw else 0
        for g in (gen, gen - 1):
            if g < 0:
                continue
            v = store.get(f"{elastic_prefix(g, str(epoch))}/stop")
            if v:
                return int(v.decode(errors="replace").split(":")[1])
    except Exception:
        pass
    return None


class ElasticManager:
    """Store-backed membership (reference: elastic/manager.py:126 —
    register with TTL lease + heartbeat thread; watch for dead peers)."""

    def __init__(self, store: TCPStore, rank: int, world_size: int,
                 heartbeat_interval: float = 1.0,
                 heartbeat_timeout: float = 5.0):
        self._store = store
        self.rank = rank
        self.world_size = world_size
        self._interval = heartbeat_interval
        self._timeout = heartbeat_timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._beat()

        def loop():
            while not self._stop.wait(self._interval):
                self._beat()
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def _beat(self):
        self._store.set(f"__hb/{self.rank}", str(time.time()))

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    def dead_ranks(self) -> List[int]:
        now = time.time()
        dead = []
        for r in range(self.world_size):
            v = self._store.get(f"__hb/{r}")
            if v is None or now - float(v) > self._timeout:
                dead.append(r)
        return dead

    def all_alive(self) -> bool:
        return not self.dead_ranks()


def parse_np(np_arg: Optional[str]):
    """``--np`` elastic bounds: "N" (fixed) or "min:max" (reference:
    fleet/elastic/manager.py — np range enables scale-in/out)."""
    if np_arg is None:
        return None
    if ":" in np_arg:
        lo, hi = np_arg.split(":", 1)
        lo, hi = int(lo), int(hi)
    else:
        lo = hi = int(np_arg)
    if not (1 <= lo <= hi):
        raise ValueError(f"--np must satisfy 1 <= min <= max, got {np_arg}")
    return lo, hi


def launch(script: str, script_args: Optional[List[str]] = None,
           nproc_per_node: int = 1, master: Optional[str] = None,
           max_restarts: int = 0, log_dir: Optional[str] = None,
           node_rank: int = 0, nnodes: int = 1,
           np_range: Optional[tuple] = None,
           max_resumes: Optional[int] = None) -> int:
    """Spawn ``nproc_per_node`` trainer processes with reference-compatible
    env (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER) and
    restart-on-failure up to ``max_restarts`` (elastic relaunch).

    Single-node (``master=None``): this launcher hosts the TCPStore.
    Multi-node: ``master`` is ``host:port``; the ``node_rank == 0`` launcher
    binds the store at that port, every other node connects to it as a
    client, so all trainers rendezvous against ONE store. Trainer ranks are
    GLOBAL: ``node_rank * nproc_per_node + local`` out of
    ``nnodes * nproc_per_node``.

    Elastic restarts are coordinated cluster-wide through a shared
    ``__restart_epoch`` counter: any launcher whose local trainers fail
    bumps it; every launcher polls it and restarts its trainers when it
    moves. Rendezvous keys (store barriers) are namespaced by the epoch
    (PADDLE_RESTART_EPOCH), so an attempt can never consume a previous
    attempt's stale keys — no cross-node key deletion is needed.

    ``np_range = (min, max)`` turns on SCALE-IN/OUT (reference:
    fleet/elastic/manager.py np-range decision logic, single-node scope
    here): a dead trainer no longer costs a same-size full restart — the
    launcher recomputes the world as the surviving count (>= min) and
    pushes it to the trainers through rewritten env (PADDLE_TRAINER_ID /
    PADDLE_TRAINERS_NUM / PADDLE_RESTART_EPOCH), relaunching at the
    smaller size without failing the job. When capacity
    returns, bumping the ``__scale_out`` store counter (a replacement
    worker announcing itself — or an operator) triggers one more
    membership change back up to max. Below min the job fails. Scale
    events do not consume the ``max_restarts`` crash budget.

    PREEMPTION (docs/RESILIENCE.md): trainers exiting with
    ``RESUMABLE_EXIT_CODE`` committed a final checkpoint first — the
    launcher waits (bounded) for the coordinated exit of all ranks, then
    relaunches WITHOUT consuming ``max_restarts``; the relaunched
    trainers resume from ``latest_step``. ``max_resumes`` (default
    ``$PADDLE_TPU_MAX_RESUMES`` or 8) bounds the loop — past it the
    launcher itself exits with the resumable code, surfacing "this job
    keeps getting preempted" to the operator.
    """
    script_args = script_args or []
    np_min, np_max = np_range if np_range else (None, None)
    if np_range and np_min == np_max:
        # fixed --np N: plain process count, works everywhere
        if nproc_per_node not in (1, np_max):
            raise ValueError(
                f"--np {np_max} conflicts with --nproc_per_node "
                f"{nproc_per_node}")
        nproc_per_node = np_max
        np_range = None
    elif np_range is not None:
        if nproc_per_node != 1:
            raise ValueError(
                "--np min:max and --nproc_per_node are mutually "
                "exclusive: the elastic range sets the process count")
        if nnodes == 1:
            nproc_per_node = np_max
        elif np_max != nnodes:
            raise ValueError(
                f"multi-node elastic: --np max ({np_max}) must equal "
                f"--nnodes ({nnodes}) — one trainer per host (the TPU "
                "process shape); min bounds the surviving node count")
    world_size = nnodes * nproc_per_node
    if master is None:
        store = TCPStore(is_master=True, world_size=world_size)
        master_addr = f"127.0.0.1:{store.port}"
    else:
        master_addr = master
        mhost, mport = master.rsplit(":", 1)
        store = TCPStore(host=mhost, port=int(mport),
                         is_master=(node_rank == 0),
                         world_size=world_size)
    def _exit(code: int) -> int:
        # the store-hosting launcher must be last out: peers may be mid-
        # poll against it. Everyone acks exit; the host waits (bounded)
        # for all acks before returning, since returning drops the store
        # and stops the server.
        try:
            store.add("__exit_ack", 1)
            if store._server:
                deadline = time.monotonic() + 15
                while int(store.add("__exit_ack", 0)) < nnodes and \
                        time.monotonic() < deadline:
                    time.sleep(0.1)
        except Exception:
            pass
        return code

    if np_range is not None and nnodes > 1:
        return _elastic_multinode(script, script_args, master_addr, store,
                                  nnodes, node_rank, np_min, np_max,
                                  max_restarts, log_dir,
                                  _max_resumes(max_resumes))

    epoch = int(store.add("__restart_epoch", 0))
    attempts = 0  # local relaunch budget (epoch can over-bump on races)
    resumes = 0   # preemption relaunch budget (separate from crashes)
    resume_budget = _max_resumes(max_resumes)
    resizes = 0   # consensus resize count (separate from both budgets)
    resize_budget = _max_resizes()
    resize_relaunch = False  # next relaunch gap bins `reshard`, not
                             # `restart` (planned membership change)
    cur_np = nproc_per_node  # this epoch's local trainer count (elastic)
    scale_seen = int(store.add("__scale_out", 0))
    down_at = None  # when the previous attempt's trainers were all dead
    while True:
        cur_world = nnodes * cur_np
        procs = []
        logs = []
        for local in range(cur_np):
            rank = node_rank * cur_np + local
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(cur_world),
                "PADDLE_LOCAL_RANK": str(local),
                "PADDLE_NODE_RANK": str(node_rank),
                "PADDLE_MASTER": master_addr,
                "PADDLE_STORE_PORT": str(store.port),
                "PADDLE_RESTART_EPOCH": str(epoch),
            })
            if down_at is not None:
                # relaunch: stamp the previous incarnation's death time
                # so the child's GoodputLedger bins the gap — `reshard`
                # after a planned membership change (scale/resize),
                # `restart` badput otherwise
                # (docs/OBSERVABILITY.md#goodput)
                env["PADDLE_TPU_GOODPUT_RESIZE_AT" if resize_relaunch
                    else "PADDLE_TPU_GOODPUT_DOWN_AT"] = repr(down_at)
            if log_dir:
                os.makedirs(log_dir, exist_ok=True)
                lf = open(os.path.join(log_dir, f"worker.{rank}.log"), "w")
                logs.append(lf)
                out = lf
            else:
                out = None
            procs.append(subprocess.Popen(
                [sys.executable, script, *script_args], env=env,
                stdout=out, stderr=subprocess.STDOUT if out else None))

        # supervise: watch local procs, the cluster restart epoch, and
        # (elastic) the scale-out request counter
        fail_code = None
        scale_event = None  # "in" | "out"
        resume_event = False
        resize_event = False
        resize_relaunch = False  # consumed by the spawn above
        while True:
            codes = [p.poll() for p in procs]
            if any(c == RESIZE_EXIT_CODE for c in codes) and \
                    all(c in (None, 0, RESIZE_EXIT_CODE) for c in codes):
                # consensus resize boundary (resilience.elastic): ranks
                # exiting 83 DEPARTED at an agreed step — a membership
                # change, never a crash. Distinguish the two flavors
                # within a short window: survivors still RUNNING means an
                # in-place resize (they hold the full state — just retire
                # the departed lanes and keep supervising); everyone
                # exiting 0/83 means a coordinated resize-relaunch at the
                # agreed world size.
                deadline = time.monotonic() + _RESIZE_GRACE
                while any(p.poll() is None for p in procs) and \
                        time.monotonic() < deadline:
                    time.sleep(0.1)
                codes = [p.poll() for p in procs]
                if any(c is None for c in codes):
                    keep_p, keep_l = [], []
                    for i, p in enumerate(procs):
                        if p.poll() == RESIZE_EXIT_CODE:
                            if logs:
                                logs[i].close()
                        else:
                            keep_p.append(p)
                            if logs:
                                keep_l.append(logs[i])
                    procs, logs = keep_p, keep_l
                    cur_np = len(procs)
                    resizes += 1
                    continue
                if all(c in (0, RESIZE_EXIT_CODE) for c in codes):
                    resize_event = True
                    if int(store.add("__restart_epoch", 0)) == epoch:
                        store.add("__restart_epoch", 1)
                    break
                # else: a real crash raced the boundary — fall through
            if any(c not in (None, 0) for c in codes):
                nonzero = [c for c in codes if c not in (None, 0)]
                if all(c == RESUMABLE_EXIT_CODE for c in nonzero):
                    # preempted trainers coordinate a final blocking save
                    # and exit together — give the stragglers a bounded
                    # window before deciding this was a resumable stop
                    deadline = time.monotonic() + _RESUME_GRACE
                    while any(p.poll() is None for p in procs) and \
                            time.monotonic() < deadline:
                        time.sleep(0.1)
                    codes = [p.poll() for p in procs]
                    if all(c in (0, RESUMABLE_EXIT_CODE) for c in codes):
                        resume_event = True
                        if int(store.add("__restart_epoch", 0)) == epoch:
                            store.add("__restart_epoch", 1)
                        break
                fail_code = next(
                    (c for c in codes
                     if c not in (None, 0, RESUMABLE_EXIT_CODE)),
                    RESUMABLE_EXIT_CODE)
                if np_range:
                    survivors = sum(1 for c in codes if c is None)
                    if survivors >= np_min:
                        # scale-in: continue smaller instead of failing
                        scale_event = "in"
                        cur_np = survivors
                        fail_code = None
                # signal the whole cluster (idempotent-enough: concurrent
                # failers over-bump, launchers re-read the counter below)
                if int(store.add("__restart_epoch", 0)) == epoch:
                    store.add("__restart_epoch", 1)
                break
            if all(c == 0 for c in codes):
                break
            if int(store.add("__restart_epoch", 0)) > epoch:
                break  # another node requested a restart
            if np_range:
                bumped = int(store.add("__scale_out", 0))
                if bumped > scale_seen:
                    # absorb the announcement even at full size — a stale
                    # bump must not fire a spurious scale-out after the
                    # next scale-in
                    scale_seen = bumped
                    if cur_np < np_max:
                        # replacement capacity announced: grow to max
                        scale_event = "out"
                        cur_np = np_max
                        if int(store.add("__restart_epoch", 0)) == epoch:
                            store.add("__restart_epoch", 1)
                        break
            time.sleep(0.2)

        if fail_code is None and scale_event is None and not resume_event \
                and not resize_event \
                and int(store.add("__restart_epoch", 0)) > epoch:
            # a PEER bumped the epoch before our own trainers' exit codes
            # were read. If this epoch carries a preemption verdict (the
            # consensus stop key the listeners publish), our trainers are
            # mid-final-save and about to exit resumable: give them the
            # grace window and classify the event as a resume, not a
            # crash that eats max_restarts
            try:
                preempt_verdict = store.get(
                    preempt_stop_key(epoch)) is not None
            except Exception:
                preempt_verdict = False
            if preempt_verdict:
                deadline = time.monotonic() + _RESUME_GRACE
                while any(p.poll() is None for p in procs) and \
                        time.monotonic() < deadline:
                    time.sleep(0.1)
                codes = [p.poll() for p in procs]
                if codes and any(c == RESUMABLE_EXIT_CODE for c in codes) \
                        and all(c in (0, RESUMABLE_EXIT_CODE)
                                for c in codes):
                    resume_event = True

        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            p.wait()
        down_at = time.time()  # goodput restart-gap stamp for relaunch
        for lf in logs:
            lf.close()

        final_codes = [p.returncode for p in procs]
        if not resume_event and final_codes and \
                any(c == RESUMABLE_EXIT_CODE for c in final_codes) and \
                all(c in (0, RESUMABLE_EXIT_CODE) for c in final_codes):
            # every trainer ultimately left cleanly or resumable: this was
            # a coordinated preemption stop regardless of what the
            # supervise loop concluded mid-flight (a straggler's blocking
            # final save outlasting the grace window can masquerade as a
            # scale-in or crash) — resume at FULL size, spend the resume
            # budget, leave max_restarts alone
            resume_event = True
            scale_event = None
            fail_code = None
            cur_np = len(procs)

        new_epoch = int(store.add("__restart_epoch", 0))
        if resize_event:
            # coordinated resize-relaunch (resilience.elastic): every
            # rank left at the agreed boundary — relaunch at the agreed
            # world size, stamping the gap into the goodput `reshard` bin
            # (PADDLE_TPU_GOODPUT_RESIZE_AT) and spending only the
            # PADDLE_TPU_MAX_RESIZES budget, never max_restarts/resumes
            resizes += 1
            if resizes > resize_budget:
                return _exit(RESIZE_EXIT_CODE)
            tgt = _resize_target_world(store, epoch)
            if tgt is not None:
                start = node_rank * cur_np
                new_local = max(0, min(cur_np, tgt - start))
                if new_local == 0:
                    return _exit(0)  # every rank of this host departed
                cur_np = new_local
            resize_relaunch = True
            if new_epoch == epoch:
                store.add("__restart_epoch", 1)
                new_epoch = int(store.add("__restart_epoch", 0))
            epoch = new_epoch
            continue
        if resume_event:
            # preemption stop, checkpoint committed: relaunch (trainers
            # resume from latest_step) without consuming max_restarts
            resumes += 1
            if resumes > resume_budget:
                return _exit(RESUMABLE_EXIT_CODE)
            if new_epoch == epoch:
                store.add("__restart_epoch", 1)
                new_epoch = int(store.add("__restart_epoch", 0))
            epoch = new_epoch
            continue
        if scale_event is not None:
            # membership change, not a crash: rewrite env and relaunch the
            # survivors at the new size without consuming max_restarts.
            # The epoch ALWAYS advances through the store counter, so
            # epoch-namespaced rendezvous keys can never be reused.
            resize_relaunch = True  # goodput: a resize, not a restart
            if new_epoch == epoch:
                store.add("__restart_epoch", 1)
                new_epoch = int(store.add("__restart_epoch", 0))
            epoch = new_epoch
            continue
        if fail_code is None and new_epoch == epoch:
            # clean local exit — but a peer may still fail and request a
            # restart; leaving now would also tear down the master store
            # under the cluster. Publish done and leave only when every
            # node finished this epoch cleanly (or a restart is requested).
            store.set(f"__done/{epoch}/{node_rank}", b"1")
            while True:
                new_epoch = int(store.add("__restart_epoch", 0))
                if new_epoch != epoch:
                    break
                if all(store.get(f"__done/{epoch}/{n}") is not None
                       for n in range(nnodes)):
                    return _exit(0)
                time.sleep(0.2)
        attempts += 1
        if attempts > max_restarts:
            return _exit(fail_code if fail_code is not None else 1)
        epoch = new_epoch


_LHB_INTERVAL = 0.5    # launcher heartbeat period (s)
_LHB_TIMEOUT = 4.0     # peer launcher declared dead after this silence
_SETTLE = 2.0          # membership join window per epoch
_BOOT_TIMEOUT = 30.0   # wait this long for an under-min join set (cold
                       # start pod stagger) before aborting the job
_CLAIM_TIMEOUT = 40.0  # a won-but-unpublished claim (claimer died mid-
                       # decision) is abandoned by bumping the epoch; must
                       # exceed _BOOT_TIMEOUT so the abort can fire first


def _elastic_multinode(script, script_args, master_addr, store, nnodes,
                       node_rank, np_min, np_max, max_restarts, log_dir,
                       resume_budget=8):
    """Cluster-wide elastic membership (reference:
    fleet/elastic/manager.py:126 — etcd-leased node registry with a leader
    deciding the world; here the TCPStore is the registry).

    Per epoch: every live launcher registers ``__join/{epoch}/{node}``,
    the LOWEST-rank joiner (with an atomic-claim fallback should it die
    mid-decision) publishes the verdict ``__world/{epoch}`` = the member
    list; members spawn one trainer each with contiguous re-ranked
    PADDLE_TRAINER_ID. Launchers heartbeat ``__lhb/{node}``; a stale
    member heartbeat or a local trainer failure bumps the shared epoch,
    driving a new membership round — survivors >= min continue smaller
    (scale-in). A late/re-started launcher whose join missed the verdict
    announces itself through ``__scale_out`` and is absorbed by the next
    round (scale-out). Scale events never consume ``max_restarts``; only
    local trainer crashes do."""
    try:
        return _elastic_multinode_loop(
            script, script_args, master_addr, store, nnodes, node_rank,
            np_min, np_max, max_restarts, log_dir, resume_budget)
    except (ConnectionError, OSError) as e:
        # only claim "store lost" when the store actually IS unreachable —
        # a FileNotFoundError from Popen or a log-dir PermissionError must
        # keep its traceback, not masquerade as a network failure
        try:
            store.get("__probe")
        except Exception:
            print(f"[elastic] job store lost ({e!r}) — the store-hosting "
                  "launcher is gone; failing this node", file=sys.stderr)
            return 1
        raise


def _elastic_multinode_loop(script, script_args, master_addr, store,
                            nnodes, node_rank, np_min, np_max,
                            max_restarts, log_dir, resume_budget=8):
    epoch = int(store.add("__restart_epoch", 0))
    scale_seen = int(store.add("__scale_out", 0))
    attempts = 0
    resumes = 0

    def mn_exit(code, cur_epoch, members):
        """Membership-scoped exit sync: acks are keyed by (epoch, node) so
        a dead launcher's ack from an OLD membership can never satisfy the
        store host's wait and tear the store down under a replacement
        launcher still using it. The store-hosting node waits (bounded)
        for the FINAL epoch's members; a host crash-exit still ends the
        job — the store is the rendezvous, like the reference's etcd."""
        try:
            store.set(f"__exit_ack/{cur_epoch}/{node_rank}", b"1")
            if store._server:
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline and not all(
                        store.get(f"__exit_ack/{cur_epoch}/{n}")
                        is not None for n in members):
                    time.sleep(0.1)
        except Exception:
            pass
        return code

    def beat():
        store.set(f"__lhb/{node_rank}", str(time.time()).encode())

    def bump_if_current(e):
        if int(store.add("__restart_epoch", 0)) == e:
            store.add("__restart_epoch", 1)

    def wait_next_epoch(e):
        while int(store.add("__restart_epoch", 0)) == e:
            beat()
            time.sleep(0.2)
        return int(store.add("__restart_epoch", 0))

    down_at = None  # when the previous round's trainer died (goodput)
    resize_relaunch = False  # next round's gap bins `reshard` (planned)
    while True:
        beat()
        store.set(f"__join/{epoch}/{node_rank}", b"1")

        # settle window: fast-path out when every possible node joined
        t0 = time.monotonic()
        while time.monotonic() - t0 < _SETTLE:
            if all(store.get(f"__join/{epoch}/{n}") is not None
                   for n in range(nnodes)):
                break
            time.sleep(0.1)

        verdict_key = f"__world/{epoch}"
        t_claim = time.monotonic()
        stale_epoch = False
        while store.get(verdict_key) is None:
            if int(store.add("__restart_epoch", 0)) > epoch:
                # round superseded (e.g. a wedged claim was abandoned by a
                # peer bumping the epoch) — re-join at the new one
                stale_epoch = True
                break
            elapsed = time.monotonic() - t_claim
            joined = [n for n in range(nnodes)
                      if store.get(f"__join/{epoch}/{n}") is not None]
            lowest = joined and joined[0] == node_rank
            fallback = elapsed > 2 * _SETTLE
            if (lowest or fallback) and len(joined) >= np_min and \
                    int(store.add(f"__claim/{epoch}", 1)) == 1:
                # decide only with quorum: at cold start launchers may
                # join many seconds apart (pod stagger) — an under-min
                # join set WAITS (up to _BOOT_TIMEOUT) instead of
                # aborting a job that is one second from healthy
                store.set(verdict_key,
                          ",".join(map(str, joined)).encode())
            if elapsed > _BOOT_TIMEOUT and len(joined) < np_min and \
                    int(store.add(f"__claim/{epoch}", 1)) == 1:
                store.set(verdict_key, b"__abort")
            if elapsed > _CLAIM_TIMEOUT:
                # a claimer won __claim then died before publishing: no
                # verdict can ever appear for THIS epoch — abandon it
                # (fresh epoch = fresh claim key, the wedge clears)
                bump_if_current(epoch)
            beat()
            time.sleep(0.1)
        if stale_epoch:
            epoch = int(store.add("__restart_epoch", 0))
            continue
        verdict = store.get(verdict_key)
        if verdict == b"__abort":
            # drain acks from every launcher that saw this round, so the
            # store host doesn't drop the server mid-poll under peers
            joined = [n for n in range(nnodes)
                      if store.get(f"__join/{epoch}/{n}") is not None]
            return mn_exit(1, epoch, joined)
        members = [int(x) for x in verdict.decode().split(",")]
        world = len(members)

        if node_rank not in members:
            # our join missed this epoch's verdict: we ARE the replacement
            # capacity — announce and fold into the next round
            store.add("__scale_out", 1)
            scale_seen = int(store.add("__scale_out", 0))
            epoch = wait_next_epoch(epoch)
            continue

        rank = members.index(node_rank)
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_LOCAL_RANK": "0",
            "PADDLE_NODE_RANK": str(node_rank),
            "PADDLE_MASTER": master_addr,
            "PADDLE_STORE_PORT": str(store.port),
            "PADDLE_RESTART_EPOCH": str(epoch),
        })
        if down_at is not None:
            # relaunch round: stamp the previous trainer's death time for
            # the child's goodput accounting — `reshard` after a planned
            # membership change, `restart` otherwise
            env["PADDLE_TPU_GOODPUT_RESIZE_AT" if resize_relaunch
                else "PADDLE_TPU_GOODPUT_DOWN_AT"] = repr(down_at)
        resize_relaunch = False
        lf = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            # epoch-scoped name: the previous epoch's log holds the crash
            # that CAUSED this round — never truncate it
            lf = open(os.path.join(
                log_dir, f"worker.n{node_rank}.e{epoch}.log"), "w")
        proc = subprocess.Popen(
            [sys.executable, script, *script_args], env=env, stdout=lf,
            stderr=subprocess.STDOUT if lf else None)

        fail_code = None
        last_beat = 0.0
        grace = time.monotonic() + _LHB_TIMEOUT  # peers re-join slowly
        # staleness by VALUE-change observation on the reader's monotonic
        # clock: cross-host wall-clock arithmetic would declare a
        # skewed-NTP peer dead forever and churn restarts
        lhb_seen: dict = {}

        def lhb_stale(n: int) -> bool:
            v = store.get(f"__lhb/{n}")
            if v is None:
                return False  # never beat: still booting, not dead
            prev = lhb_seen.get(n)
            mono = time.monotonic()
            if prev is None or prev[0] != v:
                lhb_seen[n] = (v, mono)
                return False
            return mono - prev[1] > _LHB_TIMEOUT

        while True:
            now = time.monotonic()
            if now - last_beat >= _LHB_INTERVAL:
                beat()
                last_beat = now
            code = proc.poll()
            if code not in (None, 0):
                fail_code = code
                bump_if_current(epoch)
                break
            if code == 0:
                break
            if int(store.add("__restart_epoch", 0)) > epoch:
                break  # cluster-wide membership change requested
            bumped = int(store.add("__scale_out", 0))
            if bumped > scale_seen:
                scale_seen = bumped
                if world < np_max:
                    resize_relaunch = True  # planned membership growth
                    bump_if_current(epoch)
                    break
            if now > grace:
                # a host that DEPARTED at a consensus resize boundary
                # stops beating on purpose — never read that as a death
                stale = [n for n in members if n != node_rank
                         and lhb_stale(n) and
                         store.get(f"__departed/{epoch}/{n}") is None]
                if stale:
                    bump_if_current(epoch)
                    break
            time.sleep(0.2)

        if proc.poll() is None:
            proc.terminate()
        proc.wait()
        down_at = time.time()  # goodput restart-gap stamp for relaunch
        if lf:
            lf.close()

        if proc.returncode == RESIZE_EXIT_CODE:
            # this host's rank departed at a consensus resize boundary
            # (resilience.elastic): the surviving members carry the full
            # state and continue IN PLACE. Mark the departure (so peers
            # don't read our stopping heartbeat as a death) and leave the
            # job cleanly — no epoch bump, no budget spent.
            try:
                store.set(f"__departed/{epoch}/{node_rank}", b"1")
            except Exception:
                pass
            return mn_exit(0, epoch, [])

        if fail_code is None and proc.returncode == 0 and \
                int(store.add("__restart_epoch", 0)) == epoch:
            # clean local exit: leave when every MEMBER finished this
            # epoch (or a membership change supersedes it)
            store.set(f"__done/{epoch}/{node_rank}", b"1")
            while True:
                beat()
                if int(store.add("__restart_epoch", 0)) != epoch:
                    break
                bumped = int(store.add("__scale_out", 0))
                if bumped > scale_seen and world < np_max:
                    # a replacement announced itself during completion:
                    # run one more round at the bigger size instead of
                    # exiting and tearing the store down under it
                    scale_seen = bumped
                    resize_relaunch = True
                    bump_if_current(epoch)
                    break
                if all(store.get(f"__done/{epoch}/{n}") is not None or
                       store.get(f"__departed/{epoch}/{n}") is not None
                       for n in members):
                    return mn_exit(0, epoch, members)
                time.sleep(0.2)

        if fail_code == RESUMABLE_EXIT_CODE:
            # preempted-with-checkpoint (resilience contract): rejoin the
            # next membership round without consuming the crash budget
            resumes += 1
            if resumes > resume_budget:
                return mn_exit(RESUMABLE_EXIT_CODE, epoch, [])
        elif fail_code is not None:
            attempts += 1
            if attempts > max_restarts:
                # exit immediately: surviving members are CONTINUING (they
                # rejoin the next round), so waiting for their exit acks
                # would only stall 15 s. If this node hosts the store the
                # job dies with it — the store IS the rendezvous
                # (reference analog: losing etcd fails the job)
                return mn_exit(fail_code, epoch, [])
        new_epoch = int(store.add("__restart_epoch", 0))
        if new_epoch == epoch:  # ensure forward progress
            store.add("__restart_epoch", 1)
            new_epoch = int(store.add("__restart_epoch", 0))
        epoch = new_epoch


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed trainer processes")
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument("--master", type=str, default=None)
    parser.add_argument("--max_restarts", type=int, default=0)
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--np", type=str, default=None, dest="np_arg",
                        help="elastic trainer-count bounds: N or min:max "
                             "(reference fleet/elastic --np)")
    parser.add_argument("--max_resumes", type=int, default=None,
                        help="preemption relaunch budget (trainers exiting "
                             "with the resumable code; default "
                             "$PADDLE_TPU_MAX_RESUMES or 8)")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)
    return launch(args.script, args.script_args, args.nproc_per_node,
                  args.master, args.max_restarts, args.log_dir,
                  args.node_rank, args.nnodes,
                  np_range=parse_np(args.np_arg),
                  max_resumes=args.max_resumes)


if __name__ == "__main__":
    sys.exit(main())
