"""Tensor sharding annotations — the GSPMD front door.

Parity with the reference's auto-parallel marking API
(``python/paddle/distributed/auto_parallel/interface.py`` shard_tensor +
``placement_type.py`` Shard/Replicate/Partial): a tensor is placed on the
default mesh with a per-dim placement; XLA's sharding propagation (the analog
of the reference's Completer, ``completion.py:920``) spreads the annotations
through the program and inserts collectives — the Resharder's job — during
compilation.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

from paddle_tpu.core.tensor import Tensor
from .mesh import get_mesh

__all__ = ["Shard", "Replicate", "Partial", "shard_tensor", "reshard",
           "named_sharding", "spec_of", "with_sharding_constraint"]


class Placement:
    pass


class Shard(Placement):
    """Shard tensor dim ``dim`` across a mesh axis."""

    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("S", self.dim))


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("R")


class Partial(Placement):
    """Pending-reduction placement (reference: Partial status). GSPMD has
    no user-visible partial-sum annotation — XLA tracks pending reductions
    internally and inserts the reduce where the value is consumed — so a
    user-placed Partial cannot be honored. Using it in ``placements``
    raises rather than silently behaving as Replicate (which would skip
    the reduction the caller asked for)."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


def _placements_to_spec(placements: Sequence, mesh, ndim: int):
    """placements[i] describes MESH AXIS i (paddle convention): build the
    per-tensor-dim PartitionSpec."""
    from jax.sharding import PartitionSpec
    dim_axes: List[Optional[object]] = [None] * ndim
    for axis_idx, pl in enumerate(placements):
        if isinstance(pl, Partial):
            raise NotImplementedError(
                "Partial placement cannot be annotated at the GSPMD "
                "surface (XLA owns pending-reduction state). Compute the "
                "reduction explicitly (all_reduce / psum inside "
                "dist.spmd) or use Replicate/Shard placements.")
        if isinstance(pl, Shard):
            name = mesh.axis_names[axis_idx]
            cur = dim_axes[pl.dim]
            if cur is None:
                dim_axes[pl.dim] = name
            elif isinstance(cur, tuple):
                dim_axes[pl.dim] = cur + (name,)
            else:
                dim_axes[pl.dim] = (cur, name)
    return PartitionSpec(*dim_axes)


def named_sharding(spec, mesh=None):
    import jax
    mesh = mesh or get_mesh()
    return jax.sharding.NamedSharding(mesh, spec)


def shard_tensor(x, mesh=None, placements=None, spec=None,
                 stop_gradient=None):
    """Place ``x`` on the mesh (reference: dist.shard_tensor).

    Either paddle-style ``placements`` (one Placement per mesh axis) or a
    jax ``PartitionSpec`` via ``spec``. Returns a Tensor whose storage is a
    global sharded jax array; ``_sharding_spec`` records the spec for the
    jit path (TrainStep propagates it into in/out_shardings).
    """
    import jax
    from jax.sharding import PartitionSpec

    mesh = mesh or get_mesh()
    if mesh is None:
        raise RuntimeError("no default mesh; call dist.init_mesh first")
    t = x if isinstance(x, Tensor) else Tensor(x)
    if spec is None:
        placements = placements or []
        spec = _placements_to_spec(placements, mesh, t.ndim)
    sharding = jax.sharding.NamedSharding(mesh, spec)
    arr = jax.device_put(t.data, sharding)
    out = Tensor(arr, stop_gradient=t.stop_gradient
                 if stop_gradient is None else stop_gradient, name=t.name)
    out._sharding_spec = spec
    # in-place annotate Parameters so layers keep their identity
    if isinstance(x, Tensor):
        x._data = arr
        x._sharding_spec = spec
        if stop_gradient is not None:
            x.stop_gradient = stop_gradient
        return x
    return out


def reshard(x, mesh=None, placements=None, spec=None):
    """Change a tensor's placement (reference: Resharder, reshard.py:2668 —
    here a single device_put; XLA emits the transfer collectives)."""
    return shard_tensor(x, mesh, placements, spec)


def spec_of(t: Tensor):
    """The PartitionSpec annotation of a tensor (fully-replicated if none)."""
    from jax.sharding import PartitionSpec
    s = getattr(t, "_sharding_spec", None)
    return s if s is not None else PartitionSpec()


def with_sharding_constraint(t, spec, mesh=None):
    """In-trace sharding annotation (the compiler-visible hint — reference
    analog: dist attrs on intermediate vars)."""
    import jax
    from paddle_tpu.core.autograd import apply_op
    mesh = mesh or get_mesh()
    sharding = jax.sharding.NamedSharding(mesh, spec)
    return apply_op(lambda v: jax.lax.with_sharding_constraint(v, sharding),
                    t, op_name="sharding_constraint")
