"""Collective communication API.

Parity surface: ``python/paddle/distributed/communication/`` (all_reduce,
all_gather, reduce_scatter, broadcast, all_to_all, send/recv, barrier) and the
C++ ProcessGroup family (SURVEY.md §2.4). TPU-native redesign: a collective is
not a runtime call into NCCL — it is an *XLA op over a named mesh axis*
(psum/all_gather/ppermute compiled onto ICI). Per-rank semantics (each rank
holding different data) exist inside :func:`spmd` (shard_map) regions; that is
where these functions are used, exactly as the reference uses them inside a
rank's train script. The reference's process groups become :class:`Group`
objects naming mesh axes.

Example (loss-parity test pattern, SURVEY.md §4)::

    mesh = dist.init_mesh({"dp": 8})

    @dist.spmd(mesh=mesh, in_specs=P("dp"), out_specs=P())
    def global_mean(local_batch):
        s = dist.all_reduce(local_batch.sum(), group=dist.Group(("dp",)))
        return s / total
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import numpy as np

from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.observability.comm import (comm_event, comm_scope,
                                           payload_bytes)
from .mesh import get_mesh

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "all_reduce",
           "all_gather", "all_gather_object", "reduce", "reduce_scatter",
           "broadcast", "all_to_all", "scatter", "send", "recv", "barrier",
           "spmd", "shard_map", "P"]

from jax.sharding import PartitionSpec as P  # re-export for specs


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communicator. Two flavors, matching the two planes the reference's
    ProcessGroup serves:

    - **device groups**: a tuple of mesh axis names — XLA collectives
      inside shard_map regions (the ring-id, reduced to its essence).
    - **host groups**: an explicit list of global host-process ``ranks`` —
      the store-backed OBJECT collectives address processes directly, so
      arbitrary rank subsets are representable there (and only there).
    """

    _registry = {}
    _next_id = 0

    def __init__(self, axes: Union[str, Sequence[str]], mesh=None,
                 ranks: Optional[Sequence[int]] = None):
        self.axes: Tuple[str, ...] = (axes,) if isinstance(axes, str) \
            else tuple(axes)
        self._mesh = mesh
        if ranks is not None:
            ranks = tuple(int(r) for r in ranks)
            if len(set(ranks)) != len(ranks):
                raise ValueError(f"duplicate ranks in group: {ranks}")
        # USER order is the group-rank order (reference new_group
        # semantics): scatter payload gi goes to ranks[gi], gathers return
        # in this order — never silently sorted
        self.ranks: Optional[Tuple[int, ...]] = ranks

    @property
    def mesh(self):
        return self._mesh or get_mesh()

    @property
    def nranks(self) -> int:
        if self.ranks is not None:
            return len(self.ranks)
        m = self.mesh
        if m is None:
            return 1
        return int(np.prod([m.shape[a] for a in self.axes]))

    @property
    def axis_name(self):
        return self.axes if len(self.axes) > 1 else self.axes[0]

    def __repr__(self):
        return f"Group(axes={self.axes}, nranks={self.nranks})"


def new_group(ranks=None, axes=None, mesh=None) -> Group:
    """Create a communicator. On a mesh, DEVICE groups are axis-aligned:
    pass ``axes``. An explicit ``ranks`` subset builds a HOST group —
    usable by the store-backed object collectives (which address host
    processes directly); arbitrary rank lists still have no XLA analog, so
    a host group inside a shard_map region raises."""
    g = None
    if axes is None:
        m = mesh or get_mesh()
        full = int(np.prod(list(m.shape.values()))) if m is not None \
            else None
        if ranks is not None and (
                m is None or list(ranks) != list(range(full))):
            # anything but the identity covering of the mesh — a subset, a
            # permutation, no mesh at all — is a host-rank group for the
            # object-collective plane (order/dups validated by Group)
            g = Group((), mesh, ranks=ranks)
        else:
            axes = tuple(m.axis_names) if m is not None else ("dp",)
    if g is None:
        g = Group(axes, mesh)
    gid = Group._next_id
    Group._next_id += 1
    Group._registry[gid] = g
    g.id = gid
    return g


def get_group(gid: int) -> Optional[Group]:
    return Group._registry.get(gid)


def _axis_size(axis):
    """Bound-axis size across jax versions: ``jax.lax.axis_size`` where it
    exists, else the classic ``psum(1, axis)`` idiom (statically evaluated
    for named axes; raises the same unbound-name NameError)."""
    import jax
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis)
    return jax.lax.psum(1, axis)


def _linear_rank(axes):
    """Group-linear rank inside a mapped context (axes[0] major — the
    same flattening order jax collectives use for axis tuples)."""
    import jax
    idx = jax.lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * _axis_size(a) + jax.lax.axis_index(a)
    return idx


def _axes(group) -> Tuple[str, ...]:
    if group is None:
        m = get_mesh()
        return tuple(m.axis_names) if m is not None else ()
    if isinstance(group, Group):
        if group.ranks is not None:
            raise RuntimeError(
                "host-rank groups (new_group(ranks=[...])) serve the "
                "store-backed OBJECT collectives; device collectives need "
                "an axis-aligned group (new_group(axes=('dp',)))")
        return group.axes
    if isinstance(group, str):
        return (group,)
    return tuple(group)


def _in_mapped_context(axes) -> bool:
    """True when the named axes are bound (i.e. we are inside shard_map)."""
    try:
        for a in axes:
            _axis_size(a)
        return True
    except NameError:  # jax's unbound-axis-name error
        return False


def _collective(fn, t, op_name):
    if isinstance(t, Tensor):
        return apply_op(fn, t, op_name=op_name)
    return fn(t)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce across the group; every rank gets the result
    (reference: communication/all_reduce.py → ProcessGroup::AllReduce)."""
    import jax
    axes = _axes(group)
    if not axes or not _in_mapped_context(axes):
        if group is None or Group(axes).nranks == 1:
            return tensor  # single-rank: identity, matching paddle
        raise RuntimeError(
            "per-rank collectives run inside dist.spmd/shard_map regions; "
            "outside, arrays are global and all_reduce has no meaning")
    red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
           ReduceOp.MIN: jax.lax.pmin, ReduceOp.AVG: jax.lax.pmean}
    if op == ReduceOp.PROD:
        def f(x):
            import jax.numpy as jnp
            logs = jax.lax.psum(jnp.log(jnp.abs(x)), axes)
            sign = jax.lax.psum((x < 0).astype(jnp.int32), axes)
            return jnp.exp(logs) * jnp.where(sign % 2 == 1, -1.0, 1.0)
    else:
        def f(x):
            return red[op](x, axes)
    with comm_scope("all_reduce", axes, payload=tensor,
                    extra={"reduce_op": op}):
        return _collective(f, tensor, f"all_reduce_{op}")


def all_gather(tensor_or_list, tensor=None, group=None, sync_op=True,
               axis=0):
    """Gather shards from every rank (concatenated along ``axis``).

    Supports both call shapes: paddle's ``all_gather(out_list, t)`` and the
    functional ``out = all_gather(t)``.
    """
    import jax
    out_list = None
    if tensor is None:
        t = tensor_or_list
    else:
        out_list, t = tensor_or_list, tensor
    axes = _axes(group)
    if not axes or not _in_mapped_context(axes):
        if group is None or Group(axes).nranks == 1:
            result, n = t, 1  # identity: the "gather" holds one copy
        else:
            raise RuntimeError("all_gather outside a dist.spmd region")
    else:
        def f(x):
            return jax.lax.all_gather(x, axes, axis=axis, tiled=True)
        with comm_scope("all_gather", axes, payload=t):
            result = _collective(f, t, "all_gather")
        n = Group(axes).nranks
    if out_list is not None:
        from paddle_tpu import ops
        out_list.extend(ops.split(result, n, axis=axis)
                        if n > 1 else [result])
        return None
    return result


_obj_seq: dict = {}  # (kind, group-tag) -> per-call sequence counter


def _multi_host_world():
    """(rank, world) of HOST PROCESSES — launcher env when present, else
    the PJRT process view. Deliberately not get_world_size(): that falls
    back to the device count, and object collectives move host objects
    between processes, not chips. The jax fallback is only touched when
    the env vars are absent (calling it would initialize the backend)."""
    import os
    rank = os.environ.get("PADDLE_TRAINER_ID")
    world = os.environ.get("PADDLE_TRAINERS_NUM")
    if rank is not None and world is not None:
        return int(rank), int(world)
    import jax
    return (int(rank) if rank is not None else jax.process_index(),
            int(world) if world is not None else jax.process_count())


def _group_members(group, what: str):
    """(member ranks, my global rank, store tag) for an object collective.

    ``group=None`` → the full world. A host-rank group
    (``new_group(ranks=[...])``) scopes the collective to its members —
    store keys are namespaced by the member tuple so concurrent groups
    never collide. Axis (device) groups are rejected: they partition
    chips, not host processes."""
    rank, world = _multi_host_world()
    if group is None:
        return tuple(range(world)), rank, "w"
    ranks = getattr(group, "ranks", None)
    if ranks is None:
        if getattr(group, "nranks", None) in (None, world):
            return tuple(range(world)), rank, "w"
        raise NotImplementedError(
            f"{what}: device (axis) groups do not scope host-object "
            "collectives; build a host group with new_group(ranks=[...])")
    bad = [r for r in ranks if not 0 <= r < world]
    if bad:
        raise ValueError(f"{what}: ranks {bad} outside world {world}")
    return ranks, rank, "-".join(map(str, ranks))


def _reaped_barrier(store, name: str, world: int):
    """barrier_via_store + key reaping: the LAST process to leave deletes
    the barrier namespace (counter/done/left keys), so per-call barriers
    don't grow the store without bound."""
    import os
    from .tcp_store import barrier_via_store
    barrier_via_store(store, name, world)
    epoch = os.environ.get("PADDLE_RESTART_EPOCH", "0")
    if store.add(f"__barrier/{epoch}/{name}/left", 1) == world:
        store.delete_prefix(f"__barrier/{epoch}/{name}")


def _obj_key(kind: str, tag: str = "w") -> str:
    """Unique per-call store namespace. All MEMBER processes issue a
    group's collectives in the same program order, so a per-(kind, group)
    counter is consistent; the member-tuple tag keeps concurrent groups
    apart and the elastic restart epoch prevents reuse across
    relaunches."""
    import os
    epoch = os.environ.get("PADDLE_RESTART_EPOCH", "0")
    seq = _obj_seq.get((kind, tag), 0)
    _obj_seq[(kind, tag)] = seq + 1
    return f"__objcol/{epoch}/{tag}/{kind}{seq}"


def all_gather_object(object_list, obj, group=None):
    """Host-object gather (reference: communication/all_gather.py
    all_gather_object). Single process: trivial. Multi-process (DCN): each
    rank publishes its pickled object to the job's TCPStore and reads the
    others — the store-backed control plane the reference implements over
    its gloo/TCP store.

    Non-member contract: on ranks OUTSIDE ``group`` this is a no-op and
    ``object_list`` is left untouched (empty if passed empty) — matching
    the reference's non-member pass-through. Symmetric caller code that
    indexes ``object_list`` on every rank must guard on membership."""
    import pickle
    members, rank, tag = _group_members(group, "all_gather_object")
    if rank not in members:
        return None  # non-members pass through (reference semantics)
    if len(members) <= 1:
        object_list.append(obj)
        return None
    from .tcp_store import job_store
    store = job_store()
    key = _obj_key("ag", tag)
    blob = pickle.dumps(obj)
    with comm_scope("all_gather_object", (), nbytes=len(blob),
                    extra={"members": len(members)}):
        store.set(f"{key}/{rank}", blob)
        for r in members:
            object_list.append(pickle.loads(store.wait(f"{key}/{r}")))
        # every member has read everything: safe to drop our slot
        _reaped_barrier(store, key, len(members))
        store.delete_key(f"{key}/{rank}")
    return None


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """psum then keep (XLA has no single-dst reduce cheaper than allreduce
    on ICI; the reference's reduce is NCCL Reduce — result equal on dst,
    undefined elsewhere; we return the reduced value everywhere)."""
    return all_reduce(tensor, op, group, sync_op)


def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
                   axis=0):
    """Reduce + scatter shards (reference: communication/reduce_scatter.py).
    Input per-rank shape [N, ...] -> output [N/world, ...]."""
    import jax
    axes = _axes(group)
    if not axes or not _in_mapped_context(axes):
        if group is None or Group(axes).nranks == 1:
            return tensor
        raise RuntimeError("reduce_scatter outside a dist.spmd region")

    def f(x):
        return jax.lax.psum_scatter(x, axes, scatter_dimension=axis,
                                    tiled=True)
    with comm_scope("reduce_scatter", axes, payload=tensor,
                    extra={"reduce_op": op}):
        return _collective(f, tensor, "reduce_scatter")


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Replicate src's value across the group. On a mesh this is a
    psum of a rank-masked select (memory-lean collective-select)."""
    import jax
    import jax.numpy as jnp
    axes = _axes(group)
    if not axes or not _in_mapped_context(axes):
        if group is None or Group(axes).nranks == 1:
            return tensor
        raise RuntimeError("broadcast outside a dist.spmd region")
    n = Group(axes).nranks
    if not 0 <= src < n:
        # the masked-select psum would silently yield zeros for an absent
        # src rank — keep the old all_gather+index failure mode
        raise ValueError(f"broadcast src {src} out of range for group "
                         f"of {n}")

    def f(x):
        # psum of a masked select: peak memory 2x the tensor, not the
        # world-size x of an all_gather+index — this is how large params
        # broadcast over the mesh
        idx = _linear_rank(axes)
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        if jnp.issubdtype(x.dtype, jnp.bool_):
            return jax.lax.psum(masked.astype(jnp.int8), axes).astype(
                x.dtype)
        return jax.lax.psum(masked, axes)
    with comm_scope("broadcast", axes, payload=tensor,
                    extra={"src": src}):
        return _collective(f, tensor, "broadcast")


def all_to_all(in_tensor_list, out_tensor_list=None, group=None,
               sync_op=True, split_axis=0, concat_axis=0):
    """All-to-all over the group (reference: communication/all_to_all.py →
    the MoE dispatch primitive ``global_scatter``). Functional form: pass a
    single tensor whose ``split_axis`` divides by world size."""
    import jax
    axes = _axes(group)
    single = not isinstance(in_tensor_list, (list, tuple))
    if not axes or not _in_mapped_context(axes):
        if group is None or Group(axes).nranks == 1:
            return in_tensor_list
        raise RuntimeError("all_to_all outside a dist.spmd region")
    axis_name = axes if len(axes) > 1 else axes[0]
    if single:
        def f(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                                      concat_axis=concat_axis, tiled=True)
        with comm_scope("all_to_all", axes, payload=in_tensor_list):
            return _collective(f, in_tensor_list, "all_to_all")
    # list form: stack -> all_to_all -> unstack into out_tensor_list
    from paddle_tpu import ops
    stacked = ops.stack(list(in_tensor_list), axis=0)

    def f(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                                  tiled=False)
    with comm_scope("all_to_all", axes, payload=stacked):
        out = _collective(f, stacked, "all_to_all")
    outs = [out[i] for i in range(len(in_tensor_list))]
    if out_tensor_list is not None:
        out_tensor_list.extend(outs)
        return None
    return outs


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Take src's i-th shard on rank i (reference: communication/scatter)."""
    import jax
    axes = _axes(group)
    if not axes or not _in_mapped_context(axes):
        if group is None or Group(axes).nranks == 1:
            return tensor
        raise RuntimeError("scatter outside a dist.spmd region")

    def f(x):
        # all_to_all then keep src's lane: src's slice i reaches rank i
        # with peak memory 2x the tensor, not the world-size x of the old
        # all_gather+index formulation
        axis = axes[0] if len(axes) == 1 else axes
        n = _axis_size(axis)
        chunk = x.shape[0] // n
        recv = jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
        return jax.lax.dynamic_slice_in_dim(recv, src * chunk, chunk, 0)
    if tensor_list is not None:
        from paddle_tpu import ops
        tensor = ops.concat(list(tensor_list), axis=0)
    with comm_scope("scatter", axes, payload=tensor, extra={"src": src}):
        return _collective(f, tensor, "scatter")


def send(tensor, dst=0, group=None, sync_op=True):
    """P2P send — on a mesh this is a collective_permute (ppermute) to the
    destination; pair with :func:`recv` in the same spmd program. The
    reference's send_v2/recv_v2 (PP micro-batch transfer) maps to
    :func:`p2p_shift` which is what the pipeline engine uses."""
    # record the attempt: a flight-recorder postmortem should show which
    # rank tried an unsupported raw P2P before the crash
    comm_event("send", (), payload=tensor, extra={"dst": dst})
    raise NotImplementedError(
        "raw send/recv have no XLA analog; use dist.p2p_shift (ppermute) "
        "inside an spmd region — the PP engine does")


def recv(tensor, src=0, group=None, sync_op=True):
    comm_event("recv", (), payload=tensor, extra={"src": src})
    raise NotImplementedError(
        "raw send/recv have no XLA analog; use dist.p2p_shift (ppermute) "
        "inside an spmd region — the PP engine does")


def p2p_shift(tensor, group=None, shift: int = 1):
    """Shift values along a mesh axis ring: rank i's data goes to rank
    (i+shift) % n — the ICI-native form of send/recv used for pipeline
    micro-batch handoff (reference: p2p_communication.py _p2p_helper)."""
    import jax
    axes = _axes(group)
    axis = axes[0] if len(axes) == 1 else axes

    def f(x):
        n = _axis_size(axis)
        perm = [(i, (i + shift) % n) for i in range(n)]
        return jax.lax.ppermute(x, axis, perm)
    with comm_scope("p2p_shift", axes, payload=tensor,
                    extra={"shift": shift}):
        return _collective(f, tensor, "p2p_shift")


def barrier(group=None):
    """Device-level barriers are implicit in XLA program boundaries; this
    synchronizes the host on outstanding work (paddle barrier blocks the
    host the same way)."""
    import jax
    axes = getattr(group, "axes", ()) if group is not None else ()
    with comm_scope("barrier", axes):
        jax.effects_barrier()
    return None


def shard_map(fn, mesh=None, in_specs=None, out_specs=None,
              check_rep=False):
    """Thin wrapper over jax shard_map operating on Tensors."""
    import jax
    from jax.sharding import PartitionSpec

    mesh = mesh or get_mesh()

    def unwrap(x):
        return x.data if isinstance(x, Tensor) else x

    def run(*args):
        # lazy: fleet.utils <-> collective would cycle at module scope
        from .fleet.utils import shard_map_compat
        inner = shard_map_compat(
            lambda *a: jax.tree_util.tree_map(
                unwrap, fn(*[Tensor(x) if hasattr(x, "dtype") else x
                             for x in a]),
                is_leaf=lambda v: isinstance(v, Tensor)),
            mesh, in_specs, out_specs, check_vma=check_rep)
        out = inner(*[unwrap(a) for a in args])
        return jax.tree_util.tree_map(
            lambda x: Tensor(x) if hasattr(x, "dtype") else x, out)
    return run


def spmd(fn=None, mesh=None, in_specs=None, out_specs=None):
    """Decorator form of :func:`shard_map` — the region where per-rank
    (paddle-style) collective semantics hold."""
    def wrap(f):
        return shard_map(f, mesh, in_specs, out_specs)
    return wrap(fn) if fn is not None else wrap
