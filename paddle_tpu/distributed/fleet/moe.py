"""Mixture-of-Experts with expert parallelism.

Parity with the reference's MoE stack (``python/paddle/incubate/distributed/
models/moe/moe_layer.py:261`` MoELayer, ``moe/gate/`` naive/switch/gshard
gates, ``MoEScatter``/``MoEGather`` PyLayers over the ``global_scatter/
global_gather`` all-to-all ops, and the cutlass grouped GEMM
``phi/kernels/fusion/cutlass/moe/moe_kernel.cu``).

TPU-native redesign: experts are *stacked* weight tensors
``[E, d_model, d_hidden]`` sharded on the ``ep`` mesh axis, so one einsum is
the grouped GEMM and GSPMD lowers the token redistribution to the
all-to-all the reference launches explicitly. Over-capacity tokens drop
(contribute zero), matching ``global_scatter`` semantics.

Two dispatch formulations behind the same API (``dispatch_mode``):

* ``"ragged"`` (default) — index routing, the ``global_scatter/
  global_gather`` shape: each of the T*K (token, expert) assignments gets a
  capacity slot ``e*C + position`` (position = running count within the
  expert, the same order-dependent rule as the dense path, so drops are
  bit-identical). The data movement is GATHER-ONLY in both directions:
  tiny int32 scatters invert assignment→slot into a slot→token map once,
  then dispatch-forward, dispatch-backward, combine-forward and
  combine-backward are all row gathers (``custom_vjp`` supplies the
  inverse-map backward) — TPU scatters of [*, M] rows serialize badly and
  were the measured bottleneck of the scatter-add formulation. Peak
  intermediate is O(E*C*M + T*E) — no ``[T, E, C]`` tensor ever exists,
  which at DeepSeekMoE scale (E=64, T=16K) is the difference between ~2 MB
  of routing state and a multi-GB one-hot wall.
* ``"dense"`` — the original GShard one-hot einsum formulation
  ([T, E, C] dispatch/combine contractions); kept as the differential
  -testing oracle and for tiny shapes.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer_base import Layer
from ..mesh import get_mesh
from ..sharding_api import shard_tensor

__all__ = ["MoELayer", "NaiveGate", "SwitchGate", "GShardGate"]


def _ragged_moves(n_slots):
    """Gather-only dispatch/combine over a slot↔assignment inverse map.

    ``slot_src`` [n_slots+1] holds the token filling each capacity slot
    (sentinel = T → the zero pad row); ``slots_stack`` [K, T] holds each
    assignment's slot (sentinel = n_slots → the zero pad row). The two maps
    are inverses, so every VJP is itself a gather — no [*, M] row scatter
    ever runs (TPU scatters serialize; this was the ragged path's measured
    bottleneck). Integer operands take ``float0`` cotangents.
    """
    import jax
    import jax.numpy as jnp

    def _f0(x):
        return np.zeros(x.shape, jax.dtypes.float0)

    def _take0(arr, idx):
        pad = jnp.concatenate([arr, jnp.zeros((1, arr.shape[1]),
                                              arr.dtype)])
        return pad[jnp.minimum(idx, arr.shape[0])]

    @jax.custom_vjp
    def dispatch(xt, slot_src, slots_stack):
        return _take0(xt, slot_src[:n_slots])

    def dispatch_fwd(xt, slot_src, slots_stack):
        return dispatch(xt, slot_src, slots_stack), \
            (slots_stack, slot_src, xt.shape[0])

    def dispatch_bwd(res, g):
        slots_stack, slot_src, T = res
        dxt = _take0(g, slots_stack[0])
        for k in range(1, slots_stack.shape[0]):
            dxt = dxt + _take0(g, slots_stack[k])
        return dxt, _f0(slot_src), _f0(slots_stack)

    dispatch.defvjp(dispatch_fwd, dispatch_bwd)

    @jax.custom_vjp
    def combine(flat, w_stack, slot_src, slots_stack, w_slot):
        # out[t] = Σ_k flat[slots[k, t]] * w[k, t]
        out = _take0(flat, slots_stack[0]) * w_stack[0][:, None]
        for k in range(1, slots_stack.shape[0]):
            out = out + _take0(flat, slots_stack[k]) * w_stack[k][:, None]
        return out

    def combine_fwd(flat, w_stack, slot_src, slots_stack, w_slot):
        return combine(flat, w_stack, slot_src, slots_stack, w_slot), \
            (flat, w_stack, slot_src, slots_stack, w_slot)

    def combine_bwd(res, g):
        flat, w_stack, slot_src, slots_stack, w_slot = res
        # d_flat[s] = g[token(s)] * w(s): the INVERSE map makes this a
        # gather of g rows, not a scatter of weighted rows
        d_flat = _take0(g, slot_src[:n_slots]) * w_slot[:n_slots, None]
        # d_w[k, t] = <flat[slots[k, t]], g[t]>
        d_w = jnp.stack([
            (_take0(flat, slots_stack[k]) * g).sum(-1)
            for k in range(slots_stack.shape[0])])
        return d_flat, d_w.astype(w_stack.dtype), _f0(slot_src), \
            _f0(slots_stack), jnp.zeros_like(w_slot)

    combine.defvjp(combine_fwd, combine_bwd)
    return dispatch, combine


class _GateBase(Layer):
    top_k = 2

    def __init__(self, d_model, num_experts, top_k=None):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        if top_k is not None:
            self.top_k = top_k
        self.weight = self.create_parameter(
            shape=[d_model, num_experts],
            default_initializer=I.XavierUniform())


class NaiveGate(_GateBase):
    """top-k softmax gate, no auxiliary loss (reference: gate/naive_gate.py)."""
    aux = "none"


class SwitchGate(_GateBase):
    """top-1 gate with the Switch-Transformer load-balance loss
    (reference: gate/switch_gate.py)."""
    top_k = 1
    aux = "switch"


class GShardGate(_GateBase):
    """top-2 gate with GShard's mean(me * ce) * E^2 aux loss
    (reference: gate/gshard_gate.py)."""
    top_k = 2
    aux = "gshard"


class MoELayer(Layer):
    """Reference: moe_layer.py:261. Experts are a stacked SwiGLU-free MLP
    (w1 -> act -> w2) with weights [E, ...] sharded on the expert axis;
    ``forward`` sets ``self.l_aux`` to the gate's balance loss.
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 top_k=None, capacity_factor=1.25, activation="gelu",
                 dispatch_mode="ragged", mesh=None,
                 axis: Optional[str] = "ep", name=None):
        super().__init__()
        if dispatch_mode not in ("ragged", "dense"):
            raise ValueError(f"dispatch_mode {dispatch_mode!r} must be "
                             "'ragged' or 'dense'")
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.dispatch_mode = dispatch_mode
        self._activation = activation
        if isinstance(gate, str):
            cls = {"naive": NaiveGate, "switch": SwitchGate,
                   "gshard": GShardGate}[gate]
            gate = cls(d_model, num_experts, top_k=top_k)
        self.gate = gate
        std = 1.0 / math.sqrt(d_model)
        self.w1 = self.create_parameter(
            shape=[num_experts, d_model, d_hidden],
            default_initializer=I.Uniform(-std, std))
        self.b1 = self.create_parameter(shape=[num_experts, d_hidden],
                                        is_bias=True)
        self.w2 = self.create_parameter(
            shape=[num_experts, d_hidden, d_model],
            default_initializer=I.Uniform(-1.0 / math.sqrt(d_hidden),
                                          1.0 / math.sqrt(d_hidden)))
        self.b2 = self.create_parameter(shape=[num_experts, d_model],
                                        is_bias=True)
        self._mesh = mesh or get_mesh()
        if self._mesh is not None and axis in getattr(
                self._mesh, "axis_names", ()):
            ep = self._mesh.shape[axis]
            if num_experts % ep == 0:
                for w in (self.w1, self.b1, self.w2, self.b2):
                    shard_tensor(w, self._mesh, spec=P(
                        axis, *([None] * (len(w.shape) - 1))))
        self.l_aux = None

    def forward(self, x, token_mask=None):
        """x: [..., d_model] -> same shape; stores self.l_aux.

        ``token_mask`` (optional, broadcastable to x's leading dims,
        True = real token) excludes padding from routing: masked tokens
        are assigned a sentinel expert id, so they claim no capacity
        positions, no bincount share, and no aux-loss weight — the
        serving engine's inactive decode slots and padded prefill-chunk
        tails must not steal expert capacity from (or perturb the drop
        pattern of) real tokens."""
        import jax
        import jax.numpy as jnp

        E = self.num_experts
        K = self.gate.top_k
        cap_f = self.capacity_factor
        aux_kind = getattr(self.gate, "aux", "none")
        act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
               "silu": jax.nn.silu}[self._activation]

        ragged = self.dispatch_mode == "ragged"

        def f(xa, gw, w1, b1, w2, b2, *rest):
            lead = xa.shape[:-1]
            xt = xa.reshape(-1, xa.shape[-1])  # [T, M]
            T, M = xt.shape
            C = max(int(cap_f * T * K / E), 1)
            vm = None
            if rest:
                vm = jnp.broadcast_to(rest[0].astype(bool),
                                      lead).reshape(T)

            logits = xt @ gw  # [T, E]
            probs = jax.nn.softmax(logits, axis=-1)

            # top-k selection, vectorized but ORDER-IDENTICAL to the
            # sequential GShard argmax-and-mask walk: lax.top_k returns
            # descending picks with first-index tie-breaks (same winner
            # sequence). Per-expert running counts come from ONE stable
            # argsort of the pick-major expert ids: within a sorted
            # segment, position = index - segment start — measured ~2x
            # faster on chip than the [K*T, E] one-hot cumsum these
            # replaced (same positions, so capacity drops stay
            # bit-identical).
            if vm is None:
                me = probs.mean(axis=0)  # mean gate prob per expert
            else:
                n_real = jnp.maximum(vm.sum(), 1).astype(probs.dtype)
                me = (probs * vm[:, None].astype(probs.dtype)).sum(0) \
                    / n_real
            gate_k, idx_k = jax.lax.top_k(probs, K)  # [T, K] descending
            e_flat = jnp.swapaxes(idx_k, 0, 1).reshape(K * T)
            if vm is not None:
                # padding routes to sentinel expert E: sorts into its own
                # trailing segment, takes no positions/counts below
                e_flat = jnp.where(jnp.tile(vm, K), e_flat, E)
            order = jnp.argsort(e_flat, stable=True)
            e_sorted = e_flat[order]
            ar = jnp.arange(K * T, dtype=jnp.int32)
            boundary = jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), e_sorted[1:] != e_sorted[:-1]])
            seg_start = jax.lax.associative_scan(
                jnp.maximum, jnp.where(boundary, ar, 0))
            pos_flat = jnp.zeros((K * T,), jnp.int32).at[order].set(
                ar - seg_start)
            pos_km = pos_flat.reshape(K, T)
            counts = jnp.bincount(e_flat, length=E)  # sentinel E excluded
            if vm is None:
                ce_acc = counts.astype(probs.dtype) / T
                picks = [(idx_k[:, k], gate_k[:, k], pos_km[k],
                          pos_km[k] < C) for k in range(K)]
            else:
                ce_acc = counts.astype(probs.dtype) / n_real
                picks = [(idx_k[:, k], gate_k[:, k], pos_km[k],
                          (pos_km[k] < C) & vm) for k in range(K)]

            # renormalize gates over the KEPT assignments (dense path
            # normalized the combine tensor — same entries)
            denom = sum(gv * kp.astype(gv.dtype)
                        for _, gv, _, kp in picks)
            denom = jnp.maximum(denom, 1e-9)  # [T]

            if ragged:
                # ---- index routing (global_scatter/global_gather shape):
                # slot = e*C + position; dropped assignments point at the
                # sentinel pad row. Build the slot→token inverse map with
                # tiny int32 scatters (conflict-free: positions are unique
                # per expert), then every [*, M] move is a gather.
                tok = jnp.arange(T, dtype=jnp.int32)
                slot_src = jnp.full((E * C + 1,), T, jnp.int32)
                slots_list = []
                for idx, gv, pos_t, keep in picks:
                    slots = jnp.where(keep, idx * C + pos_t, E * C)
                    slot_src = slot_src.at[slots].set(tok)
                    slots_list.append(slots)
                slots_stack = jnp.stack(slots_list)  # [K, T]
                dispatch, combine = _ragged_moves(E * C)
                expert_in = dispatch(xt, slot_src,
                                     slots_stack).reshape(E, C, M)
            else:
                # ---- dense GShard one-hot contraction ([T, E, C] lives).
                # dispatch and combine share one per-pick [T,E]x[T,C]
                # outer product so the drop encoding exists exactly once
                dispatch = jnp.zeros((T, E, C), xt.dtype)
                combine = jnp.zeros((T, E, C), xt.dtype)
                for idx, gv, pos_t, keep in picks:
                    onehot = jax.nn.one_hot(idx, E, dtype=xt.dtype)
                    pos_oh = jax.nn.one_hot(
                        jnp.where(keep, pos_t, C), C + 1,
                        dtype=xt.dtype)[:, :C]
                    cell = onehot[:, :, None] * pos_oh[:, None, :]
                    dispatch = dispatch + cell
                    combine = combine + \
                        (gv / denom).astype(xt.dtype)[:, None, None] * cell
                expert_in = jnp.einsum("tec,tm->ecm", dispatch, xt)

            # grouped GEMM over stacked experts (ep-sharded on the mesh)
            h = act(jnp.einsum("ecm,emh->ech", expert_in, w1) +
                    b1[:, None, :])
            expert_out = jnp.einsum("ech,ehm->ecm", h, w2) + b2[:, None, :]

            if ragged:
                flat = expert_out.reshape(E * C, M)
                w_stack = jnp.stack([
                    (gv * kp.astype(gv.dtype) / denom).astype(xt.dtype)
                    for _, gv, _, kp in picks])  # [K, T]
                # per-slot combine weight (for the gather-only backward):
                # same tiny int32-scatter trick as slot_src
                w_slot = jnp.zeros((E * C + 1,), xt.dtype)
                for (idx, gv, pos_t, keep), wk in zip(picks, w_stack):
                    slots = jnp.where(keep, idx * C + pos_t, E * C)
                    w_slot = w_slot.at[slots].set(wk)
                out = combine(flat, w_stack, slot_src, slots_stack, w_slot)
            else:
                out = jnp.einsum("tec,ecm->tm", combine, expert_out)

            if aux_kind == "switch":
                aux = (me * ce_acc).sum() * E
            elif aux_kind == "gshard":
                aux = (me * (ce_acc / K)).sum() * E
            else:
                aux = jnp.zeros((), xt.dtype)
            return out.reshape(*lead, xa.shape[-1]), aux

        extra = () if token_mask is None else (token_mask,)
        out, aux = apply_op(f, x, self.gate.weight, self.w1, self.b1,
                            self.w2, self.b2, *extra, op_name="moe_layer")
        self.l_aux = aux
        return out
