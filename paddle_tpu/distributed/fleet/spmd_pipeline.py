"""SPMD (collective) pipeline parallelism: the whole 1F1B/interleave
schedule inside ONE compiled XLA program.

Why a second pipeline engine: ``fleet/pipeline.py``'s list scheduler moves
micro-batch activations with single-controller ``jax.device_put`` — legal
only across devices addressable by one process, so its pipeline cannot span
hosts. The reference spans nodes with per-rank send_v2/recv_v2 loops
(``fleet/meta_parallel/pp_utils/p2p_communication.py:298``,
``pipeline_parallel.py:117``). The TPU-native equivalent of those p2p ops is
``lax.ppermute`` over a ``pp`` mesh axis inside a compiled program: XLA
lowers every stage hop to an ICI/DCN collective-permute, so the same
program runs unmodified on a v5p pod where stages sit on different hosts
(multi-controller: every process executes the same jitted step).

Design (the "How to Scale Your Model" pipelining recipe, done natively):

- Stage bodies are HOMOGENEOUS (the transformer trunk): one ``body_fn``
  applied by every stage to its own parameter slice. Parameters are stacked
  ``[v, S, ...]`` (virtual chunk r, stage s ⇒ pipeline chunk ``c = r*S+s``,
  the Megatron round-robin placement) and sharded ``P(None, 'pp', ...)`` —
  each stage holds exactly its ``v`` chunks. Embedding/head stay OUTSIDE
  the pipelined region (replicated over pp, sharded over dp/mp), which is
  how production TPU pipelining divides labor.

- The schedule is a ``lax.scan`` over clock ticks. At tick ``t`` stage
  ``s`` decomposes ``u = t - s`` as ``u = g·vS + r·S + i`` (mixed radix):
  it runs virtual chunk ``r`` on micro-batch ``m = g·S + i`` iff
  ``u ≥ 0 and m < M``. Boundary activations rotate one stage per tick via
  a ``ppermute`` ring (stage S-1 wraps to stage 0 carrying the next
  virtual round — the circular/interleaved pipeline). Inactive ticks
  compute on zeros and are masked: that idle compute IS the bubble,
  ``(S-1)/(v·M + S-1)`` of the span — the same fraction the list
  scheduler measures for the interleaved schedule.

- Backward needs no scheduler: ``jax.grad`` through scan + ppermute
  generates the reverse pipeline (transpose of a permute is the reverse
  permute), and ``jax.checkpoint`` around the body gives 1F1B-grade
  memory: only boundary activations are saved per tick, chunk internals
  are rematerialized.

Boundaries are pytrees: ``body_fn`` may thread tuples/dicts of tensors
between stages (the reference's ``_p2p_helper`` handshakes arbitrary tensor
tuples — here the pytree structure is static so no meta handshake is
needed).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from paddle_tpu.core.autograd import apply_op, no_grad
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer
from ..mesh import get_mesh

__all__ = ["pipeline_spmd", "spmd_schedule_stats", "SpmdPipelineLayer",
           "SpmdPipelineParallel", "pipeline_spmd_hetero",
           "SpmdHeteroPipelineLayer"]


def _completion_ticks(S: int, v: int, M: int) -> np.ndarray:
    """Tick at which micro-batch m's LAST chunk (stage S-1, round v-1)
    executes: t_m = (S-1) + (m//S)·vS + (v-1)·S + (m%S)."""
    m = np.arange(M)
    return (S - 1) + (m // S) * v * S + (v - 1) * S + (m % S)


def spmd_schedule_stats(num_stages: int, num_virtual_stages: int,
                        n_micro: int) -> dict:
    """Analytic schedule accounting in forward-tick units (the compiled
    schedule is exact, so no simulation is needed; the backward pipeline
    autodiff generates mirrors it). Matches the list scheduler's keys."""
    S, v, M = num_stages, num_virtual_stages, n_micro
    span = int(_completion_ticks(S, v, M)[-1]) + 1
    busy = v * M  # ticks each stage actually computes
    return {
        "slots_span": span,
        "busy": {s: busy for s in range(S)},
        "bubble_fraction": round(1.0 - busy / span, 4) if span else 0.0,
        "n_micro": M,
        "n_chunks": S * v,
    }


def pipeline_spmd(body_fn: Callable, stacked_params, micro_inputs,
                  mesh=None, axis: str = "pp",
                  num_virtual_stages: int = 1, remat: bool = True):
    """Run the collective pipeline on raw jax pytrees.

    ``body_fn(chunk_params, x) -> y``: one pipeline chunk. ``x``/``y`` are
    pytrees of identical structure/shape/dtype (the ring carry).
    ``stacked_params``: pytree with leaves ``[v, S, ...]``.
    ``micro_inputs``: pytree with leaves ``[M, ...]`` (micro-batch leading).
    Returns the last chunk's outputs, leaves ``[M, ...]``, replicated over
    ``axis``. Differentiable; all stage hops are compiled ppermutes.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise RuntimeError(f"pipeline_spmd needs a mesh with axis {axis!r}")
    S = mesh.shape[axis]
    v = num_virtual_stages
    leaves = jax.tree_util.tree_leaves(micro_inputs)
    M = leaves[0].shape[0]
    for lf in jax.tree_util.tree_leaves(stacked_params):
        if lf.shape[:2] != (v, S):
            raise ValueError(
                f"stacked param leaf {lf.shape} must lead with "
                f"[v={v}, S={S}]")
    t_idx = _completion_ticks(S, v, M)
    span = int(t_idx[-1]) + 1
    body = jax.checkpoint(body_fn) if remat else body_fn

    from .utils import pvary_compat

    def _pvary(x):
        return pvary_compat(x, axis)

    def per_stage(params, xs):
        # params leaves [v, 1, ...] (stage slice); xs leaves [M, ...]
        params = jax.tree_util.tree_map(lambda a: jnp.squeeze(a, 1), params)
        s = jax.lax.axis_index(axis)
        vS = v * S
        perm = [(j, (j + 1) % S) for j in range(S)]

        def tick(carry, t):
            u = t - s
            g = u // vS
            rem = u % vS
            r = rem // S
            i = rem % S
            m = g * S + i
            active = (u >= 0) & (m < M)
            m_safe = jnp.clip(m, 0, M - 1)
            inject = active & (s == 0) & (r == 0)

            def pick(buf, ix):
                return jax.lax.dynamic_index_in_dim(buf, ix, 0,
                                                    keepdims=False)

            x_new = jax.tree_util.tree_map(
                lambda b: pick(b, m_safe), xs)
            x_in = jax.tree_util.tree_map(
                lambda new, c: jnp.where(
                    active,
                    jnp.where(inject, _pvary(new), c),
                    jnp.zeros_like(c)),
                x_new, carry)
            cp = jax.tree_util.tree_map(
                lambda a: pick(a, jnp.clip(r, 0, v - 1)), params)
            y = body(cp, x_in)
            # inactive stages computed on zeros: mask so garbage can never
            # reach an active consumer (and grads through the masked side
            # are exact zeros)
            y = jax.tree_util.tree_map(
                lambda a: jnp.where(active, a, jnp.zeros_like(a)), y)
            y_next = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, axis, perm), y)
            return y_next, y

        x0 = jax.tree_util.tree_map(
            lambda b: _pvary(jnp.zeros(b.shape[1:], b.dtype)), xs)
        _, ys = jax.lax.scan(tick, x0, jnp.arange(span))
        # micro m's final-chunk output was emitted on stage S-1 at tick
        # t_idx[m]; everywhere else the buffer holds zeros, so a psum over
        # the pp ring is a pure selection (no arithmetic mixing)
        is_last = (s == S - 1)
        sel = jnp.asarray(t_idx)

        def collect(buf):
            out = jnp.take(buf, sel, axis=0)
            out = jnp.where(is_last, out, jnp.zeros_like(out))
            return jax.lax.psum(out, axis)

        return jax.tree_util.tree_map(collect, ys)

    pspec = jax.tree_util.tree_map(
        lambda a: P(None, axis), stacked_params)
    xspec = jax.tree_util.tree_map(lambda a: P(), micro_inputs)
    ospec = jax.tree_util.tree_map(lambda a: P(), micro_inputs)
    from .utils import shard_map_compat
    return shard_map_compat(per_stage, mesh, (pspec, xspec), ospec,
                            axis_names={axis})(stacked_params, micro_inputs)


class SpmdPipelineLayer(Layer):
    """Homogeneous-trunk pipeline Layer over a ``pp`` mesh axis.

    ``block_factory()`` builds one trunk chunk (e.g. a run of transformer
    blocks); ``S * num_virtual_stages`` independent instances are built,
    their parameters stacked into ``[v, S, ...]`` Parameters sharded
    ``P(None, 'pp', ...)``. The forward takes micro-batched input
    ``[M, B, ...]`` and returns ``[M, B, ...]`` — every stage hop is a
    compiled ppermute, so the layer trains across hosts under a
    multi-controller mesh (the multi-host path the device_put engine in
    ``fleet/pipeline.py`` cannot take).

    Blocks must be stateless apart from parameters (no BN running stats):
    the chunk body runs under functional parameter swap.
    """

    def __init__(self, block_factory: Callable[[], Layer],
                 num_virtual_stages: int = 1, mesh=None, axis: str = "pp",
                 remat: bool = True, loss_fn: Optional[Callable] = None):
        super().__init__()
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.core.tensor import Parameter

        self._mesh = mesh or get_mesh()
        if self._mesh is None or axis not in self._mesh.axis_names:
            raise RuntimeError(
                f"SpmdPipelineLayer needs a mesh with axis {axis!r}")
        self.axis = axis
        self.num_stages = self._mesh.shape[axis]
        self.num_virtual_stages = num_virtual_stages
        self.num_chunks = self.num_stages * num_virtual_stages
        self.remat = remat
        self._loss_fn = loss_fn

        blocks = [block_factory() for _ in range(self.num_chunks)]
        template = blocks[0]
        names = [n for n, _ in template.named_parameters()]
        for b in blocks[1:]:
            got = [n for n, _ in b.named_parameters()]
            if got != names:
                raise ValueError(
                    "block_factory must build identical parameter "
                    f"structures (got {got} vs {names})")
        if any(b is not None for _, b in template.named_buffers()):
            raise ValueError(
                "SpmdPipelineLayer blocks must be stateless (no buffers/"
                "running stats); use the host-scheduled PipelineParallel "
                "for stateful stages")
        # template kept OUT of the sublayer registry: its (chunk-0 copy)
        # parameters must not appear next to the stacked ones
        self.__dict__["_template"] = template
        self._param_names = names
        S, v = self.num_stages, num_virtual_stages
        by_name = [dict(b.named_parameters()) for b in blocks]
        for name in names:
            # chunk c = r*S + s sits at index [r, s]
            arr = jnp.stack([by_name[c][name].data
                             for c in range(self.num_chunks)])
            arr = arr.reshape((v, S) + arr.shape[1:])
            p = Parameter(arr, trainable=not by_name[0][name].stop_gradient)
            p._sharding_spec = P(None, self.axis,
                                 *([None] * (arr.ndim - 2)))
            self.add_parameter(name.replace(".", "__"), p)

    def _stacked(self):
        return {n: getattr(self, n.replace(".", "__"))
                for n in self._param_names}

    def schedule_stats(self, n_micro: int) -> dict:
        return spmd_schedule_stats(self.num_stages, self.num_virtual_stages,
                                   n_micro)

    def forward(self, micro_x):
        """``micro_x``: Tensor ``[M, B, ...]`` (or pytree of such) ->
        same-structure ``[M, B, ...]`` outputs of the final chunk."""
        import jax
        template = self.__dict__["_template"]
        names = self._param_names
        stacked = self._stacked()
        mesh, axis, v, remat = (self._mesh, self.axis,
                                self.num_virtual_stages, self.remat)

        def f(xs, *param_arrays):
            params = dict(zip(names, param_arrays))

            def body_fn(chunk_params, x):
                from paddle_tpu.jit.functional import swap_state
                with no_grad(), swap_state(template, chunk_params,
                                           collect_buffers=False):
                    y = template(Tensor(x, stop_gradient=True))
                return y.data if isinstance(y, Tensor) else \
                    jax.tree_util.tree_map(
                        lambda t: t.data if isinstance(t, Tensor) else t, y)

            return pipeline_spmd(body_fn, params, xs, mesh=mesh, axis=axis,
                                 num_virtual_stages=v, remat=remat)

        return apply_op(f, micro_x, *[stacked[n] for n in names],
                        op_name="pipeline_spmd")


class SpmdPipelineParallel(Layer):
    """``train_batch`` engine over an :class:`SpmdPipelineLayer` — the
    multi-host counterpart of :class:`PipelineParallel` (same contract:
    reference ``pipeline_parallel.py:228 train_batch``). The schedule lives
    inside the compiled program, so ``last_schedule_stats`` is the exact
    analytic accounting of that program rather than a simulation."""

    def __init__(self, layers: SpmdPipelineLayer,
                 accumulate_steps: Optional[int] = None):
        super().__init__()
        self._layers = layers
        self.accumulate_steps = accumulate_steps or layers.num_stages
        self._loss_fn = layers._loss_fn
        self.last_schedule_stats: dict = {}

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def forward(self, micro_x):
        return self._layers(micro_x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        from paddle_tpu import ops

        inputs, labels = data
        M = self.accumulate_steps
        B = inputs.shape[0]
        if B % M:
            raise ValueError(
                f"batch {B} not divisible by accumulate_steps {M}")
        micro_x = ops.reshape(inputs, [M, B // M] + list(inputs.shape[1:]))
        out = self._layers(micro_x)  # [M, b, ...]
        merged = ops.reshape(out, [B] + list(out.shape[2:]))
        loss = self._loss_fn(merged, labels)
        if scaler is not None:
            scaler.scale(loss).backward()
            scaler.step(optimizer)
            scaler.update()
        else:
            loss.backward()
            optimizer.step()
        optimizer.clear_grad(set_to_zero=False)
        if lr_scheduler is not None:
            lr_scheduler.step()
        self.last_schedule_stats = self._layers.schedule_stats(M)
        return loss

    @no_grad()
    def eval_batch(self, data, compute_loss=True):
        from paddle_tpu import ops
        inputs, labels = data
        M = self.accumulate_steps
        B = inputs.shape[0]
        if B % M:
            raise ValueError(
                f"batch {B} not divisible by accumulate_steps {M}")
        micro_x = ops.reshape(inputs, [M, B // M] + list(inputs.shape[1:]))
        out = self._layers(micro_x)
        merged = ops.reshape(out, [B] + list(out.shape[2:]))
        if compute_loss and self._loss_fn is not None:
            return self._loss_fn(merged, labels)
        return merged


# ===================== heterogeneous + tied-weight stages ====================
# The homogeneous engine above stacks ONE body's params [v, S, ...]. The
# reference additionally pipelines arbitrary per-stage bodies and ties
# weights across stages with a grad allreduce (SharedLayerDesc,
# fleet/meta_parallel/parallel_layers/pp_layers.py:77; segmentation :209).
# TPU-native equivalents:
#
#   * HETEROGENEOUS chunks — each chunk's param pytree is flattened and
#     concatenated into ONE vector, padded to the longest chunk, stacked
#     [v, S, Lmax] and sharded P(None, 'pp'): every stage holds exactly
#     its own chunks' weights (the "padded stacked param superset"). The
#     tick body dispatches over the chunk index with ``lax.switch`` —
#     each branch statically unflattens ITS chunk's slice (shapes are
#     compile-time metadata), so heterogeneity costs program size, not
#     memory or transfers. Boundary activations must still share one
#     pytree structure (the ring carry is a fixed-shape collective).
#
#   * TIED weights — ``shared_params`` ride into every stage REPLICATED
#     over pp; any chunk may consume them (chunk 0's embedding, chunk
#     C-1's head). The transpose of a replicated shard_map input is a
#     psum over the axis: XLA inserts the exact grad allreduce
#     SharedLayerDesc implements by hand.


def pipeline_spmd_hetero(chunk_bodies, chunk_params, micro_inputs,
                         mesh=None, axis: str = "pp",
                         num_virtual_stages: int = 1,
                         shared_params=None, remat: bool = True):
    """Heterogeneous collective pipeline on raw jax pytrees.

    ``chunk_bodies``: list of ``v*S`` callables; chunk ``c`` computes
    ``chunk_bodies[c](params_c, shared_params, x) -> y`` where ``x``/``y``
    share one pytree structure across ALL chunks (the ring carry).
    ``chunk_params``: list of ``v*S`` per-chunk pytrees (shapes may differ
    arbitrarily between chunks). ``shared_params``: optional pytree
    visible to every chunk (tied weights) — grads sum over the pp axis.
    Returns the last chunk's outputs ``[M, ...]``; differentiable.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise RuntimeError(
            f"pipeline_spmd_hetero needs a mesh with axis {axis!r}")
    S = mesh.shape[axis]
    v = num_virtual_stages
    C = v * S
    if len(chunk_bodies) != C or len(chunk_params) != C:
        raise ValueError(
            f"need {C} chunk bodies/params (S={S} x v={v}); got "
            f"{len(chunk_bodies)}/{len(chunk_params)}")

    # flatten each chunk to one vector; remember the static recipe
    treedefs, shapes_list, sizes, dtype = [], [], [], None
    flats = []
    for c, p in enumerate(chunk_params):
        leaves, td = jax.tree_util.tree_flatten(p)
        for lf in leaves:
            if dtype is None:
                dtype = lf.dtype
            elif lf.dtype != dtype:
                raise ValueError(
                    "heterogeneous pipeline params must share one dtype "
                    f"(chunk {c} mixes {lf.dtype} with {dtype})")
        treedefs.append(td)
        shapes_list.append([lf.shape for lf in leaves])
        flat = jnp.concatenate([lf.reshape(-1) for lf in leaves]) \
            if leaves else jnp.zeros((0,), dtype or jnp.float32)
        sizes.append(flat.size)
        flats.append(flat)
    Lmax = max(max(sizes), 1)
    padded = jnp.stack([jnp.pad(f, (0, Lmax - f.size)) for f in flats])
    padded = padded.reshape(v, S, Lmax)
    if shared_params is None:
        shared_params = {}

    def unflatten(c, vec):
        out, off = [], 0
        for shp in shapes_list[c]:
            n = int(np.prod(shp)) if shp else 1
            out.append(vec[off:off + n].reshape(shp))
            off += n
        return jax.tree_util.tree_unflatten(treedefs[c], out)

    def make_branch(c):
        body = chunk_bodies[c]

        def branch(vec, shared, x):
            return body(unflatten(c, vec), shared, x)
        return branch

    branches = [make_branch(c) for c in range(C)]
    return _hetero_schedule(branches, padded, shared_params, micro_inputs,
                            mesh, axis, v, remat)


def _hetero_schedule(branches, padded, shared_params, micro_inputs,
                     mesh, axis, num_virtual_stages, remat=True):
    """Schedule core over the ALREADY padded-stacked [v, S, Lmax] param
    array: ``branches[c](vec, shared, x)`` unflattens its own chunk's
    slice via static metadata. Split out so SpmdHeteroPipelineLayer can
    feed its stored stacked Parameter directly — routing a per-step
    slice/re-pad/re-stack round trip over the whole trunk through the
    public list-of-pytrees API wasted HBM bandwidth every step."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    v = num_virtual_stages
    S = mesh.shape[axis]
    C = v * S
    if remat:
        branches = [jax.checkpoint(b) for b in branches]
    if shared_params is None:
        shared_params = {}

    leaves = jax.tree_util.tree_leaves(micro_inputs)
    M = leaves[0].shape[0]
    t_idx = _completion_ticks(S, v, M)
    span = int(t_idx[-1]) + 1

    from .utils import pvary_compat

    def _pvary(x):
        return pvary_compat(x, axis)

    def per_stage(stage_vecs, shared, xs):
        # stage_vecs [v, 1, Lmax] -> [v, Lmax]
        stage_vecs = jnp.squeeze(stage_vecs, 1)
        # pvary the shared (tied) params HERE, uniformly on every device:
        # left implicit, the cast happens inside whichever switch branch
        # consumes them — a collective only SOME pp ranks execute
        # (deadlock). Outside the switch, every rank runs it in lockstep.
        shared = jax.tree_util.tree_map(_pvary, shared)
        s = jax.lax.axis_index(axis)
        vS = v * S
        perm = [(j, (j + 1) % S) for j in range(S)]

        def tick(carry, t):
            u = t - s
            g = u // vS
            rem = u % vS
            r = rem // S
            i = rem % S
            m = g * S + i
            active = (u >= 0) & (m < M)
            m_safe = jnp.clip(m, 0, M - 1)
            inject = active & (s == 0) & (r == 0)

            def pick(buf, ix):
                return jax.lax.dynamic_index_in_dim(buf, ix, 0,
                                                    keepdims=False)

            x_new = jax.tree_util.tree_map(lambda b: pick(b, m_safe), xs)
            x_in = jax.tree_util.tree_map(
                lambda new, cr: jnp.where(
                    active,
                    jnp.where(inject, _pvary(new), cr),
                    jnp.zeros_like(cr)),
                x_new, carry)
            r_safe = jnp.clip(r, 0, v - 1)
            vec = pick(stage_vecs, r_safe)
            # this stage's chunk at round r is c = r*S + s: every branch
            # is compiled, ONE executes per tick (program size buys
            # heterogeneity; weights stay stage-local)
            cidx = jnp.clip(r_safe * S + s, 0, C - 1)
            y = jax.lax.switch(cidx, branches, vec, shared, x_in)
            y = jax.tree_util.tree_map(
                lambda a: jnp.where(active, a, jnp.zeros_like(a)), y)
            y_next = jax.tree_util.tree_map(
                lambda a: jax.lax.ppermute(a, axis, perm), y)
            return y_next, y

        x0 = jax.tree_util.tree_map(
            lambda b: _pvary(jnp.zeros(b.shape[1:], b.dtype)), xs)
        _, ys = jax.lax.scan(tick, x0, jnp.arange(span))
        is_last = (s == S - 1)
        sel = jnp.asarray(t_idx)

        def collect(buf):
            out = jnp.take(buf, sel, axis=0)
            out = jnp.where(is_last, out, jnp.zeros_like(out))
            return jax.lax.psum(out, axis)

        return jax.tree_util.tree_map(collect, ys)

    xspec = jax.tree_util.tree_map(lambda a: P(), micro_inputs)
    sspec = jax.tree_util.tree_map(lambda a: P(), shared_params)
    # FULL-manual over every mesh axis (unlike the homogeneous engine's
    # partial-manual {axis}): ``lax.switch`` branch selection varies per
    # pp rank, and under partial-manual GSPMD would auto-partition branch
    # INTERNALS over the other axes — inserting per-branch collectives
    # whose schedules then differ across pp ranks (deadlock). Full-manual
    # keeps branch bodies collective-free; the pipeline is replicated
    # over non-pp axes. Blocks whose forward builds fresh scan carries
    # (RNNs) must vma-match them to their inputs — see
    # ``fleet.utils.match_vma`` (nn.RNN does this natively).
    from .utils import shard_map_compat
    return shard_map_compat(
        per_stage, mesh, (P(None, axis, None), sspec, xspec), xspec,
        axis_names=set(mesh.axis_names))(padded, shared_params,
                                         micro_inputs)


class SpmdHeteroPipelineLayer(Layer):
    """Heterogeneous-trunk pipeline Layer: per-chunk bodies + optional
    tied (shared) sublayer, over a ``pp`` mesh axis.

    ``block_factories``: list of ``S * num_virtual_stages`` callables,
    each building that chunk's Layer (structures may differ arbitrarily;
    chunk boundaries must exchange one fixed pytree shape). The chunks'
    parameters live in ONE stacked-padded Parameter ``[v, S, Lmax]``
    sharded ``P(None, 'pp')`` — each stage stores only its own chunks.

    ``shared_factory`` builds a Layer replicated over pp whose forward
    any chunk may call: chunk bodies receive ``(x, shared)`` when their
    forward takes two arguments, ``(x)`` otherwise. Its gradient is the
    SUM of every chunk's contribution (psum over pp — the
    SharedLayerDesc tied-weight semantics, pp_layers.py:77)."""

    def __init__(self, block_factories, num_virtual_stages: int = 1,
                 mesh=None, axis: str = "pp", remat: bool = True,
                 loss_fn: Optional[Callable] = None, shared_factory=None):
        super().__init__()
        import inspect

        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.core.tensor import Parameter

        self._mesh = mesh or get_mesh()
        if self._mesh is None or axis not in self._mesh.axis_names:
            raise RuntimeError(
                f"SpmdHeteroPipelineLayer needs a mesh with axis {axis!r}")
        self.axis = axis
        self.num_stages = self._mesh.shape[axis]
        self.num_virtual_stages = num_virtual_stages
        self.num_chunks = self.num_stages * num_virtual_stages
        self.remat = remat
        self._loss_fn = loss_fn
        if len(block_factories) != self.num_chunks:
            raise ValueError(
                f"need {self.num_chunks} block factories "
                f"(S={self.num_stages} x v={num_virtual_stages}); got "
                f"{len(block_factories)}")

        blocks = [f() for f in block_factories]
        for c, b in enumerate(blocks):
            if any(buf is not None for _, buf in b.named_buffers()):
                raise ValueError(
                    f"chunk {c} has buffers/running stats; hetero spmd "
                    "chunks must be stateless apart from parameters")
        self.__dict__["_blocks"] = blocks

        def wants_shared(b):
            # only REQUIRED positional params opt a block into receiving
            # the shared layer — forward(self, x, mask=None) keeps its
            # default, forward(self, x, shared) gets the tied sublayer
            sig = inspect.signature(b.forward)
            required = [p for p in sig.parameters.values()
                        if p.default is inspect.Parameter.empty
                        and p.kind in (p.POSITIONAL_ONLY,
                                       p.POSITIONAL_OR_KEYWORD)]
            return len(required) >= 2
        self._wants_shared = [wants_shared(b) for b in blocks]
        self._names = [[n for n, _ in b.named_parameters()]
                       for b in blocks]
        self._shapes = [[tuple(p.shape) for _, p in b.named_parameters()]
                        for b in blocks]
        sizes = [int(sum(np.prod(s) or 1 for s in shp)) or 0
                 for shp in self._shapes]
        self._sizes = sizes
        Lmax = max(max(sizes), 1)
        v, S = num_virtual_stages, self.num_stages
        flats = []
        dtype = None
        for c, b in enumerate(blocks):
            ps = [p.data for _, p in b.named_parameters()]
            for p in ps:
                if dtype is None:
                    dtype = p.dtype
                elif p.dtype != dtype:
                    # same contract the function API enforces — a silent
                    # concatenate would promote everything to the widest
                    # dtype (wrong memory footprint, no error)
                    raise ValueError(
                        "hetero pipeline blocks must share one param "
                        f"dtype (chunk {c} mixes {p.dtype} with {dtype})")
            flat = jnp.concatenate([p.reshape(-1) for p in ps]) if ps \
                else jnp.zeros((0,), dtype or jnp.float32)
            flats.append(jnp.pad(flat, (0, Lmax - flat.size)))
        arr = jnp.stack(flats).reshape(v, S, Lmax)
        trainable = any(not p.stop_gradient
                        for b in blocks for p in b.parameters())
        p = Parameter(arr, trainable=trainable)
        p._sharding_spec = P(None, self.axis, None)
        self.add_parameter("trunk_flat", p)
        if shared_factory is not None:
            self.shared = shared_factory()
        else:
            self.shared = None

    def schedule_stats(self, n_micro: int) -> dict:
        return spmd_schedule_stats(self.num_stages,
                                   self.num_virtual_stages, n_micro)

    def chunk_state_dict(self, c: int):
        """Chunk ``c``'s parameters as a plain name->numpy dict (unpadded,
        unflattened) — the serve-elsewhere export path."""
        vec = np.asarray(self.trunk_flat.numpy()).reshape(
            self.num_chunks, -1)[c]
        out, off = {}, 0
        for name, shp in zip(self._names[c], self._shapes[c]):
            n = int(np.prod(shp)) if shp else 1
            out[name] = vec[off:off + n].reshape(shp)
            off += n
        return out

    def forward(self, micro_x):
        import jax
        from paddle_tpu.jit.functional import swap_state

        blocks = self.__dict__["_blocks"]
        wants = self._wants_shared
        mesh, axis = self._mesh, self.axis
        v, remat = self.num_virtual_stages, self.remat
        shared = self.shared
        shared_named = dict(shared.named_parameters()) \
            if shared is not None else {}
        shared_keys = sorted(shared_named)

        def make_body(c):
            block = blocks[c]

            def body(params_c, shared_p, x):
                with no_grad(), swap_state(block, params_c,
                                           collect_buffers=False):
                    if wants[c] and shared is not None:
                        with swap_state(shared, shared_p,
                                        collect_buffers=False):
                            y = block(Tensor(x, stop_gradient=True),
                                      shared)
                    else:
                        y = block(Tensor(x, stop_gradient=True))
                return y.data if isinstance(y, Tensor) else \
                    jax.tree_util.tree_map(
                        lambda t: t.data if isinstance(t, Tensor) else t,
                        y)
            return body

        bodies = [make_body(c) for c in range(self.num_chunks)]
        shapes, nm = self._shapes, self._names
        C = self.num_chunks

        def f(xs, flat, *shared_leaves):
            shared_p = dict(zip(shared_keys, shared_leaves))

            def make_branch(c):
                body = bodies[c]

                def branch(vec, shared, x):
                    # unflatten THIS chunk's slice of the stacked padded
                    # param (static recipe); the stacked array feeds the
                    # schedule directly — no per-step re-pad/re-stack
                    out, off = {}, 0
                    for name, shp in zip(nm[c], shapes[c]):
                        n = int(np.prod(shp)) if shp else 1
                        out[name] = vec[off:off + n].reshape(shp)
                        off += n
                    return body(out, shared, x)
                return branch

            return _hetero_schedule(
                [make_branch(c) for c in range(C)], flat, shared_p, xs,
                mesh, axis, v, remat)

        return apply_op(f, micro_x, self.trunk_flat,
                        *[shared_named[k] for k in shared_keys],
                        op_name="pipeline_spmd_hetero")
