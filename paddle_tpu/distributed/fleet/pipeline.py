"""Pipeline parallelism: PipelineLayer + chunk-granular 1F1B schedule with
virtual-pipeline interleave.

Parity with the reference's PP stack
(``fleet/meta_parallel/parallel_layers/pp_layers.py``: ``LayerDesc:57``,
``SharedLayerDesc:77``, ``PipelineLayer:209`` segmenting a layer list into
stages — including ``num_virtual_pipeline_stages``; and
``fleet/meta_parallel/pipeline_parallel.py``:
``forward_backward_pipeline:117`` 1F1B, ``train_batch:228``,
``PipelineParallelWithInterleave:461`` virtual-pipeline interleave).

TPU-native redesign (SURVEY.md §7: "PP stays host-orchestrated — the one
piece of FleetExecutor worth rebuilding"): each stage's parameters live on
that stage's devices; the schedule issues per-chunk forward/backward
programs from the single controller and moves micro-batch activations
between stages with ``jax.device_put`` (compiling to ICI transfers — the
send_v2/recv_v2 of the reference's ``_p2p_helper``). Because jax dispatch is
async, issuing work in schedule order overlaps stage compute the way the
reference's NCCL-stream schedule does, while the scheduler bounds in-flight
activations exactly like 1F1B.

Interleave: with ``num_virtual_pipeline_stages = v`` each physical stage
holds ``v`` model chunks assigned round-robin (chunk c lives on stage
``c % S`` — the reference/Megatron placement), and scheduling happens at
chunk granularity. The warmup ramp then costs chunk-units of ``1/v`` of a
stage's work, shrinking the pipeline-fill bubble by ~``v`` — the
interleave's entire point. The scheduler is a deterministic list scheduler:
every slot, each free stage takes its oldest ready unit, preferring
backward (classic 1F1B memory policy); it also records per-stage busy/idle
slots, exposed as ``last_schedule_stats`` so the bubble is *measured*, not
asserted.

``recompute_interval = k`` wraps every run of ``k`` consecutive layers
inside a chunk in activation recompute (``fleet.utils.recompute`` — the
tape-level ``jax.checkpoint``), trading one extra forward for dropping
intra-chunk residuals; only chunk-boundary activations stay live (the
reference's ``_recompute_interval`` semantics in pp_layers.py).
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from paddle_tpu.core.autograd import no_grad
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer
from ..mesh import get_mesh

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel"]


def _tree_map(fn, x):
    """Map ``fn`` over Tensor leaves of a (possibly nested) tuple/list
    activation structure — the reference's ``_p2p_helper`` handshakes
    arbitrary tensor tuples between stages (p2p_communication.py:298)."""
    if isinstance(x, (tuple, list)):
        return type(x)(_tree_map(fn, t) for t in x)
    return fn(x)


def _tree_leaves(x) -> List:
    if isinstance(x, (tuple, list)):
        out = []
        for t in x:
            out.extend(_tree_leaves(t))
        return out
    return [x]


def _call_layer(layer, x):
    """Reference PipelineLayer forward convention: tuple activations
    unpack as positional args; a single tensor passes directly."""
    return layer(*x) if isinstance(x, (tuple, list)) else layer(x)


class LayerDesc:
    """Lazy layer constructor (reference: pp_layers.py:57) so stages only
    materialize where placed."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self, registry=None) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied-weight layer (reference: pp_layers.py:77) — e.g. embedding
    shared between the first and last stage. All instances share the same
    Parameter objects; the backward accumulates into the shared leaves
    automatically (same tape leaf), replacing the reference's explicit
    allreduce over the shared-weight group."""

    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func

    def build(self, registry=None) -> Layer:
        # the registry is scoped to one PipelineLayer build — two models
        # built in the same process must never silently share weights
        if registry is None:
            registry = {}
        if self.key not in registry:
            registry[self.key] = super().build(registry)
        return registry[self.key]


class _RecomputeGroup(Layer):
    """Wraps a run of existing layers (sharing their Parameter objects) so
    ``fleet.utils.recompute`` threads the parameters through the
    rematerialized region."""

    def __init__(self, layers):
        super().__init__()
        from paddle_tpu.nn.containers import LayerList
        self.seq = LayerList(layers)

    def forward(self, *xs):
        x = xs if len(xs) > 1 else xs[0]
        for l in self.seq:
            x = _call_layer(l, x)
        return x


class PipelineLayer(Layer):
    """Segment a layer sequence into pipeline stages
    (reference: pp_layers.py:209).

    ``layers`` is a list of Layers / LayerDescs / callables. Segmentation is
    uniform by count (reference's default "uniform" seg_method) over
    ``num_stages * num_virtual_pipeline_stages`` chunks; chunk ``c`` is
    placed on physical stage ``c % num_stages`` (round-robin, the
    Megatron/reference interleave placement). Each chunk's parameters are
    committed to its stage's devices.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None, topology=None,
                 seg_method: str = "uniform", recompute_interval: int = 0,
                 num_virtual_pipeline_stages: int = 1,
                 mesh=None, devices: Optional[List] = None):
        super().__init__()
        import jax

        self._mesh = mesh or get_mesh()
        if devices is not None:
            self._stage_devices = devices
        elif self._mesh is not None and "pp" in self._mesh.axis_names:
            pp = self._mesh.shape["pp"]
            axes = self._mesh.axis_names
            arr = np.asarray(self._mesh.devices)
            pp_idx = axes.index("pp")
            self._stage_devices = [
                np.take(arr, s, axis=pp_idx).flatten().tolist()
                for s in range(pp)]
        else:
            devs = jax.devices()
            n = num_stages or len(devs)
            self._stage_devices = [[devs[i * len(devs) // n]]
                                   for i in range(n)]
        self.num_stages = num_stages or len(self._stage_devices)
        if len(self._stage_devices) != self.num_stages:
            # re-chunk device list into num_stages groups
            flat = [d for g in self._stage_devices for d in g]
            per = max(len(flat) // self.num_stages, 1)
            self._stage_devices = [flat[i * per:(i + 1) * per]
                                   for i in range(self.num_stages)]
        self._loss_fn = loss_fn
        if num_virtual_pipeline_stages < 1:
            raise ValueError("num_virtual_pipeline_stages must be >= 1")
        self.num_virtual_stages = num_virtual_pipeline_stages
        self.num_chunks = self.num_stages * self.num_virtual_stages
        self.recompute_interval = recompute_interval

        # materialize layers and segment uniformly over chunks
        built: List[Layer] = []
        shared_registry: dict = {}
        for item in layers:
            if isinstance(item, LayerDesc):
                built.append(item.build(shared_registry))
            elif isinstance(item, Layer):
                built.append(item)
            else:
                raise TypeError(f"unsupported pipeline item {item!r}")
        if len(built) < self.num_chunks:
            raise ValueError(
                f"{len(built)} layers cannot fill {self.num_chunks} chunks "
                f"({self.num_stages} stages x {self.num_virtual_stages} "
                "virtual)")
        bounds = self._segment(built, self.num_chunks, seg_method)
        self._chunk_layers: List[List[Layer]] = []
        from paddle_tpu.nn.containers import LayerList
        all_list = LayerList()
        for c in range(self.num_chunks):
            seg = built[bounds[c]:bounds[c + 1]]
            self._chunk_layers.append(seg)
            for l in seg:
                all_list.append(l)
        self.layers = all_list
        # recompute groups are Layer wrappers (fleet.utils.recompute only
        # threads parameters through Layers/bound methods, not closures);
        # kept OUT of the sublayer registry so parameters() stays exact
        if recompute_interval > 0:
            k = recompute_interval
            groups = []
            for seg in self._chunk_layers:
                groups.append([_RecomputeGroup(seg[i:i + k])
                               for i in range(0, len(seg), k)])
            self.__dict__["_recompute_groups"] = groups
        self._place_params()

    @staticmethod
    def _segment(built: List[Layer], n_stages: int,
                 method: str) -> List[int]:
        """Chunk boundaries over the built layer list.

        ``"uniform"``       — equal layer counts (reference default).
        ``"layer:REGEX"``   — layers whose class name matches REGEX
                              (case-insensitive search) weigh 1, others 0;
                              each chunk gets an equal share of matches,
                              boundaries fall after each share (reference
                              SegmentLayers.do_segment, pp_layers.py:112).
        ``"uniform_params"`` — parameter-count-weighted balance: chunk
                              boundaries minimize the spread of summed
                              parameter counts (greenfield: unbalanced
                              stacks — embedding-heavy stage 0 — otherwise
                              eat the bubble the interleave removed).
        """
        n_layers = len(built)
        if method == "uniform":
            base, rem = divmod(n_layers, n_stages)
            bounds = [0]
            for s in range(n_stages):
                bounds.append(bounds[-1] + base + (1 if s < rem else 0))
            return bounds
        if method.startswith("layer:"):
            import re
            pat = re.compile(method.split(":", 1)[1], re.IGNORECASE)
            weights = [1 if pat.search(type(l).__name__) else 0
                       for l in built]
            total = sum(weights)
            if total == 0:
                raise ValueError(
                    f"seg_method {method!r} matched no layer "
                    f"({sorted({type(l).__name__ for l in built})})")
            if total % n_stages:
                raise ValueError(
                    f"{total} layers matching {method!r} cannot split "
                    f"evenly into {n_stages} chunks")
            share = total // n_stages
            bounds, acc = [0], 0
            for idx, wgt in enumerate(weights):
                acc += wgt
                if acc == share and len(bounds) < n_stages:
                    bounds.append(idx + 1)
                    acc = 0
            bounds.append(n_layers)
            return bounds
        if method == "uniform_params":
            # weight each layer by its parameter count (min 1 so
            # parameter-free activations still advance the cursor), then
            # cut at the ideal cumulative fractions
            weights = [max(sum(int(np.prod(p.shape))
                               for p in l.parameters()), 1)
                       for l in built]
            csum = np.cumsum(weights, dtype=np.float64)
            total = float(csum[-1])
            bounds = [0]
            for j in range(1, n_stages):
                pos = int(np.searchsorted(csum, total * j / n_stages)) + 1
                lo = bounds[-1] + 1              # every chunk >= 1 layer
                hi = n_layers - (n_stages - j)   # leave room for the rest
                bounds.append(min(max(pos, lo), hi))
            bounds.append(n_layers)
            return bounds
        raise NotImplementedError(
            f"seg_method {method!r}; use 'uniform', 'layer:REGEX', or "
            "'uniform_params'")

    # chunk c lives on stage c % S (round-robin interleave placement)
    def chunk_stage(self, c: int) -> int:
        return c % self.num_stages

    def chunk_device(self, c: int):
        return self._stage_devices[self.chunk_stage(c)][0]

    def _place_params(self):
        """Commit each chunk's params to its stage's first device."""
        import jax
        for c, seg in enumerate(self._chunk_layers):
            dev = self.chunk_device(c)
            for layer in seg:
                for p in layer.parameters():
                    p._data = jax.device_put(p.data, dev)
                for b in layer.buffers():
                    if b is not None:
                        b._data = jax.device_put(b.data, dev)

    def stage_device(self, s: int):
        return self._stage_devices[s][0]

    # --- legacy single-virtual-stage accessors (v=1: chunk == stage) ----
    @property
    def _stage_layers(self):
        if self.num_virtual_stages != 1:
            raise AttributeError(
                "_stage_layers is undefined under interleave; use "
                "_chunk_layers")
        return self._chunk_layers

    def stage_forward(self, s: int, x):
        return self.chunk_forward(s, x)

    def chunk_forward(self, c: int, x):
        """Run chunk ``c`` on input ``x``, honoring recompute_interval:
        every run of k consecutive layers executes under activation
        recompute, so only the run boundaries stay live on the tape."""
        if self.recompute_interval <= 0 or not self.training:
            for layer in self._chunk_layers[c]:
                x = _call_layer(layer, x)
            return x
        from .utils import recompute
        for group in self.__dict__["_recompute_groups"][c]:
            x = recompute(group, *x) if isinstance(x, (tuple, list)) \
                else recompute(group, x)
        return x

    def forward(self, x):
        """Non-pipelined sequential run (debug/eval parity path)."""
        import jax
        for c in range(self.num_chunks):
            x = _tree_map(
                lambda t: Tensor(jax.device_put(t.data,
                                                self.chunk_device(c)),
                                 stop_gradient=t.stop_gradient)
                if isinstance(t, Tensor) else t, x)
            x = self.chunk_forward(c, x)
        return x


class PipelineParallel(Layer):
    """Chunk-granular 1F1B micro-batch engine
    (reference: pipeline_parallel.py:117 ``forward_backward_pipeline``,
    :461 ``PipelineParallelWithInterleave``).

    ``train_batch(data, optimizer)`` splits the batch into micro-batches,
    runs the 1F1B list schedule over (micro, chunk) units, accumulates
    gradients, steps the optimizer, and returns the mean loss — the
    reference's ``train_batch:228`` contract. After each call,
    ``last_schedule_stats`` holds the measured schedule: per-stage busy and
    idle slots, the bubble fraction, and the peak number of in-flight
    activation sets.
    """

    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None,
                 accumulate_steps: Optional[int] = None):
        super().__init__()
        self._layers = layers
        self.accumulate_steps = accumulate_steps or layers.num_stages
        self._loss_fn = layers._loss_fn
        self.last_schedule_stats: dict = {}
        self._schedule_cache: dict = {}

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def forward(self, x):
        return self._layers(x)

    # ------------------------------------------------------------------
    # deterministic 1F1B list schedule over (micro, chunk) units
    # ------------------------------------------------------------------
    def _build_schedule(self, n_micro: int):
        """Return (issue order [("f"|"b", micro, chunk), ...], stats).

        v == 1: greedy 1F1B list schedule (backward-first, oldest-ready) —
        it reproduces the textbook ramp and the exact
        (S-1)/(n_micro + S - 1) bubble. v > 1: the reference/Megatron
        interleaved order (``PipelineParallelWithInterleave``), which is
        NOT greedy-optimal slot packing but the specific sequence whose
        warmup steps cost 1/v of a stage — that's where the bubble shrinks
        to ~(S-1)/(v * n_micro). Both are simulated on S workers (bwd
        costs 2 fwd units) to produce real busy/idle accounting in
        ``stats``.
        """
        if self._layers.num_virtual_stages > 1:
            return self._interleave_schedule(n_micro)
        return self._greedy_schedule(n_micro)

    def _greedy_schedule(self, n_micro: int):
        S = self._layers.num_stages
        C = self._layers.num_chunks
        v = self._layers.num_virtual_stages
        done_f = set()
        done_b = set()
        live = {s: 0 for s in range(S)}  # fwd activation sets held
        cap = {s: (S - s) + (v - 1) * S for s in range(S)}
        order = []
        # simulated clock per stage, in fwd-unit slots (bwd = 2 slots)
        clock = {s: 0.0 for s in range(S)}
        busy = {s: 0.0 for s in range(S)}
        finish_f = {}  # (m, c) -> sim completion time
        finish_b = {}

        def ready_f(m, c):
            return (m, c) not in done_f and (
                c == 0 or (m, c - 1) in done_f)

        def ready_b(m, c):
            return (m, c) not in done_b and (m, c) in done_f and (
                c == C - 1 or (m, c + 1) in done_b)

        total_units = 2 * n_micro * C
        while len(done_f) + len(done_b) < total_units:
            progressed = False
            for s in range(S):
                chunks = [c for c in range(C)
                          if self._layers.chunk_stage(c) == s]
                # 1F1B: drain the oldest ready backward first
                cand_b = sorted((m, c) for c in chunks
                                for m in range(n_micro) if ready_b(m, c))
                cand_f = sorted((m, c) for c in chunks
                                for m in range(n_micro) if ready_f(m, c))
                unit = None
                if cand_b:
                    unit = ("b",) + cand_b[0]
                elif cand_f and live[s] < cap[s]:
                    unit = ("f",) + cand_f[0]
                elif cand_f and not cand_b:
                    unit = ("f",) + cand_f[0]  # cap reached but nothing
                    # to drain yet (deep warmup): must progress
                if unit is None:
                    continue
                kind, m, c = unit
                # simulated start: worker free AND dependency finished
                if kind == "f":
                    dep = finish_f.get((m, c - 1), 0.0) if c else 0.0
                    t0 = max(clock[s], dep)
                    clock[s] = t0 + 1.0
                    busy[s] += 1.0
                    finish_f[(m, c)] = clock[s]
                    done_f.add((m, c))
                    live[s] += 1
                else:
                    dep = (finish_b.get((m, c + 1), 0.0)
                           if c < C - 1 else finish_f.get((m, c), 0.0))
                    t0 = max(clock[s], dep)
                    clock[s] = t0 + 2.0
                    busy[s] += 2.0
                    finish_b[(m, c)] = clock[s]
                    done_b.add((m, c))
                    live[s] -= 1
                order.append(unit)
                progressed = True
            if not progressed:  # defensive: cannot happen with valid deps
                raise RuntimeError("pipeline schedule deadlocked")
        span = max(clock.values())
        stats = {
            "slots_span": span,
            "busy": dict(busy),
            "bubble_fraction": round(
                1.0 - sum(busy.values()) / (span * S), 4) if span else 0.0,
        }
        return order, stats

    def _interleave_schedule(self, n_micro: int):
        """Reference/Megatron interleaved 1F1B
        (``pipeline_parallel.py:461``; Megatron ``schedules.py``
        ``forward_backward_pipelining_with_interleaving``): rank r warms up
        ``2*(S-r-1) + (v-1)*S`` chunk-forwards, then strictly alternates
        1F1B; micro-batches advance in groups of S per chunk, forward
        chunks ascending, backward chunks descending. Requires
        ``n_micro % S == 0`` (the reference's constraint too)."""
        S = self._layers.num_stages
        v = self._layers.num_virtual_stages
        C = self._layers.num_chunks
        if n_micro % S:
            raise ValueError(
                f"interleaved pipeline needs accumulate_steps divisible by "
                f"num_stages (got {n_micro} micro-batches, {S} stages)")
        mv = n_micro * v
        pv = S * v

        def unit(r, k, forward):
            group, ing = divmod(k, pv)
            local_chunk = ing // S
            if not forward:
                local_chunk = v - 1 - local_chunk
            micro = group * S + ing % S
            return micro, local_chunk * S + r

        # local (in-order) sequence per rank
        local = {}
        for r in range(S):
            w = min(2 * (S - r - 1) + (v - 1) * S, mv)
            seq = [("f", k) for k in range(w)]
            fi, bi = w, 0
            while fi < mv:  # steady state: one forward, then one backward
                seq.append(("f", fi))
                fi += 1
                seq.append(("b", bi))
                bi += 1
            while bi < mv:
                seq.append(("b", bi))
                bi += 1
            local[r] = seq

        # simulate: each rank executes its sequence strictly in order,
        # starting a unit once its cross-rank dependency has finished
        f_dur, b_dur = 1.0 / v, 2.0 / v
        pos = {r: 0 for r in range(S)}
        clock = {r: 0.0 for r in range(S)}
        busy = {r: 0.0 for r in range(S)}
        finish_f, finish_b = {}, {}
        events = []
        remaining = sum(len(s) for s in local.values())
        while remaining:
            progressed = False
            for r in range(S):
                while pos[r] < len(local[r]):
                    kind, k = local[r][pos[r]]
                    m, c = unit(r, k, kind == "f")
                    if kind == "f":
                        if c > 0 and (m, c - 1) not in finish_f:
                            break
                        dep = finish_f.get((m, c - 1), 0.0)
                        dur = f_dur
                    else:
                        if (m, c) not in finish_f:
                            break
                        if c < C - 1 and (m, c + 1) not in finish_b:
                            break
                        dep = (finish_b.get((m, c + 1), 0.0)
                               if c < C - 1 else finish_f[(m, c)])
                        dur = b_dur
                    start = max(clock[r], dep)
                    clock[r] = start + dur
                    busy[r] += dur
                    (finish_f if kind == "f" else finish_b)[(m, c)] = \
                        clock[r]
                    events.append((start, r, kind, m, c))
                    pos[r] += 1
                    remaining -= 1
                    progressed = True
            if not progressed:
                raise RuntimeError("interleaved schedule deadlocked")
        events.sort(key=lambda e: (e[0], e[1]))
        order = [(kind, m, c) for _, _, kind, m, c in events]
        span = max(clock.values())
        stats = {
            "slots_span": span,
            "busy": dict(busy),
            "bubble_fraction": round(
                1.0 - sum(busy.values()) / (span * S), 4) if span else 0.0,
        }
        return order, stats

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        import jax
        from paddle_tpu import ops
        from paddle_tpu.profiler import RecordEvent

        inputs, labels = data
        n_micro = self.accumulate_steps
        L = self._layers
        C = L.num_chunks
        if isinstance(inputs, (tuple, list)):  # multi-stream model inputs
            parts = [ops.split(t, n_micro, axis=0) for t in inputs]
            micro_x = [tuple(p[m] for p in parts) for m in range(n_micro)]
        else:
            micro_x = ops.split(inputs, n_micro, axis=0)
        micro_y = ops.split(labels, n_micro, axis=0)

        # saved per-(micro, chunk) forward results to drive backward in
        # schedule order; activation PYTREES hop stages leaf-by-leaf via
        # device_put (the reference's tuple p2p handshake)
        fwd_out = {}  # (m, c) -> (output tree, input tree)
        losses = []
        grads_ready = {}  # m -> cotangent tree flowing into chunk c
        peak_in_flight = [0]

        def to_stage(tree, c, stop_gradient):
            return _tree_map(
                lambda t: Tensor(jax.device_put(t.data, L.chunk_device(c)),
                                 stop_gradient=stop_gradient)
                if isinstance(t, Tensor) else t, tree)

        def run_fwd(m, c):
            x = fwd_out[(m, c - 1)][0] if c > 0 else micro_x[m]
            x = to_stage(x, c, stop_gradient=False)
            with RecordEvent(f"pp_fwd_m{m}_c{c}"):
                out = L.chunk_forward(c, x)
            fwd_out[(m, c)] = (out, x)
            peak_in_flight[0] = max(peak_in_flight[0], len(fwd_out))
            if c == C - 1:
                y = to_stage(micro_y[m], c, stop_gradient=True)
                with RecordEvent(f"pp_loss_m{m}"):
                    loss = self._loss_fn(out, y)
                losses.append(loss)
                fwd_out[(m, c)] = (loss, x)

        def run_bwd(m, c):
            out, x_in = fwd_out.pop((m, c))
            with RecordEvent(f"pp_bwd_m{m}_c{c}"):
                if c == C - 1:
                    # scale for mean over micro-batches
                    out.backward(Tensor(np.float32(1.0 / n_micro)))
                else:
                    from paddle_tpu.core.autograd import backward as \
                        tape_backward
                    roots, cots = [], []
                    for o, g in zip(_tree_leaves(out),
                                    _tree_leaves(grads_ready.pop(m))):
                        if isinstance(o, Tensor) and not o.stop_gradient:
                            roots.append(o)
                            cots.append(g)
                    tape_backward(roots, cots)
            if c > 0:
                def hop_grad(t):
                    if not isinstance(t, Tensor):
                        return t
                    g = t.grad
                    if g is None:  # leaf unused by this chunk: zero cot
                        import jax.numpy as jnp
                        g = Tensor(jnp.zeros(t.shape, t.data.dtype))
                    return Tensor(
                        jax.device_put(g.data, L.chunk_device(c - 1)),
                        stop_gradient=True)

                grads_ready[m] = _tree_map(hop_grad, x_in)
            # boundary tensors are non-leaves: drop their grad storage
            _tree_map(lambda t: setattr(t, "grad", None) or t
                      if isinstance(t, Tensor) else t, x_in)

        if n_micro not in self._schedule_cache:
            self._schedule_cache[n_micro] = self._build_schedule(n_micro)
        order, stats = self._schedule_cache[n_micro]
        stats = dict(stats)
        for kind, m, c in order:
            (run_fwd if kind == "f" else run_bwd)(m, c)
        stats["peak_in_flight_activations"] = peak_in_flight[0]
        stats["n_micro"] = n_micro
        stats["n_chunks"] = C
        self.last_schedule_stats = stats

        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad(set_to_zero=False)
        if lr_scheduler is not None:
            lr_scheduler.step()
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        return total / float(n_micro)

    @no_grad()
    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._loss_fn is not None:
            return self._loss_fn(out, labels)
        return out
