"""Pipeline parallelism: PipelineLayer + host-driven 1F1B schedule.

Parity with the reference's PP stack
(``fleet/meta_parallel/parallel_layers/pp_layers.py``: ``LayerDesc:57``,
``SharedLayerDesc:77``, ``PipelineLayer:209`` segmenting a layer list into
stages; ``fleet/meta_parallel/pipeline_parallel.py``:
``forward_backward_pipeline:117`` 1F1B, ``train_batch:228``).

TPU-native redesign (SURVEY.md §7: "PP stays host-orchestrated — the one
piece of FleetExecutor worth rebuilding"): each stage's parameters live on
that stage's devices; the 1F1B loop issues per-stage forward/backward
programs from the single controller and moves micro-batch activations
between stages with ``jax.device_put`` (which compiles to ICI transfers —
the send_v2/recv_v2 of the reference's ``_p2p_helper``). Because jax
dispatch is async, issuing in 1F1B order overlaps stage compute exactly the
way the reference's NCCL-stream schedule does, while bounding the number of
in-flight activation sets to the pipeline depth.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np

from paddle_tpu.core.autograd import no_grad
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer
from ..mesh import get_mesh

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer",
           "PipelineParallel"]


class LayerDesc:
    """Lazy layer constructor (reference: pp_layers.py:57) so stages only
    materialize where placed."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self, registry=None) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Tied-weight layer (reference: pp_layers.py:77) — e.g. embedding
    shared between the first and last stage. All instances share the same
    Parameter objects; the backward accumulates into the shared leaves
    automatically (same tape leaf), replacing the reference's explicit
    allreduce over the shared-weight group."""

    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func

    def build(self, registry=None) -> Layer:
        # the registry is scoped to one PipelineLayer build — two models
        # built in the same process must never silently share weights
        if registry is None:
            registry = {}
        if self.key not in registry:
            registry[self.key] = super().build(registry)
        return registry[self.key]


class PipelineLayer(Layer):
    """Segment a layer sequence into pipeline stages
    (reference: pp_layers.py:209).

    ``layers`` is a list of Layers / LayerDescs / callables. Segmentation is
    uniform by count (reference's default "uniform" seg_method); each
    stage's parameters are committed to that stage's devices.
    """

    def __init__(self, layers: Sequence, num_stages: Optional[int] = None,
                 loss_fn: Optional[Callable] = None, topology=None,
                 seg_method: str = "uniform", recompute_interval: int = 0,
                 mesh=None, devices: Optional[List] = None):
        super().__init__()
        import jax

        self._mesh = mesh or get_mesh()
        if devices is not None:
            self._stage_devices = devices
        elif self._mesh is not None and "pp" in self._mesh.axis_names:
            pp = self._mesh.shape["pp"]
            axes = self._mesh.axis_names
            arr = np.asarray(self._mesh.devices)
            pp_idx = axes.index("pp")
            self._stage_devices = [
                np.take(arr, s, axis=pp_idx).flatten().tolist()
                for s in range(pp)]
        else:
            devs = jax.devices()
            n = num_stages or len(devs)
            self._stage_devices = [[devs[i * len(devs) // n]]
                                   for i in range(n)]
        self.num_stages = num_stages or len(self._stage_devices)
        if len(self._stage_devices) != self.num_stages:
            # re-chunk device list into num_stages groups
            flat = [d for g in self._stage_devices for d in g]
            per = max(len(flat) // self.num_stages, 1)
            self._stage_devices = [flat[i * per:(i + 1) * per]
                                   for i in range(self.num_stages)]
        self._loss_fn = loss_fn

        # materialize layers and segment uniformly
        built: List[Layer] = []
        shared_registry: dict = {}
        for item in layers:
            if isinstance(item, LayerDesc):
                built.append(item.build(shared_registry))
            elif isinstance(item, Layer):
                built.append(item)
            else:
                raise TypeError(f"unsupported pipeline item {item!r}")
        bounds = self._segment(len(built), self.num_stages, seg_method)
        self._stage_layers: List[List[Layer]] = []
        from paddle_tpu.nn.containers import LayerList
        all_list = LayerList()
        for s in range(self.num_stages):
            seg = built[bounds[s]:bounds[s + 1]]
            self._stage_layers.append(seg)
            for l in seg:
                all_list.append(l)
        self.layers = all_list
        self._place_params()

    @staticmethod
    def _segment(n_layers: int, n_stages: int, method: str) -> List[int]:
        if method != "uniform":
            raise NotImplementedError(
                f"seg_method {method!r}; only 'uniform' is implemented")
        base, rem = divmod(n_layers, n_stages)
        bounds = [0]
        for s in range(n_stages):
            bounds.append(bounds[-1] + base + (1 if s < rem else 0))
        return bounds

    def _place_params(self):
        """Commit each stage's params to its first device (ICI neighbors)."""
        import jax
        for s, seg in enumerate(self._stage_layers):
            dev = self._stage_devices[s][0]
            for layer in seg:
                for p in layer.parameters():
                    p._data = jax.device_put(p.data, dev)
                for b in layer.buffers():
                    if b is not None:
                        b._data = jax.device_put(b.data, dev)

    def stage_device(self, s: int):
        return self._stage_devices[s][0]

    def stage_forward(self, s: int, x):
        for layer in self._stage_layers[s]:
            x = layer(x)
        return x

    def forward(self, x):
        """Non-pipelined sequential run (debug/eval parity path)."""
        import jax
        for s in range(self.num_stages):
            if isinstance(x, Tensor):
                x = Tensor(jax.device_put(x.data, self.stage_device(s)),
                           stop_gradient=x.stop_gradient)
            x = self.stage_forward(s, x)
        return x


class PipelineParallel(Layer):
    """1F1B micro-batch engine (reference: pipeline_parallel.py:117).

    ``train_batch(data, optimizer)`` splits the batch into micro-batches,
    runs the 1F1B schedule (warmup fwd, steady fwd/bwd pairs, cooldown bwd),
    accumulates gradients, steps the optimizer, and returns the mean loss —
    the reference's ``train_batch:228`` contract.
    """

    def __init__(self, layers: PipelineLayer, hcg=None, strategy=None,
                 accumulate_steps: Optional[int] = None):
        super().__init__()
        self._layers = layers
        self.accumulate_steps = accumulate_steps or layers.num_stages
        self._loss_fn = layers._loss_fn

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        import jax
        from paddle_tpu import ops

        inputs, labels = data
        n_micro = self.accumulate_steps
        S = self._layers.num_stages
        micro_x = ops.split(inputs, n_micro, axis=0)
        micro_y = ops.split(labels, n_micro, axis=0)

        # tape-per-microbatch: saved (per stage) forward closures to drive
        # backward in 1F1B order; activations hop stages via device_put
        fwd_out = {}  # (micro, stage) -> (output Tensor, input Tensor)
        losses = []
        grads_ready = {}  # micro -> cotangent Tensor flowing backward

        def run_fwd(m, s):
            x = fwd_out[(m, s - 1)][0] if s > 0 else micro_x[m]
            x = Tensor(jax.device_put(x.data,
                                      self._layers.stage_device(s)),
                       stop_gradient=False)
            out = self._layers.stage_forward(s, x)
            fwd_out[(m, s)] = (out, x)
            if s == S - 1:
                y = Tensor(jax.device_put(
                    micro_y[m].data, self._layers.stage_device(s)),
                    stop_gradient=True)
                loss = self._loss_fn(out, y)
                losses.append(loss)
                fwd_out[(m, s)] = (loss, x)

        def run_bwd(m, s):
            out, x_in = fwd_out.pop((m, s))
            if s == S - 1:
                # scale for mean over micro-batches
                out.backward(Tensor(np.float32(1.0 / n_micro)))
            else:
                out.backward(grads_ready.pop(m))
            if s > 0:
                g = x_in.grad
                grads_ready[m] = Tensor(jax.device_put(
                    g.data, self._layers.stage_device(s - 1)),
                    stop_gradient=True)
            # x_in is a non-leaf boundary tensor: drop its grad storage
            x_in.grad = None

        # --- 1F1B schedule, issued stage-major so async dispatch overlaps:
        # classic single-controller ordering — all fwds for a micro-batch
        # ripple down; backward starts as soon as the last stage finishes a
        # micro-batch; memory in flight bounded by S micro-batches.
        warmup = min(S, n_micro)
        fwd_m = 0
        bwd_m = 0
        for m in range(warmup):
            for s in range(S):
                run_fwd(m, s)
            fwd_m += 1
        while bwd_m < n_micro:
            for s in reversed(range(S)):
                run_bwd(bwd_m, s)
            bwd_m += 1
            if fwd_m < n_micro:
                for s in range(S):
                    run_fwd(fwd_m, s)
                fwd_m += 1

        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad(set_to_zero=False)
        if lr_scheduler is not None:
            lr_scheduler.step()
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        return total / float(n_micro)

    @no_grad()
    def eval_batch(self, data, compute_loss=True):
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._loss_fn is not None:
            return self._loss_fn(out, labels)
        return out
