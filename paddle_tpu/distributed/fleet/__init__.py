"""fleet facade (reference: ``python/paddle/distributed/fleet/``).

``fleet.init`` builds the 4-D topology and the mesh;
``distributed_model``/``distributed_optimizer`` wrap by strategy — on TPU the
wrapping is sharding annotation (DataParallel spec, mpu layer shardings)
rather than NCCL group plumbing (reference: fleet.py:168, model.py:30).
"""
from __future__ import annotations

from typing import Optional

from ..mesh import get_mesh
from ..parallel import DataParallel
from ..topology import CommunicateTopology, HybridCommunicateGroup
from . import mpu  # noqa: F401
from . import pipeline  # noqa: F401
from . import moe  # noqa: F401
from .moe import MoELayer, NaiveGate, SwitchGate, GShardGate  # noqa: F401
from . import sequence_parallel  # noqa: F401
from .sequence_parallel import (  # noqa: F401
    ring_attention, ulysses_attention, scatter_sequence, gather_sequence,
)
from .pipeline import (  # noqa: F401
    LayerDesc, SharedLayerDesc, PipelineLayer, PipelineParallel,
)
from . import spmd_pipeline  # noqa: F401
from .spmd_pipeline import (  # noqa: F401
    pipeline_spmd, spmd_schedule_stats, SpmdPipelineLayer,
    SpmdPipelineParallel, pipeline_spmd_hetero, SpmdHeteroPipelineLayer,
)
from .mpu import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from . import utils  # noqa: F401
from .utils import recompute  # noqa: F401

__all__ = ["init", "DistributedStrategy", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_num", "worker_index", "mpu", "ColumnParallelLinear",
           "RowParallelLinear", "VocabParallelEmbedding",
           "ParallelCrossEntropy", "LayerDesc", "SharedLayerDesc", "PipelineLayer", "PipelineParallel", "pipeline_spmd", "spmd_schedule_stats", "SpmdPipelineLayer", "SpmdPipelineParallel", "pipeline_spmd_hetero", "SpmdHeteroPipelineLayer", "MoELayer", "NaiveGate", "SwitchGate", "GShardGate", "ring_attention", "ulysses_attention", "scatter_sequence", "gather_sequence", "utils", "recompute"]

_state = {"hcg": None, "strategy": None}


class DistributedStrategy:
    """Reference: ``fleet/base/distributed_strategy.py`` — the switchboard.
    Only the knobs with TPU meaning are consumed; the rest are accepted for
    API compatibility and recorded."""

    def __init__(self):
        self.hybrid_configs = {"dp_degree": 1, "mp_degree": 1,
                               "pp_degree": 1, "sharding_degree": 1,
                               "sep_degree": 1}
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.find_unused_parameters = False


def init(role_maker=None, is_collective=True, strategy: Optional[
        DistributedStrategy] = None):
    """Reference: fleet.py:168 — build topology + communicators (here: the
    mesh) from the strategy's hybrid_configs."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    topo = CommunicateTopology(
        ["data", "pipe", "sharding", "model"],
        [hc.get("dp_degree", 1), hc.get("pp_degree", 1),
         hc.get("sharding_degree", 1), hc.get("mp_degree", 1)])
    hcg = HybridCommunicateGroup(topo)
    _state["hcg"] = hcg
    _state["strategy"] = strategy
    return hcg


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    if _state["hcg"] is None:
        raise RuntimeError("call fleet.init() first")
    return _state["hcg"]


def distributed_model(model):
    """Reference: model.py:30 — wrap by mode. DP wrapping covers the pure
    data-parallel case; TP/PP models are built from mpu/pipeline layers and
    pass through (their parallelism already lives in the shardings).
    ``strategy.recompute`` is honored for models that expose a
    ``cfg.recompute`` switch (the zoo models do)."""
    hcg = get_hybrid_communicate_group()
    strategy = _state.get("strategy")
    if strategy is not None and strategy.recompute:
        cfg = getattr(model, "cfg", None)
        if cfg is not None and hasattr(cfg, "recompute"):
            cfg.recompute = True
    if hcg.get_data_parallel_world_size() > 1 and \
            hcg.get_model_parallel_world_size() == 1 and \
            hcg.get_pipe_parallel_world_size() == 1:
        return DataParallel(model, mesh=get_mesh())
    return model


def distributed_optimizer(optimizer, strategy=None):
    """Reference: fleet.py distributed_optimizer → HybridParallelOptimizer.
    Under GSPMD the gradient collectives live inside the compiled step, so
    the optimizer passes through unchanged."""
    return optimizer


def worker_num() -> int:
    from ..env import get_world_size
    return get_world_size()


def worker_index() -> int:
    from ..env import get_rank
    return get_rank()
