"""Sequence/context parallelism + ring attention.

Greenfield capability (SURVEY.md §5: the reference snapshot has NO sequence
parallelism — no ring attention, no Ulysses; SURVEY.md §7 directs designing
it GSPMD-natively for the Llama long-context north star).

Design: activations shard the *sequence* dim on the ``sp`` mesh axis. For
attention — the one op that mixes sequence positions — K/V shards rotate
around the ring with ``lax.ppermute`` (one ICI hop per step) while each
rank's resident Q block folds the incoming block into an online-softmax
accumulator. Peak memory per rank is O((S/n)^2) scores and the K/V transfer
overlaps the block matmuls (async ICI DMA), which is exactly the RingAttention
schedule. Causal masking skips rotations that are entirely in the future.

``ulysses_attention`` offers the all-to-all alternative (head-scatter):
re-shard [B, S/n, H, D] -> [B, S, H/n, D], run any attention (the Pallas
flash kernel on chip), and shard back — two all-to-alls on ICI.
"""
from __future__ import annotations

import math
from typing import Optional

from jax.sharding import PartitionSpec as P

from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor
from ..mesh import get_mesh
from ..sharding_api import with_sharding_constraint

__all__ = ["ring_attention", "ulysses_attention", "scatter_sequence",
           "gather_sequence"]


def scatter_sequence(x: Tensor, mesh=None, axis: str = "sp",
                     seq_dim: int = 1) -> Tensor:
    """Annotate the sequence dim sharded on the sp axis."""
    mesh = mesh or get_mesh()
    spec = [P.UNCONSTRAINED] * x.ndim
    spec[seq_dim] = axis
    return with_sharding_constraint(x, P(*spec), mesh)


def gather_sequence(x: Tensor, mesh=None, seq_dim: int = 1) -> Tensor:
    """Constrain the sequence dim replicated (an all-gather over sp)."""
    mesh = mesh or get_mesh()
    spec = [P.UNCONSTRAINED] * x.ndim
    spec[seq_dim] = None
    return with_sharding_constraint(x, P(*spec), mesh)


def _ring_attention_arrays(q, k, v, mesh, axis, causal, sm_scale):
    """Pure-array ring attention over a seq-sharded [B, S, H, D] triple."""
    import jax
    import jax.numpy as jnp

    n = mesh.shape[axis]

    def per_rank(ql, kl, vl):
        # local shards [B, Sq, H, D]
        b, sq, h, d = ql.shape
        rank = jax.lax.axis_index(axis)
        qt = jnp.swapaxes(ql, 1, 2).astype(jnp.float32)  # [B, H, Sq, D]
        scale = sm_scale

        def step(r, carry):
            m, l, acc, kc, vc = carry
            src = (rank - r) % n  # origin rank of the current K/V block

            def compute(m, l, acc):
                kt = jnp.swapaxes(kc, 1, 2).astype(jnp.float32)
                vt = jnp.swapaxes(vc, 1, 2).astype(jnp.float32)
                s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
                if causal:
                    q_pos = rank * sq + jnp.arange(sq)
                    k_pos = src * sq + jnp.arange(sq)
                    mask = q_pos[:, None] >= k_pos[None, :]
                    s = jnp.where(mask[None, None], s, -jnp.inf)
                m_cur = jnp.max(s, axis=-1)
                m_new = jnp.maximum(m, m_cur)
                # guard fully-masked rows (exp(-inf - -inf))
                safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
                p = jnp.exp(s - safe_m[..., None])
                p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0, p)
                alpha = jnp.where(jnp.isneginf(m), 0.0,
                                  jnp.exp(m - safe_m))
                l_new = l * alpha + p.sum(axis=-1)
                acc_new = acc * alpha[..., None] + \
                    jnp.einsum("bhqk,bhkd->bhqd", p, vt)
                return m_new, l_new, acc_new

            if causal:
                # blocks entirely in the future (src > rank) skip the
                # matmuls — the ring still rotates so later steps see the
                # right K/V
                m, l, acc = jax.lax.cond(
                    src <= rank, compute, lambda m_, l_, a_: (m_, l_, a_),
                    m, l, acc)
            else:
                m, l, acc = compute(m, l, acc)
            perm = [(i, (i + 1) % n) for i in range(n)]
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return m, l, acc, kc, vc

        m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, sq), jnp.float32)
        a0 = jnp.zeros((b, h, sq, d), jnp.float32)
        # mark the replicated initializers device-varying so the scan carry
        # type matches the rank-dependent outputs (shard_map vma rule)
        from .utils import pvary_compat
        m0, l0, a0 = (pvary_compat(x, axis) for x in (m0, l0, a0))
        m, l, acc, _, _ = jax.lax.fori_loop(0, n, step,
                                            (m0, l0, a0, kl, vl))
        out = acc / jnp.maximum(l, 1e-20)[..., None]
        return jnp.swapaxes(out, 1, 2).astype(ql.dtype)

    spec = P(None, axis, None, None)
    return jax.shard_map(per_rank, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)


def ring_attention(query, key, value, mesh=None, axis: str = "sp",
                   causal: bool = False, sm_scale: Optional[float] = None):
    """Ring attention over a sequence-sharded [B, S, H, D] triple
    (Tensor-in/Tensor-out, taped)."""
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise RuntimeError(f"ring_attention needs a mesh with axis {axis!r}")
    if sm_scale is None:
        d = query.shape[-1]
        sm_scale = 1.0 / math.sqrt(d)
    return apply_op(
        lambda q, k, v: _ring_attention_arrays(q, k, v, mesh, axis, causal,
                                               sm_scale),
        query, key, value, op_name="ring_attention")


def ulysses_attention(query, key, value, mesh=None, axis: str = "sp",
                      causal: bool = False):
    """Ulysses/DeepSpeed-style SP: all-to-all heads<->sequence so each rank
    holds full sequences for a head subset, then ordinary attention."""
    from paddle_tpu.nn import functional as F
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise RuntimeError(
            f"ulysses_attention needs a mesh with axis {axis!r}")
    # re-shard: seq-sharded -> head-sharded (GSPMD emits the all-to-all)
    head_spec = P(None, None, axis, None)

    def reshard(t, spec):
        return with_sharding_constraint(t, spec, mesh)

    q = reshard(query, head_spec)
    k = reshard(key, head_spec)
    v = reshard(value, head_spec)
    out = F.scaled_dot_product_attention(q, k, v, is_causal=causal)
    return reshard(out, P(None, axis, None, None))
