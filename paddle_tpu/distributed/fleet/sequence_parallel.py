"""Sequence/context parallelism + ring attention.

Greenfield capability (SURVEY.md §5: the reference snapshot has NO sequence
parallelism — no ring attention, no Ulysses; SURVEY.md §7 directs designing
it GSPMD-natively for the Llama long-context north star).

Design: activations shard the *sequence* dim on the ``sp`` mesh axis. For
attention — the one op that mixes sequence positions — K/V shards rotate
around the ring with ``lax.ppermute`` (one ICI hop per step) while each
rank's resident Q block folds the incoming block into an online-softmax
accumulator. Peak memory per rank is O((S/n)^2) scores and the K/V transfer
overlaps the block matmuls (async ICI DMA), which is exactly the RingAttention
schedule. Causal masking skips rotations that are entirely in the future.

``ulysses_attention`` offers the all-to-all alternative (head-scatter):
re-shard [B, S/n, H, D] -> [B, S, H/n, D], run any attention (the Pallas
flash kernel on chip), and shard back — two all-to-alls on ICI.
"""
from __future__ import annotations

import math
from typing import Optional

from jax.sharding import PartitionSpec as P

from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor
from ..mesh import get_mesh
from ..sharding_api import with_sharding_constraint

__all__ = ["ring_attention", "ulysses_attention", "scatter_sequence",
           "gather_sequence"]


def scatter_sequence(x: Tensor, mesh=None, axis: str = "sp",
                     seq_dim: int = 1) -> Tensor:
    """Annotate the sequence dim sharded on the sp axis."""
    mesh = mesh or get_mesh()
    spec = [P.UNCONSTRAINED] * x.ndim
    spec[seq_dim] = axis
    return with_sharding_constraint(x, P(*spec), mesh)


def gather_sequence(x: Tensor, mesh=None, seq_dim: int = 1) -> Tensor:
    """Constrain the sequence dim replicated (an all-gather over sp)."""
    mesh = mesh or get_mesh()
    spec = [P.UNCONSTRAINED] * x.ndim
    spec[seq_dim] = None
    return with_sharding_constraint(x, P(*spec), mesh)


def _ring_attention_arrays(q, k, v, mesh, axis, causal, sm_scale):
    """Pure-array ring attention over a seq-sharded [B, S, H, D] triple."""
    import jax
    import jax.numpy as jnp

    n = mesh.shape[axis]

    def per_rank(ql, kl, vl):
        # local shards [B, Sq, H, D]
        b, sq, h, d = ql.shape
        rank = jax.lax.axis_index(axis)
        qt = jnp.swapaxes(ql, 1, 2).astype(jnp.float32)  # [B, H, Sq, D]
        scale = sm_scale

        def step(r, carry):
            m, l, acc, kc, vc = carry
            src = (rank - r) % n  # origin rank of the current K/V block

            def compute(m, l, acc):
                kt = jnp.swapaxes(kc, 1, 2).astype(jnp.float32)
                vt = jnp.swapaxes(vc, 1, 2).astype(jnp.float32)
                s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
                if causal:
                    q_pos = rank * sq + jnp.arange(sq)
                    k_pos = src * sq + jnp.arange(sq)
                    mask = q_pos[:, None] >= k_pos[None, :]
                    s = jnp.where(mask[None, None], s, -jnp.inf)
                m_cur = jnp.max(s, axis=-1)
                m_new = jnp.maximum(m, m_cur)
                # guard fully-masked rows (exp(-inf - -inf))
                safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
                p = jnp.exp(s - safe_m[..., None])
                p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0, p)
                alpha = jnp.where(jnp.isneginf(m), 0.0,
                                  jnp.exp(m - safe_m))
                l_new = l * alpha + p.sum(axis=-1)
                acc_new = acc * alpha[..., None] + \
                    jnp.einsum("bhqk,bhkd->bhqd", p, vt)
                return m_new, l_new, acc_new

            if causal:
                # blocks entirely in the future (src > rank) skip the
                # matmuls — the ring still rotates so later steps see the
                # right K/V
                m, l, acc = jax.lax.cond(
                    src <= rank, compute, lambda m_, l_, a_: (m_, l_, a_),
                    m, l, acc)
            else:
                m, l, acc = compute(m, l, acc)
            perm = [(i, (i + 1) % n) for i in range(n)]
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return m, l, acc, kc, vc

        m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, sq), jnp.float32)
        a0 = jnp.zeros((b, h, sq, d), jnp.float32)
        # mark the replicated initializers device-varying so the scan carry
        # type matches the rank-dependent outputs (shard_map vma rule)
        from .utils import pvary_compat
        m0, l0, a0 = (pvary_compat(x, axis) for x in (m0, l0, a0))
        m, l, acc, _, _ = jax.lax.fori_loop(0, n, step,
                                            (m0, l0, a0, kl, vl))
        out = acc / jnp.maximum(l, 1e-20)[..., None]
        return jnp.swapaxes(out, 1, 2).astype(ql.dtype)

    spec = P(None, axis, None, None)
    from .utils import shard_map_compat
    return shard_map_compat(per_rank, mesh, (spec, spec, spec),
                            spec)(q, k, v)


def _ring_flash_arrays(q, k, v, mesh, axis, causal, sm_scale):
    """Ring attention with the Pallas flash kernel per block.

    The jnp formulation materializes [Sq/n, Sk/n] score blocks per ring
    step; at pod-scale contexts those blocks are themselves huge. Here
    each step runs the flash FORWARD kernel on the resident Q against the
    incoming K/V shard (O(block) VMEM) and merges the per-step normalized
    outputs through their log-sum-exps; the backward is the ring-flash
    rule — one flash BACKWARD kernel per step with the GLOBAL lse (the
    flash-2 identity: p = exp(s - lse_global) reproduces each block's true
    softmax slice), dq accumulating locally while dk/dv ride the ring home.
    The ring loop is python-unrolled (n is static), so the diagonal step
    compiles the causal kernel and off-diagonal steps the full kernel,
    with `lax.cond` skipping entirely-future blocks at runtime."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa

    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def per_rank(ql, kl, vl):
        B, Sq, H, D = ql.shape
        Sk = kl.shape[1]
        bq = fa._pick_block(fa._DEF_BLOCK_Q, Sq)
        bk = fa._pick_block(fa._DEF_BLOCK_K, Sk)
        # same guards the public wrapper applies (we call the kernel
        # internals directly): an indivisible shard would leave grid-
        # uncovered output rows silently uninitialized, and an over-VMEM
        # forced block would die in a long Mosaic compile
        if Sq % bq or Sk % bk:
            raise ValueError(
                f"ring-flash requires the per-rank shard lengths "
                f"({Sq}, {Sk}) divisible by the kernel blocks ({bq}, {bk})"
                "; pad the sequence to a multiple of 128 x ring size")
        if bq > fa._MAX_BLOCK or bk > fa._MAX_BLOCK:
            raise ValueError(
                f"no VMEM-safe block tiling for ring shard lengths "
                f"({Sq}, {Sk}); pad the sequence to a multiple of "
                f"128 x ring size")
        rank = jax.lax.axis_index(axis)

        def to_k(x):  # [B, S, H, D] -> [B*H, S, D] kernel layout
            return jnp.swapaxes(x, 1, 2).reshape(B * H, x.shape[1], D)

        def from_k(x, s):
            return jnp.swapaxes(x.reshape(B, H, s, D), 1, 2)

        def fwd_block(qk, kk, vk, blk_causal):
            return fa._fwd(qk, kk, vk, None, None, None, None, blk_causal,
                           sm_scale, bq, bk, 1, 1, None, 0.0)

        def bwd_block(qk, kk, vk, o, lse, do, blk_causal):
            return fa._bwd(qk, kk, vk, o, lse, do, None, None, None, None,
                           blk_causal, sm_scale, bq, bk, 1, 1, None, 0.0)

        def merge(o, lse, o_s, lse_s):
            # lse layout is the kernel's [BH, 1, Sq]. The accumulator
            # stays f32 across ring steps (a per-step cast to bf16 would
            # re-quantize n times); per_rank casts once at the end.
            m = jnp.maximum(lse, lse_s)
            new_lse = m + jnp.log(jnp.exp(lse - m) + jnp.exp(lse_s - m))
            w_a = jnp.swapaxes(jnp.exp(lse - new_lse), 1, 2)  # [BH, Sq, 1]
            w_b = jnp.swapaxes(jnp.exp(lse_s - new_lse), 1, 2)
            return w_a * o + w_b * o_s.astype(jnp.float32), new_lse

        def ring_fwd(qk, kk, vk):
            # step 0 is always the resident (diagonal) shard; the output
            # accumulator is f32 until the final cast. Steps 1..n-1 are
            # IDENTICAL non-causal kernels, so they run as ONE lax.scan
            # body — program size and compile time stay O(1) in ring size
            # (a python unroll at sp=64+ would emit hundreds of kernels)
            o, lse = fwd_block(qk, kk, vk, causal)
            o = o.astype(jnp.float32)

            def step(carry, s):
                o_, lse_, kc, vc = carry
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)
                if causal:
                    # src = rank - s (mod n) is a PAST shard iff rank >= s
                    def hit(args):
                        oo, ll, kc_, vc_ = args
                        o_s, lse_s = fwd_block(qk, kc_, vc_, False)
                        return merge(oo, ll, o_s, lse_s)

                    o_, lse_ = jax.lax.cond(
                        rank >= s, hit,
                        lambda args: (args[0], args[1]),
                        (o_, lse_, kc, vc))
                else:
                    o_s, lse_s = fwd_block(qk, kc, vc, False)
                    o_, lse_ = merge(o_, lse_, o_s, lse_s)
                return (o_, lse_, kc, vc), None

            if n > 1:
                (o, lse, _, _), _ = jax.lax.scan(
                    step, (o, lse, kk, vk), jnp.arange(1, n))
            return o, lse

        @jax.custom_vjp
        def ring(qk, kk, vk):
            return ring_fwd(qk, kk, vk)[0]

        def ring_f(qk, kk, vk):
            o, lse = ring_fwd(qk, kk, vk)
            return o, (qk, kk, vk, o, lse)

        def ring_b(res, do):
            qk, kk, vk, o, lse = res
            zq = jnp.zeros(qk.shape, jnp.float32)
            zk = jnp.zeros(kk.shape, jnp.float32)
            # diagonal step
            dq_s, dk_s, dv_s = bwd_block(qk, kk, vk, o, lse, do, causal)
            dq = zq + dq_s
            dk_acc = zk + dk_s
            dv_acc = zk + dv_s

            def step(carry, s):
                dq_, dka, dva, kc, vc = carry
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)
                # dk/dv accumulators ride the SAME ring so each
                # contribution lands on its shard's row; after the full n
                # rotations they are home again
                dka = jax.lax.ppermute(dka, axis, perm)
                dva = jax.lax.ppermute(dva, axis, perm)
                if causal:
                    def hit(args):
                        d_, ka_, va_, kc_, vc_ = args
                        g_q, g_k, g_v = bwd_block(qk, kc_, vc_, o, lse,
                                                  do, False)
                        return d_ + g_q, ka_ + g_k, va_ + g_v

                    dq_, dka, dva = jax.lax.cond(
                        rank >= s, hit, lambda args: args[:3],
                        (dq_, dka, dva, kc, vc))
                else:
                    g_q, g_k, g_v = bwd_block(qk, kc, vc, o, lse, do,
                                              False)
                    dq_ = dq_ + g_q
                    dka = dka + g_k
                    dva = dva + g_v
                return (dq_, dka, dva, kc, vc), None

            if n > 1:
                (dq, dk_acc, dv_acc, _, _), _ = jax.lax.scan(
                    step, (dq, dk_acc, dv_acc, kk, vk), jnp.arange(1, n))
            # one final rotation completes the cycle (n rotations total)
            dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
            return (dq.astype(qk.dtype), dk_acc.astype(kk.dtype),
                    dv_acc.astype(vk.dtype))

        ring.defvjp(ring_f, ring_b)
        out = ring(to_k(ql), to_k(kl), to_k(vl))
        return from_k(out, Sq).astype(ql.dtype)

    spec = P(None, axis, None, None)
    # check_vma off: pallas_call's output avals carry no vma annotation,
    # which the checker (not the semantics) rejects inside shard_map
    from .utils import shard_map_compat
    return shard_map_compat(per_rank, mesh, (spec, spec, spec), spec,
                            check_vma=False)(q, k, v)


def _ring_flash_tileable(S: int, n: int) -> bool:
    """True when the per-rank shard length admits a VMEM-legal kernel
    tiling (the auto path falls back to the jnp composite otherwise, so
    flipping the default can never reject a previously-working shape)."""
    from paddle_tpu.ops.pallas import flash_attention as fa
    if S % n:
        return False
    local = S // n
    bq = fa._pick_block(fa._DEF_BLOCK_Q, local)
    bk = fa._pick_block(fa._DEF_BLOCK_K, local)
    return (local % bq == 0 and local % bk == 0
            and bq <= fa._MAX_BLOCK and bk <= fa._MAX_BLOCK)


def ring_attention(query, key, value, mesh=None, axis: str = "sp",
                   causal: bool = False, sm_scale: Optional[float] = None,
                   use_flash: Optional[bool] = None):
    """Ring attention over a sequence-sharded [B, S, H, D] triple
    (Tensor-in/Tensor-out, taped).

    ``use_flash=None`` routes each ring step through the Pallas flash
    kernel on TPU (O(block) VMEM per step — the jnp composite would
    materialize [Sq/n, Sk/n] score blocks, themselves enormous at
    pod-scale contexts) and keeps the jnp composite elsewhere; pass
    True/False to force a path (True works in interpret mode for tests).
    """
    import jax as _jax
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise RuntimeError(f"ring_attention needs a mesh with axis {axis!r}")
    if sm_scale is None:
        d = query.shape[-1]
        sm_scale = 1.0 / math.sqrt(d)
    if use_flash is None:
        # auto mode must not NARROW accepted shapes vs the composite:
        # only take the kernel path when the per-rank shard tiles
        use_flash = _jax.default_backend() == "tpu" and \
            _ring_flash_tileable(query.shape[1], mesh.shape[axis])
    impl = _ring_flash_arrays if use_flash else _ring_attention_arrays
    return apply_op(
        lambda q, k, v: impl(q, k, v, mesh, axis, causal, sm_scale),
        query, key, value, op_name="ring_attention")


def ulysses_attention(query, key, value, mesh=None, axis: str = "sp",
                      causal: bool = False):
    """Ulysses/DeepSpeed-style SP: all-to-all heads<->sequence so each rank
    holds full sequences for a head subset, then ordinary attention."""
    from paddle_tpu.nn import functional as F
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise RuntimeError(
            f"ulysses_attention needs a mesh with axis {axis!r}")
    # re-shard: seq-sharded -> head-sharded (GSPMD emits the all-to-all)
    head_spec = P(None, None, axis, None)

    def reshard(t, spec):
        return with_sharding_constraint(t, spec, mesh)

    q = reshard(query, head_spec)
    k = reshard(key, head_spec)
    v = reshard(value, head_spec)
    out = F.scaled_dot_product_attention(q, k, v, is_causal=causal)
    return reshard(out, P(None, axis, None, None))
