"""fleet.utils — activation recomputation (gradient checkpointing).

Capability parity with the reference's
``python/paddle/distributed/fleet/utils/__init__.py`` ``recompute`` (backed by
``fleet/recompute/recompute.py``: a PyLayer that stashes RNG state + inputs,
drops activations, and re-runs the forward inside backward).

TPU-native redesign: rematerialization is a *compiler* feature on XLA —
``jax.checkpoint`` marks the region and XLA re-emits the forward ops inside
the backward computation, so there is no RNG stash/restore dance (the replayed
HLO reuses the traced-in RNG values, which is exactly "preserve_rng_state").
The tape integration is one ``apply_op`` call whose vjp closure is the
checkpointed function's — saving only the region's *inputs*, not its
activations, in the GradNode.
"""
from __future__ import annotations

import functools
from typing import Any

import jax

from paddle_tpu.core import autograd as _ag
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer
from . import fs  # noqa: F401
from .fs import HDFSClient, LocalFS  # noqa: F401

__all__ = ["recompute", "recompute_sequential", "LocalFS", "HDFSClient",
           "fs", "pvary_compat", "shard_map_compat"]


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=None,
                     axis_names=None):
    """``jax.shard_map`` across jax versions: new jax takes
    ``check_vma``/``axis_names``, older jax only has
    ``jax.experimental.shard_map.shard_map`` with ``check_rep`` and the
    inverse ``auto`` set (axes NOT handled manually). Shared by the ring
    attention and SPMD pipeline kernels and the collective layer."""
    if hasattr(jax, "shard_map"):
        kw = {}
        if check_vma is not None:
            kw["check_vma"] = check_vma
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as sm
    # The legacy check_rep=True checker false-positives on valid programs
    # (psum-inside-fori_loop carries, ppermute pipelines — measured: 4
    # extra test failures with the default on jax 0.4.x), which is why
    # later jax relaxed it into check_vma. Run the legacy path unchecked
    # unless the caller explicitly asked for checking.
    kw = {"check_rep": bool(check_vma)}
    if axis_names is not None:
        kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def match_vma(value, like):
    """Cast ``value`` to carry (at least) the varying-manual-axes of
    ``like`` — the fix for fresh constants (scan carries, zero states)
    created INSIDE a shard_map manual region next to varying inputs: the
    scan's carry-in must type-match its carry-out. No-op outside manual
    regions or on pre-vma jax."""
    try:
        want = frozenset(getattr(jax.typeof(like), "vma", frozenset()))
        have = frozenset(getattr(jax.typeof(value), "vma", frozenset()))
        missing = tuple(sorted(want - have))
        if missing:
            return jax.lax.pcast(value, missing, to="varying")
    except (AttributeError, TypeError):
        pass
    return value


def pvary_compat(x, axis):
    """Mark a freshly-created invariant array device-varying over ``axis``
    (the shard_map vma rule for scan carries whose other inputs are
    rank-dependent). No-op when the value is already varying or the running
    jax predates/postdates the pcast/pvary split — shared by the ring
    attention and SPMD pipeline kernels."""
    try:
        if axis in getattr(jax.typeof(x), "vma", ()):
            return x
    except (AttributeError, TypeError):
        pass
    pcast_err = None
    try:
        return jax.lax.pcast(x, axis, to="varying")
    except AttributeError:
        pass
    except TypeError as e:
        pcast_err = e
    try:
        # pre-pcast jax: the deprecated spelling
        return jax.lax.pvary(x, axis)
    except AttributeError:
        if pcast_err is None:
            # pre-vma jax (no pcast, no pvary): nothing to mark —
            # shard_map has no varying-manual-axes typing at all here
            return x
        # pcast exists but rejected the call: surface THAT error rather
        # than leave an invariant carry to fail later with an opaque
        # shard_map vma mismatch (or mask it with pvary's AttributeError)
        raise pcast_err


def _owning_layer(function) -> Layer | None:
    if isinstance(function, Layer):
        return function
    bound = getattr(function, "__self__", None)
    return bound if isinstance(bound, Layer) else None


def _collect_state(function, layer):
    """Every Tensor whose storage must be threaded through the checkpoint
    region so its gradient flows: the owning Layer's params/buffers, or —
    for a plain function — Layers/Tensors captured by its closure (the
    ``recompute(lambda x: self.mlp(x), h)`` idiom; without this the closed-
    over weights would trace as constants and silently stop training)."""
    tensors, seen = [], set()

    def add(t):
        if t is not None and id(t) not in seen:
            seen.add(id(t))
            tensors.append(t)

    def add_layer(lay):
        for _, p in lay.named_parameters():
            add(p)
        for _, b in lay.named_buffers():
            add(b)

    visited = set()

    def scan(obj, depth):
        if depth > 3 or id(obj) in visited:
            return
        visited.add(id(obj))
        if isinstance(obj, Layer):
            add_layer(obj)
        elif isinstance(obj, Tensor):
            add(obj)
        elif isinstance(obj, functools.partial):
            scan(obj.func, depth + 1)
            for a in obj.args:
                scan(a, depth + 1)
            for v in obj.keywords.values():
                scan(v, depth + 1)
        elif isinstance(obj, (list, tuple, set)):
            for o in obj:
                scan(o, depth + 1)
        elif isinstance(obj, dict):
            for o in obj.values():
                scan(o, depth + 1)
        elif callable(obj):
            bound = getattr(obj, "__self__", None)
            if isinstance(bound, Layer):
                add_layer(bound)
            for cell in getattr(obj, "__closure__", None) or ():
                try:
                    scan(cell.cell_contents, depth + 1)
                except ValueError:  # empty cell
                    pass

    if layer is not None:
        add_layer(layer)
        return tensors
    scan(function, 0)
    return tensors


def _wrap_tree(obj):
    """Rebuild Tensor wrappers around jax arrays for the inner call."""
    if isinstance(obj, jax.Array) or hasattr(obj, "aval"):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_wrap_tree(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _wrap_tree(v) for k, v in obj.items()}
    return obj


def _unwrap_tree(obj):
    if isinstance(obj, Tensor):
        return obj.data
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unwrap_tree(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _unwrap_tree(v) for k, v in obj.items()}
    return obj


def recompute(function, *args, preserve_rng_state: bool = True,
              use_reentrant: bool = True, **kwargs):
    """Run ``function(*args, **kwargs)`` without saving its activations;
    the forward is re-run (by XLA rematerialization) during backward.

    ``function`` may be a ``Layer``, a bound method of a ``Layer`` (its
    parameters/buffers are threaded through so their gradients flow), or a
    pure function of its tensor arguments. ``preserve_rng_state`` and
    ``use_reentrant`` are accepted for API parity; RNG preservation is
    inherent (see module docstring).
    """
    del preserve_rng_state, use_reentrant
    layer = _owning_layer(function)
    call = layer.forward if layer is not None and isinstance(function, Layer) \
        else function
    state_tensors = _collect_state(function, layer)

    def region(state_list, arg_tree, kw_tree):
        # everything below runs on (possibly traced) jax arrays; the tape
        # must not record the inner ops — the whole region is ONE tape node
        saved = [t._data for t in state_tensors]
        for t, a in zip(state_tensors, state_list):
            t._data = a
        try:
            with _ag.no_grad():
                out = call(*_wrap_tree(arg_tree), **_wrap_tree(kw_tree))
        finally:
            for t, s in zip(state_tensors, saved):
                t._data = s
        return _unwrap_tree(out)

    ckpt = jax.checkpoint(region)
    return _ag.apply_op(ckpt, list(state_tensors), list(args), dict(kwargs),
                        op_name="recompute")


def recompute_sequential(ctx: Any, functions, *args):
    """Segment a ``Sequential``-like list of layers and recompute each segment
    (reference: ``incubate/distributed/fleet/recompute_sequential``).

    ``ctx`` accepts ``{"segments": N}`` (default 1 segment per layer).
    """
    layers = list(functions)
    segments = int((ctx or {}).get("segments", len(layers))) or 1
    per = max(1, (len(layers) + segments - 1) // segments)
    out = args
    for i in range(0, len(layers), per):
        chunk = layers[i:i + per]

        class _Seg(Layer):
            def __init__(self, mods):
                super().__init__()
                for j, m in enumerate(mods):
                    setattr(self, f"seg{j}", m)
                self._mods = mods

            def forward(self, *xs):
                for m in self._mods:
                    xs = m(*xs) if isinstance(xs, tuple) else m(xs)
                    if not isinstance(xs, tuple):
                        xs = (xs,)
                return xs if len(xs) > 1 else xs[0]

        seg = _Seg(chunk)
        res = recompute(seg, *(out if isinstance(out, tuple) else (out,)))
        out = res
    return out
