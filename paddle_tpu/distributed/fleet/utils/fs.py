"""Filesystem abstraction for checkpoint storage (reference:
``python/paddle/distributed/fleet/utils/fs.py`` — FS base + LocalFS +
HDFSClient used by save_persistables/auto-checkpoint).

LocalFS is fully functional; HDFSClient keeps the surface and raises on
use (no hadoop client in this build) so recipe code fails with a clear
message at the call site rather than an AttributeError.
"""
from __future__ import annotations

import os
import shutil
from typing import List, Tuple

__all__ = ["FS", "LocalFS", "HDFSClient"]


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError


class LocalFS(FS):
    """Reference: fs.py LocalFS."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def is_dir(self, fs_path) -> bool:
        return os.path.isdir(fs_path)

    def is_file(self, fs_path) -> bool:
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path) -> bool:
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path, dirs_exist_ok=True)
        else:
            shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path) and not exist_ok:
            raise FileExistsError(fs_path)
        open(fs_path, "a").close()

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if test_exists and not os.path.exists(src_path):
            raise FileNotFoundError(f"mv source does not exist: {src_path}")
        if not overwrite and os.path.exists(dst_path):
            raise FileExistsError(dst_path)
        shutil.move(src_path, dst_path)

    def list_dirs(self, fs_path) -> List[str]:
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """Surface parity only (reference: fs.py HDFSClient, a hadoop-cli
    wrapper). No hadoop client exists in this build: every method raises
    with guidance to use LocalFS or a mounted path. Deliberately NOT an
    FS subclass — the base's NotImplementedError defaults would shadow
    the helpful message."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        self._err = RuntimeError(
            "HDFSClient requires a hadoop client, which this build does "
            "not ship; mount the storage and use LocalFS instead")

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)

        def stub(*a, **k):
            raise self._err
        return stub
