"""Megatron-style tensor-parallel layers.

Parity with the reference's mpu layer set
(``python/paddle/distributed/fleet/layers/mpu/mp_layers.py``:
``VocabParallelEmbedding:35``, ``ColumnParallelLinear:173``,
``RowParallelLinear:332``, ``ParallelCrossEntropy:498`` and the PyLayer comm
primitives in ``mp_ops.py``). TPU-native redesign: there are no explicit
``_c_identity/_mp_allreduce`` collectives — each layer creates its weight
with a PartitionSpec on the ``mp`` mesh axis and constrains its activations;
GSPMD inserts the identity/allreduce/allgather exactly where the reference
hand-places them (SURVEY.md §7 principle 3: "parallelism is sharding
annotation, not program surgery").

Sharding map (weights stored [in, out] like paddle):
  ColumnParallelLinear   W: P(None, "mp")   y sharded on features
  RowParallelLinear      W: P("mp", None)   contraction → psum by GSPMD
  VocabParallelEmbedding W: P("mp", None)   vocab-sharded lookup
  ParallelCrossEntropy   logits constrained P(..., "mp") — the vocab-
                         parallel softmax-CE (ref c_softmax_with_cross_entropy)
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

_U = P.UNCONSTRAINED  # leave batch dims to the partitioner (None would
                      # force replication and all-gather a dp-sharded batch)

from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as I
from paddle_tpu.nn.layer_base import Layer
from paddle_tpu.param_attr import ParamAttr
from ..mesh import get_mesh
from ..sharding_api import shard_tensor, with_sharding_constraint

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy"]


def _mp_axis(mesh):
    for cand in ("mp", "model", "tp"):
        if cand in mesh.axis_names:
            return cand
    raise ValueError(
        f"mesh {mesh.axis_names} has no model-parallel axis "
        "('mp'/'model'/'tp')")


class ColumnParallelLinear(Layer):
    """Output-feature-sharded linear (reference: mp_layers.py:173)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None, mesh=None):
        super().__init__()
        self._mesh = mesh or get_mesh()
        self._axis = _mp_axis(self._mesh)
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.is_mp = self._mesh.shape[self._axis] > 1
        weight_attr = ParamAttr._to_attr(weight_attr)
        if weight_attr is False:
            raise ValueError("weight_attr=False: the weight is mandatory")
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr)
        shard_tensor(self.weight, self._mesh, spec=P(None, self._axis))
        # reference parity (mp_layers.py:282 "if has_bias:"): the default
        # None is falsy — no bias unless explicitly requested
        if has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
            shard_tensor(self.bias, self._mesh, spec=P(self._axis))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        spec = (P(*([_U] * (out.ndim - 1) + [None]))
                if self.gather_output
                else P(*([_U] * (out.ndim - 1) + [self._axis])))
        return with_sharding_constraint(out, spec, self._mesh)


class RowParallelLinear(Layer):
    """Input-feature-sharded linear (reference: mp_layers.py:332). The
    contraction over the sharded dim yields partial sums; constraining the
    output replicated makes GSPMD emit the mp allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None,
                 mesh=None):
        super().__init__()
        self._mesh = mesh or get_mesh()
        self._axis = _mp_axis(self._mesh)
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.is_mp = self._mesh.shape[self._axis] > 1
        weight_attr = ParamAttr._to_attr(weight_attr)
        if weight_attr is False:
            raise ValueError("weight_attr=False: the weight is mandatory")
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr)
        shard_tensor(self.weight, self._mesh, spec=P(self._axis, None))
        # bias is added after the reduction → replicated (reference adds it
        # on the full output too)
        self.bias = None if has_bias is False else self.create_parameter(
            shape=[out_features], is_bias=True)

    def forward(self, x):
        if self.input_is_parallel:
            x = with_sharding_constraint(
                x, P(*([_U] * (x.ndim - 1) + [self._axis])), self._mesh)
        out = F.linear(x, self.weight, None)
        out = with_sharding_constraint(
            out, P(*([_U] * (out.ndim - 1) + [None])), self._mesh)
        if self.bias is not None:
            out = out + self.bias
        return out


class VocabParallelEmbedding(Layer):
    """Vocab-sharded embedding table (reference: mp_layers.py:35). The
    gather over a vocab-sharded table compiles to a masked-lookup + psum
    (the reference's c_embedding kernel does the same by hand)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None, mesh=None):
        super().__init__()
        self._mesh = mesh or get_mesh()
        self._axis = _mp_axis(self._mesh)
        weight_attr = ParamAttr._to_attr(weight_attr)
        if weight_attr is False:
            raise ValueError("weight_attr=False: the embedding table is "
                             "mandatory")
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal() if (
                weight_attr is None or weight_attr.initializer is None)
            else None)
        shard_tensor(self.weight, self._mesh, spec=P(self._axis, None))

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return with_sharding_constraint(
            out, P(*([_U] * (out.ndim - 1) + [None])), self._mesh)


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax cross-entropy (reference: mp_layers.py:498 →
    ``c_softmax_with_cross_entropy``). Constraining the logits vocab-sharded
    makes the log-softmax reductions compile into mp-axis collectives — the
    full logits row is never replicated."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100,
                 mesh=None):
        super().__init__()
        self._mesh = mesh or get_mesh()
        self._axis = _mp_axis(self._mesh)
        self._ignore_index = ignore_index

    def forward(self, input, label):
        logits = with_sharding_constraint(
            input, P(*([_U] * (input.ndim - 1) + [self._axis])),
            self._mesh)
        loss = F.cross_entropy(logits, label,
                               ignore_index=self._ignore_index,
                               reduction="none")
        # reference keeps the label's trailing-1 dim (mp_ops.py:399)
        from paddle_tpu import ops
        return ops.unsqueeze(loss, -1)
