"""paddle.distributed parity namespace, TPU-native.

Reference surface: ``python/paddle/distributed/`` (SURVEY.md §2.4/§2.5).
Design (SURVEY.md §7): parallelism is sharding annotation over a named
device mesh — collectives compile into XLA programs over ICI/DCN instead of
runtime NCCL calls; per-rank semantics live inside :func:`spmd` regions.
"""
from .mesh import (  # noqa: F401
    init_mesh, get_mesh, set_mesh, mesh_scope, ProcessMesh,
)
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, all_reduce, all_gather,
    all_gather_object, reduce, reduce_scatter, broadcast, all_to_all,
    scatter, send, recv, barrier, p2p_shift, spmd, shard_map, P,
)
from .sharding_api import (  # noqa: F401
    Shard, Replicate, Partial, shard_tensor, reshard, named_sharding,
    spec_of, with_sharding_constraint,
)
from .topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
)
from .parallel import DataParallel  # noqa: F401
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from .sharding import (  # noqa: F401
    group_sharded_parallel, save_group_sharded_model,
)
from .tcp_store import TCPStore  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import Engine, Strategy  # noqa: F401
from . import rpc  # noqa: F401
from .fleet.utils import recompute  # noqa: F401
from . import launch  # noqa: F401
from .communication import stream  # noqa: F401
from .compat import (  # noqa: F401
    P2POp, batch_isend_irecv, broadcast_object_list, destroy_process_group,
    gather, get_backend, irecv, is_initialized, isend, scatter_object_list,
    spawn, split, wait,
)
