"""Device mesh construction and the global default mesh.

TPU-native replacement for the reference's 4-D communicator topology
(``python/paddle/distributed/fleet/base/topology.py:54`` CommunicateTopology
building NCCL groups per axis): on TPU the mesh IS the communicator — XLA
compiles collectives onto ICI along mesh axes, so "creating a process group
per axis" becomes "naming a mesh axis".

Canonical axis names (SURVEY.md §7): ``dp`` (data), ``pp`` (pipeline),
``sharding`` (ZeRO), ``mp`` (tensor/model), ``sp`` (sequence/context).
"""
from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["init_mesh", "get_mesh", "set_mesh", "mesh_scope", "ProcessMesh",
           "DEFAULT_AXES"]

DEFAULT_AXES = ("dp", "pp", "sharding", "mp", "sp")

_state = {"mesh": None}


def init_mesh(shape: Optional[Dict[str, int]] = None, devices=None):
    """Build a ``jax.sharding.Mesh`` over the available devices.

    ``shape`` maps axis name -> size, e.g. ``{"dp": 2, "mp": 4}``; axes
    must multiply to the device count. With no shape, all devices go on
    ``dp`` (pure data parallelism).
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if shape is None:
        shape = {"dp": n}
    sizes = list(shape.values())
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"mesh shape {shape} does not cover {n} devices")
    arr = np.array(devices).reshape(sizes)
    mesh = Mesh(arr, tuple(shape.keys()))
    _state["mesh"] = mesh
    return mesh


def get_mesh():
    """The current default mesh (None until init_mesh/set_mesh)."""
    return _state["mesh"]


def set_mesh(mesh):
    _state["mesh"] = mesh
    return mesh


@contextlib.contextmanager
def mesh_scope(mesh):
    prev = _state["mesh"]
    _state["mesh"] = mesh
    try:
        yield mesh
    finally:
        _state["mesh"] = prev


class ProcessMesh:
    """Auto-parallel style mesh descriptor (reference:
    ``python/paddle/distributed/auto_parallel/process_mesh.py``): an N-D
    array of global ranks plus dim names, convertible to a jax Mesh."""

    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 process_ids=None):
        if process_ids is not None:
            # newer-paddle convention: `mesh` is the shape, process_ids the
            # flattened rank assignment
            self._array = np.asarray(process_ids).reshape(list(mesh))
        else:
            self._array = np.asarray(mesh)
        self._dim_names = list(dim_names) if dim_names is not None else \
            [f"d{i}" for i in range(self._array.ndim)]

    @property
    def shape(self):
        return list(self._array.shape)

    @property
    def ndim(self):
        return self._array.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return [int(r) for r in self._array.flatten()]

    def get_dim_size(self, name):
        return self._array.shape[self._dim_names.index(name)]

    def to_jax(self):
        """Materialize as a jax Mesh (ranks index jax.devices())."""
        import jax
        from jax.sharding import Mesh
        devs = np.array(jax.devices())[self._array]
        return Mesh(devs, tuple(self._dim_names))

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            np.array_equal(self._array, other._array) and \
            self._dim_names == other._dim_names

    def __hash__(self):
        return hash((self._array.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dims={self._dim_names})"
