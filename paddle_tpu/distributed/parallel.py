"""Data parallelism.

Parity with the reference's dygraph ``DataParallel``
(``python/paddle/distributed/parallel.py:200``: broadcast params, register
EagerReducer bucketing, fused allreduce of grads overlapping backward).
TPU-native redesign: none of that machinery exists as runtime code — the
wrapper annotates the batch as sharded on the mesh's ``dp`` axis and leaves
params replicated; XLA's GSPMD then emits a single fused gradient
all-reduce (reduce-scatter/all-gather under ZeRO) scheduled to overlap the
backward automatically. The EagerReducer (reducer.cc:775)'s entire job —
bucketing, ready-counting, comm-stream overlap — is the compiler's.
"""
from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec as P

from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn.layer_base import Layer
from .mesh import get_mesh
from .sharding_api import shard_tensor

__all__ = ["DataParallel"]


class DataParallel(Layer):
    """Wrap a model for data-parallel training.

    Eager forward simply delegates (a global batch is already the whole
    computation); the wrapper's contract is with ``jit.TrainStep``: it
    exposes ``batch_spec`` so the compiled step shards every batch leaf on
    ``dp`` and keeps parameters replicated.
    """

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, mesh=None, batch_axis: str = "dp"):
        super().__init__()
        self._layers = layers
        self._mesh = mesh or get_mesh()
        self._batch_axis = batch_axis
        if self._mesh is not None and batch_axis in self._mesh.axis_names:
            # params replicated across dp (the reference broadcasts from
            # rank 0 at wrap time; device_put with a replicated spec is the
            # same synchronization)
            for p in layers.parameters():
                if getattr(p, "_sharding_spec", None) is None:
                    shard_tensor(p, self._mesh, spec=P())

    @property
    def batch_spec(self):
        return P(self._batch_axis)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # delegate the Layer surface to the wrapped model
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        """Reference API parity: grads are averaged by GSPMD's psum-of-mean
        already, so loss scaling is the identity here."""
        return loss

    def apply_collective_grads(self):
        """Reference API parity no-op: the compiled step's gradient
        all-reduce replaces the EagerReducer flush."""
        return None
