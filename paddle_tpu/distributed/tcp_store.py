"""TCPStore — Python surface over the native C++ store.

Parity with ``paddle.distributed.TCPStore`` (reference C++:
``paddle/phi/core/distributed/store/tcp_store.cc``; Python binding in
``parallel.py:1090`` rendezvous). The implementation is the C++ server in
``native/tcp_store.cpp`` compiled on first use (g++ -O2 -shared, cached
under ``native/build/``) and driven through ctypes — the framework's
runtime networking is native code, per the reference's architecture.
"""
from __future__ import annotations

import ctypes
import os
import socket
import subprocess
import threading
import time
from typing import Optional

__all__ = ["TCPStore", "barrier_via_store"]

_lib_lock = threading.Lock()
_lib = None


def _native_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native")


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_native_dir(), "tcp_store.cpp")
        build = os.path.join(_native_dir(), "build")
        os.makedirs(build, exist_ok=True)
        so = os.path.join(build, "libtcp_store.so")

        def compile_so():
            tmp = so + f".tmp{os.getpid()}"
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                 src, "-o", tmp],
                check=True, capture_output=True)
            os.replace(tmp, so)

        if not os.path.exists(so) or \
                os.path.getmtime(so) < os.path.getmtime(src):
            compile_so()
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # a prebuilt .so from another toolchain (e.g. newer glibc)
            # dlopen-fails even though it is up to date — rebuild against
            # THIS host and retry; raise only if the fresh build fails too
            compile_so()
            lib = ctypes.CDLL(so)
        lib.tcp_store_server_start.restype = ctypes.c_void_p
        lib.tcp_store_server_start.argtypes = [
            ctypes.c_uint16, ctypes.POINTER(ctypes.c_uint16)]
        lib.tcp_store_server_stop.argtypes = [ctypes.c_void_p]
        lib.tcp_store_connect.restype = ctypes.c_int
        lib.tcp_store_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
        lib.tcp_store_close.argtypes = [ctypes.c_int]
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.tcp_store_set.restype = ctypes.c_int64
        lib.tcp_store_set.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                      ctypes.c_uint32, ctypes.c_char_p,
                                      ctypes.c_uint32]
        lib.tcp_store_get.restype = ctypes.c_int64
        lib.tcp_store_get.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                      ctypes.c_uint32, ctypes.c_char_p,
                                      ctypes.c_uint32, u32p]
        lib.tcp_store_add.restype = ctypes.c_int64
        lib.tcp_store_add.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                      ctypes.c_uint32, ctypes.c_int64]
        lib.tcp_store_wait.restype = ctypes.c_int64
        lib.tcp_store_wait.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                       ctypes.c_uint32, ctypes.c_int64,
                                       ctypes.c_char_p, ctypes.c_uint32,
                                       u32p]
        lib.tcp_store_delete.restype = ctypes.c_int64
        lib.tcp_store_delete.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                         ctypes.c_uint32]
        lib.tcp_store_delete_prefix.restype = ctypes.c_int64
        lib.tcp_store_delete_prefix.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32]
        lib.tcp_store_ping.restype = ctypes.c_int64
        lib.tcp_store_ping.argtypes = [ctypes.c_int]
        _lib = lib
        return lib


class TCPStore:
    """paddle.distributed.TCPStore parity: the master hosts the table,
    everyone (master included) talks to it over a client socket.

    Thread-safe: each Python thread gets its own connection (a single shared
    socket would interleave request bytes — ctypes releases the GIL during
    the native call — and a blocking ``wait`` would starve heartbeats).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 300.0):
        lib = _load_lib()
        self._lib = lib
        self._server = None
        self.host = host
        self.world_size = world_size
        self._local = threading.local()
        self._fds_lock = threading.Lock()
        self._fds: dict = {}  # thread ident -> fd
        if is_master:
            out_port = ctypes.c_uint16(0)
            self._server = lib.tcp_store_server_start(
                ctypes.c_uint16(port), ctypes.byref(out_port))
            if not self._server:
                raise RuntimeError(f"failed to bind TCPStore on port {port}")
            port = out_port.value
        self.port = port
        deadline = time.monotonic() + timeout
        while True:
            try:
                fd = self._connect()
                break
            except ConnectionError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"could not reach TCPStore at {host}:{port}")
                time.sleep(0.05)
        if lib.tcp_store_ping(fd) != 0:
            raise RuntimeError("TCPStore ping failed")

    def _connect(self) -> int:
        # the native client takes numeric IPv4 only (inet_pton); resolve
        # hostnames here so master='node0.cluster:port' works
        try:
            ip = socket.gethostbyname(self.host)
        except OSError:
            ip = self.host
        fd = self._lib.tcp_store_connect(ip.encode(),
                                         ctypes.c_uint16(self.port))
        if fd < 0:
            raise ConnectionError(
                f"could not reach TCPStore at {self.host}:{self.port}")
        self._local.fd = fd
        with self._fds_lock:
            # reap connections whose owning thread has exited, so churning
            # threads (elastic restarts, loader workers) don't leak sockets
            live = {t.ident for t in threading.enumerate()}
            for ident in [i for i in self._fds if i not in live]:
                self._lib.tcp_store_close(self._fds.pop(ident))
            # thread idents are reused: a fresh thread with a dead thread's
            # ident must not silently drop (leak) the old socket
            prev = self._fds.get(threading.get_ident())
            if prev is not None:
                self._lib.tcp_store_close(prev)
            self._fds[threading.get_ident()] = fd
        return fd

    @property
    def _fd(self) -> int:
        fd = getattr(self._local, "fd", None)
        if fd is None:
            fd = self._connect()
        return fd

    # -- KV API ---------------------------------------------------------------
    def set(self, key: str, value) -> None:
        v = value if isinstance(value, bytes) else str(value).encode()
        k = key.encode()
        if self._lib.tcp_store_set(self._fd, k, len(k), v, len(v)) != 0:
            raise RuntimeError("TCPStore set failed")

    def get(self, key: str) -> Optional[bytes]:
        k = key.encode()
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = ctypes.c_uint32(0)
            status = self._lib.tcp_store_get(self._fd, k, len(k), buf,
                                             cap, ctypes.byref(n))
            if status == -1:
                return None
            if status < -1:
                raise RuntimeError("TCPStore get failed")
            if n.value <= cap:
                return buf.raw[: n.value]
            cap = n.value  # value larger than the buffer: refetch full size

    def add(self, key: str, amount: int = 1) -> int:
        k = key.encode()
        res = self._lib.tcp_store_add(self._fd, k, len(k), int(amount))
        if res <= -1000:
            raise RuntimeError("TCPStore add failed")
        return int(res)

    def wait(self, key: str, timeout: Optional[float] = None) -> bytes:
        """Block until the key exists; returns its value. Raises
        TimeoutError after ``timeout`` seconds (None = wait forever)."""
        k = key.encode()
        deadline = None if timeout is None else \
            time.monotonic() + timeout
        cap = 1 << 16
        while True:
            tmo = -1 if deadline is None else \
                max(0, int((deadline - time.monotonic()) * 1000))
            buf = ctypes.create_string_buffer(cap)
            n = ctypes.c_uint32(0)
            status = self._lib.tcp_store_wait(self._fd, k, len(k), tmo, buf,
                                              cap, ctypes.byref(n))
            if status == -3:
                raise TimeoutError(
                    f"TCPStore wait('{key}') timed out after {timeout}s")
            if status != 0:
                raise RuntimeError("TCPStore wait failed")
            if n.value <= cap:
                return buf.raw[: n.value]
            big = self.get(key)  # value larger than buffer: refetch in full
            if big is not None:
                return big
            # key deleted between wait and refetch — wait again

    def delete_key(self, key: str) -> bool:
        k = key.encode()
        return self._lib.tcp_store_delete(self._fd, k, len(k)) > 0

    def delete_prefix(self, prefix: str) -> int:
        """Erase every key starting with ``prefix``; returns the count."""
        k = prefix.encode()
        res = self._lib.tcp_store_delete_prefix(self._fd, k, len(k))
        if res <= -1000:
            raise RuntimeError("TCPStore delete_prefix failed")
        return int(res)

    def __del__(self):
        try:
            for fd in getattr(self, "_fds", {}).values():
                self._lib.tcp_store_close(fd)
            if getattr(self, "_server", None):
                self._lib.tcp_store_server_stop(self._server)
        except Exception:
            pass


def barrier_via_store(store: TCPStore, name: str, world_size: int) -> None:
    """Reference-pattern store barrier: everyone increments, then waits for
    the count to reach world_size (parallel.py's init barrier).

    Keys are namespaced by the elastic restart epoch (PADDLE_RESTART_EPOCH,
    injected by the launcher), so trainers restarted after a failure can
    never fall through a previous attempt's stale done-key."""
    epoch = os.environ.get("PADDLE_RESTART_EPOCH", "0")
    arrived = store.add(f"__barrier/{epoch}/{name}", 1)
    if arrived == world_size:
        store.set(f"__barrier/{epoch}/{name}/done", b"1")
    store.wait(f"__barrier/{epoch}/{name}/done")


_job_store_cache: dict = {}


def job_store(timeout: float = 300.0) -> TCPStore:
    """Cached client connection to the JOB's TCPStore — the one the
    launcher started and advertised via PADDLE_MASTER/PADDLE_STORE_PORT
    (fallback: MASTER_ADDR/MASTER_PORT). This is the DCN-side control
    plane the object collectives and elastic manager ride."""
    master = os.environ.get("PADDLE_MASTER") or os.environ.get(
        "MASTER_ADDR")
    if not master:
        raise RuntimeError(
            "no job store advertised: start workers via "
            "`python -m paddle_tpu.distributed.launch` (sets "
            "PADDLE_MASTER/PADDLE_STORE_PORT) or export MASTER_ADDR")
    host = master.split(":")[0]
    port = os.environ.get("PADDLE_STORE_PORT")
    if not port:
        port = (master.split(":")[1] if ":" in master
                else os.environ.get("MASTER_PORT", "8476"))
    key = (host, int(port))
    if key not in _job_store_cache:
        _job_store_cache[key] = TCPStore(host, int(port), is_master=False,
                                         timeout=timeout)
    return _job_store_cache[key]


def free_port(host: str = "127.0.0.1") -> int:
    """Pick an ephemeral port on ``host`` (bind :0, read, close). Shared by
    coordinator/endpoint negotiation; the close-then-rebind window is
    accepted (same pattern as the rpc endpoint exchange)."""
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port
