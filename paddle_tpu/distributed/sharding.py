"""ZeRO / group-sharded data parallelism.

Parity with the reference's sharding stack (``python/paddle/distributed/
sharding/group_sharded.py:37`` ``group_sharded_parallel(level='os'|'os_g'|
'p_g_os')`` → DygraphShardingOptimizer (stage 1), GroupShardedStage2/3).

TPU-native redesign: ZeRO is a *placement policy*, not runtime machinery —
  stage 1 ('os'):    optimizer accumulators shard dim 0 on the ``sharding``
                     axis (the reference colors params per rank; GSPMD
                     shards every state tensor instead).
  stage 2 ('os_g'):  + gradients materialize sharded: XLA turns the grad
                     all-reduce into reduce-scatter + all-gather pairs and
                     keeps the scattered form for the update (the
                     comm-overlap the reference hand-codes in stage2's
                     reduce hooks).
  stage 3 ('p_g_os'): + parameters themselves shard dim 0; forward
                     all-gathers weights just-in-time (the reference's
                     re-gather-on-forward in group_sharded_stage3.py).
All three fall out of sharding specs consumed by ``jit.TrainStep``.
"""
from __future__ import annotations

from typing import Optional

from jax.sharding import PartitionSpec as P

from paddle_tpu.nn.layer_base import Layer
from .mesh import get_mesh
from .sharding_api import shard_tensor

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]

_LEVELS = ("os", "os_g", "p_g_os")


def _shardable(shape, n) -> bool:
    return len(shape) > 0 and shape[0] % n == 0 and shape[0] >= n


def group_sharded_parallel(model: Layer, optimizer, level: str = "p_g_os",
                           scaler=None, group=None, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, mesh=None,
                           axis: str = "sharding"):
    """Reference: sharding/group_sharded.py:37. Returns
    (model, optimizer, scaler) with sharding annotations installed."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {_LEVELS}, got {level!r}")
    mesh = mesh or get_mesh()
    if mesh is None or axis not in mesh.axis_names:
        raise RuntimeError(
            f"group_sharded_parallel needs a mesh with a {axis!r} axis")
    n = mesh.shape[axis]

    # stage >=1: tell the compiled step to shard optimizer accumulators
    optimizer._shard_states_axis = axis
    optimizer._shard_states_mesh = mesh

    if level == "p_g_os" and n > 1:
        for p in model.parameters():
            if getattr(p, "_sharding_spec", None) is None and \
                    _shardable(p.shape, n):
                spec = P(*([axis] + [None] * (len(p.shape) - 1)))
                shard_tensor(p, mesh, spec=spec)
    return model, optimizer, scaler


def save_group_sharded_model(model: Layer, output: str, optimizer=None):
    """Reference: group_sharded.py:179 — checkpoints are full logical
    arrays here (framework/io.py gathers on host), so this is plain save."""
    import os
    from paddle_tpu.framework.io import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
