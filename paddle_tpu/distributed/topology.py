"""4-D hybrid-parallel topology bookkeeping.

Parity with ``python/paddle/distributed/fleet/base/topology.py``:
``CommunicateTopology`` (rank <-> coordinate math over the axis order
[data, pipe, sharding, model]) and ``HybridCommunicateGroup`` (per-axis
communicators + pipeline prev/next). On TPU the "NCCL group per axis"
becomes a named mesh axis; the coordinate arithmetic is kept verbatim in
spirit because launchers, checkpoint resharding, and log labeling still
need rank math.
"""
from __future__ import annotations

from itertools import product
from typing import Dict, List, Sequence

import numpy as np

from .collective import Group
from .mesh import get_mesh, init_mesh

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names: Sequence[str] = ("data", "pipe",
                                                            "sharding",
                                                            "model"),
                 dims: Sequence[int] = (1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(product(*[range(d) for d in self._dims]))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        """All ranks whose coordinate on ``axis_name`` equals ``index``."""
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items()
                      if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """Rank groups that communicate along ``axis_name`` (all coords of
        the other axes, varying this one) — the reference's NCCL group list,
        here the mesh-axis peer sets."""
        axis = self._parallel_names.index(axis_name)
        other = [i for i in range(len(self._dims)) if i != axis]
        groups = []
        for other_coord in product(*[range(self._dims[i]) for i in other]):
            ranks = []
            for k in range(self._dims[axis]):
                coord = list(other_coord)
                coord.insert(axis, k)
                ranks.append(self._coord2rank[tuple(coord)])
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = list(self.get_coord(global_rank))
        for name, v in kwargs.items():
            coord[self._parallel_names.index(name)] = v
        return self._coord2rank[tuple(coord)]


_AXIS_ALIAS = {"data": "dp", "pipe": "pp", "sharding": "sharding",
               "model": "mp", "sep": "sp"}


class HybridCommunicateGroup:
    """Reference: topology.py:140 — materializes per-axis communicators.

    TPU version: ensures the default mesh matches the topology's shape and
    hands out :class:`Group` objects naming mesh axes instead of NCCL
    communicators.
    """

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.nranks = topology.world_size()
        self.global_rank = 0  # single-controller SPMD
        names = topology.get_hybrid_group_names()
        self._axis_of = {n: _AXIS_ALIAS.get(n, n) for n in names}
        self._dp_degree = self._deg("data")
        self._pp_degree = self._deg("pipe")
        self._sharding_degree = self._deg("sharding")
        self._mp_degree = self._deg("model")
        mesh = get_mesh()
        shape = {self._axis_of[n]: topology.get_dim(n) for n in names}
        if mesh is None or dict(zip(mesh.axis_names,
                                    [mesh.shape[a] for a in mesh.axis_names])
                                ) != shape:
            init_mesh(shape)

    def _deg(self, name):
        try:
            return self._topo.get_dim(name)
        except ValueError:
            return 1

    # --- degree / rank queries (reference API surface) ---
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    # --- communicators ---
    def get_data_parallel_group(self) -> Group:
        return Group(("dp",))

    def get_model_parallel_group(self) -> Group:
        return Group(("mp",))

    def get_pipe_parallel_group(self) -> Group:
        return Group(("pp",))

    def get_sharding_parallel_group(self) -> Group:
        return Group(("sharding",))

    def get_check_parallel_group(self) -> Group:
        return Group(tuple(get_mesh().axis_names))

    def topology(self) -> CommunicateTopology:
        return self._topo
