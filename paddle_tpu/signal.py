"""paddle.signal parity (reference: ``python/paddle/signal.py`` —
frame / overlap_add / stft / istft over the phi frame+fft kernels).

TPU-native: framing is a gather with a static index matrix, overlap-add a
segment-sum — both single fused tape nodes; stft/istft compose them with
:mod:`paddle_tpu.fft`. Output layout matches paddle:
stft -> [..., n_fft//2+1 (or n_fft), n_frames].
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length: int, hop_length: int, axis: int = -1, name=None):
    """Slice overlapping frames (reference: signal.py:31).

    ``axis=-1``: [..., T] -> [..., frame_length, n_frames];
    ``axis=0``:  [T, ...] -> [n_frames, frame_length, ...].
    """
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1 (reference frame contract)")

    def f(a):
        T = a.shape[0] if axis == 0 else a.shape[-1]
        if frame_length > T:
            raise ValueError(
                f"frame_length ({frame_length}) > signal length ({T})")
        n = 1 + (T - frame_length) // hop_length
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(frame_length)[None, :])  # [n, frame_length]
        if axis == 0:
            return a[idx]                            # [n, frame_length, ...]
        out = a[..., idx]                            # [..., n, frame_length]
        return jnp.swapaxes(out, -1, -2)             # [..., frame_length, n]
    return apply_op(f, x, op_name="frame")


def overlap_add(x, hop_length: int, axis: int = -1, name=None):
    """Inverse of frame (reference: signal.py:151).

    ``axis=-1``: [..., frame_length, n_frames] -> [..., T];
    ``axis=0``:  [n_frames, frame_length, ...] -> [T, ...].
    """
    if axis not in (0, -1):
        raise ValueError("axis must be 0 or -1 (reference contract)")

    def f(a):
        if axis == 0:
            n, fl = a.shape[0], a.shape[1]
            T = (n - 1) * hop_length + fl
            pos = (jnp.arange(n)[:, None] * hop_length
                   + jnp.arange(fl)[None, :]).reshape(-1)
            flat = a.reshape((n * fl,) + a.shape[2:])
            out = jnp.zeros((T,) + a.shape[2:], a.dtype)
            return out.at[pos].add(flat)
        fl, n = a.shape[-2], a.shape[-1]
        T = (n - 1) * hop_length + fl
        frames = jnp.swapaxes(a, -1, -2)  # [..., n, fl]
        pos = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(fl)[None, :]).reshape(-1)  # [n*fl]
        flat = frames.reshape(a.shape[:-2] + (n * fl,))
        out = jnp.zeros(a.shape[:-2] + (T,), a.dtype)
        return out.at[..., pos].add(flat)
    return apply_op(f, x, op_name="overlap_add")


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None):
    """Reference: signal.py:236. Returns a complex Tensor
    [..., freq, n_frames]."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    x_data = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    if onesided and jnp.iscomplexobj(x_data):
        raise ValueError(
            "stft: onesided is not supported for complex input (reference "
            "signal.py contract); pass onesided=False")
    if window is not None:
        w = window.data if isinstance(window, Tensor) else jnp.asarray(window)
        if w.shape[0] < n_fft:  # center-pad to n_fft like paddle
            lpad = (n_fft - w.shape[0]) // 2
            w = jnp.pad(w, (lpad, n_fft - w.shape[0] - lpad))
    else:
        w = jnp.ones(n_fft, jnp.float32)

    def f(a, win):
        arr = a
        if center:
            pad = n_fft // 2
            cfg = [(0, 0)] * (arr.ndim - 1) + [(pad, pad)]
            arr = jnp.pad(arr, cfg, mode=pad_mode)
        T = arr.shape[-1]
        n = 1 + (T - n_fft) // hop_length
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :])
        seg = arr[..., idx] * win  # [..., n, n_fft]
        if onesided and not jnp.iscomplexobj(seg):
            spec = jnp.fft.rfft(seg, axis=-1, norm="ortho" if normalized
                                else "backward")
        else:
            spec = jnp.fft.fft(seg, axis=-1, norm="ortho" if normalized
                               else "backward")
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, n]
    return apply_op(f, x, w, op_name="stft")


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False,
          name=None):
    """Reference: signal.py:403 — window-weighted overlap-add inverse with
    NOLA normalization."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        w = window.data if isinstance(window, Tensor) else jnp.asarray(window)
        if w.shape[0] < n_fft:
            lpad = (n_fft - w.shape[0]) // 2
            w = jnp.pad(w, (lpad, n_fft - w.shape[0] - lpad))
    else:
        w = jnp.ones(n_fft, jnp.float32)

    def f(a, win):
        spec = jnp.swapaxes(a, -1, -2)  # [..., n, freq]
        if onesided:
            seg = jnp.fft.irfft(spec, n=n_fft, axis=-1,
                                norm="ortho" if normalized else "backward")
        else:
            seg = jnp.fft.ifft(spec, axis=-1,
                               norm="ortho" if normalized else "backward")
            if not return_complex:
                seg = seg.real
        seg = seg * win
        n = seg.shape[-2]
        T = (n - 1) * hop_length + n_fft
        pos = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :]).reshape(-1)
        flat = seg.reshape(seg.shape[:-2] + (n * n_fft,))
        out = jnp.zeros(seg.shape[:-2] + (T,), seg.dtype)
        out = out.at[..., pos].add(flat)
        # NOLA normalization: divide by the summed squared window
        wsq = (win * win)[None, :] * jnp.ones((n, 1), win.dtype)
        wsum = jnp.zeros(T, win.dtype).at[pos].add(wsq.reshape(-1))
        out = out / jnp.maximum(wsum, 1e-11)
        if center:
            pad = n_fft // 2
            out = out[..., pad:T - pad]
        if length is not None:
            out = out[..., :length]
        return out
    return apply_op(f, x, w, op_name="istft")
