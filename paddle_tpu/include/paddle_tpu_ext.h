// paddle_tpu custom-op extension header — the analog of
// paddle/extension.h for this framework's host-callback custom-op seam
// (see python/paddle/utils/cpp_extension in the reference, and
// paddle_tpu/utils/cpp_extension.py here for the loading side).
//
// A custom op exports one C function with this signature; the optional
// gradient exports `<name>_grad` with the same signature, receiving
// inputs + output cotangents and writing one gradient per forward input.
#ifndef PADDLE_TPU_EXT_H_
#define PADDLE_TPU_EXT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// ins/outs: flat float32 buffers; *_shapes[i] points at in/out i's dims;
// *_ndims[i] gives its rank. Output buffers are pre-allocated by the
// framework from the shapes the Python registration declared.
typedef void (*paddle_tpu_op_fn)(
    const float** ins, const int64_t** in_shapes, const int32_t* in_ndims,
    int32_t n_in, float** outs, const int64_t** out_shapes,
    const int32_t* out_ndims, int32_t n_out);

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // PADDLE_TPU_EXT_H_
