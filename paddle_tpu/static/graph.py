"""Static-graph facade: Program / Executor / program_guard / data.

Reference: ``python/paddle/fluid/framework.py`` (Program:5384,
Variable:1447) + ``executor.py:1394`` Executor.run — the protobuf Program
IR interpreted by InterpreterCore.

TPU-native redesign (SURVEY.md §7 step 4): there is no separate op-desc
IR. Building the "Program" RUNS the ops once on placeholder values, which
records the framework's tape; ``Executor.run`` replays that tape as one
pure jax function of (feeds, parameters) — jit-compiled and cached per
feed signature, so steady-state ``run`` is a single XLA executable, which
is InterpreterCore's whole job done by the compiler. ``minimize`` hangs
the optimizer on the program; ``run`` then also computes grads (jax.grad
of the replay) and applies the update rule.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import autograd as _ag
from paddle_tpu.core.dtype import convert_dtype
from paddle_tpu.core.tensor import Tensor

__all__ = ["Program", "Executor", "program_guard", "data",
           "default_main_program", "default_startup_program",
           "global_scope"]


_token_counter = [0]
# slotted/unsettable objects can't carry the token attribute; key them by
# id() in a side table with a GC finalizer evicting the entry, so a dead
# object's reused id() can never alias its token (and, unlike a
# WeakKeyDictionary, value-equal distinct objects never share a token)
_token_side_table: dict = {}


def _cache_token(obj) -> int:
    """Monotonic identity token, assigned on first use and pinned to the
    object (unlike id(), never reused after GC). None -> 0."""
    if obj is None:
        return 0
    tok = getattr(obj, "_exe_cache_token", None)
    if tok is None:
        _token_counter[0] += 1
        tok = _token_counter[0]
        try:
            object.__setattr__(obj, "_exe_cache_token", tok)
        except (AttributeError, TypeError):
            key = id(obj)
            if key in _token_side_table:
                return _token_side_table[key]
            import weakref
            try:
                weakref.finalize(obj, _token_side_table.pop, key, None)
            except TypeError:
                # unweakrefable AND unsettable: id+type — narrow residual
                # aliasing window only for such exotic objects
                return hash((type(obj).__qualname__, id(obj)))
            _token_side_table[key] = tok
    return tok


class Program:
    """Holds the placeholders, fetch targets, and optimizer attached
    while this program was the default (reference Program surface)."""

    def __init__(self):
        self.feeds: Dict[str, Tensor] = {}
        self.optimizer = None
        self.loss: Optional[Tensor] = None
        self._replay_cache = {}

    def clone(self, for_test: bool = False):
        return self

    def global_block(self):
        return self


_default_main = Program()
_default_startup = Program()
_stack: List[Program] = []


def default_main_program() -> Program:
    return _stack[-1] if _stack else _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    """Reference: static.program_guard context manager."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program

    def __enter__(self):
        _stack.append(self.main)
        return self.main

    def __exit__(self, *exc):
        _stack.pop()
        return False


def data(name: str, shape: Sequence[Optional[int]], dtype="float32",
         lod_level: int = 0) -> Tensor:
    """Declare a feed placeholder (reference: static/input.py data).

    The placeholder carries a concrete dummy array (None/-1 dims become
    1) so graph construction can execute eagerly and record the tape; the
    executor substitutes the fed value at replay time.
    """
    dt = convert_dtype(dtype)
    concrete = tuple(1 if (s is None or int(s) < 0) else int(s)
                     for s in shape)
    # stop_gradient=False so every op consuming the placeholder records a
    # tape node even in parameter-free graphs (the replay IS the Program);
    # minimize() only collects Parameter instances, so feeds are never
    # promoted to trainables
    t = Tensor(jnp.zeros(concrete, dt.np_dtype), stop_gradient=False,
               name=name)
    default_main_program().feeds[name] = t
    return t


class _Scope:
    def find_var(self, name):
        return None


_scope = _Scope()


def global_scope():
    return _scope


class Executor:
    """Reference: executor.py Executor — here a tape-replay jit runner."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, np.ndarray]] = None,
            fetch_list: Optional[Sequence[Tensor]] = None,
            return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if program is _default_startup or (not fetch_list
                                           and program.loss is None):
            return []  # startup program: params are already initialized

        placeholders = [program.feeds[n] for n in sorted(program.feeds)]
        feed_vals = []
        for n in sorted(program.feeds):
            if n not in feed:
                raise ValueError(f"missing feed '{n}'")
            feed_vals.append(jnp.asarray(feed[n]))

        opt = program.optimizer
        params = list(opt._parameter_list) if opt is not None else []
        # identity comparison on purpose: Tensor.__eq__ is elementwise
        loss_in_fetch = any(t is program.loss for t in fetch_list)
        targets = fetch_list + ([program.loss]
                                if opt is not None and not loss_in_fetch
                                else [])

        # monotonic tokens, NOT id(): after GC, id() values get reused and
        # could alias cache entries across different objects. The opt token
        # also keys attaching an optimizer after an eval run (the eval
        # closure, grads=None, must not be reused for training).
        key = (_cache_token(program), _cache_token(opt),
               tuple(t.name or _cache_token(t) for t in fetch_list),
               tuple(v.shape + (str(v.dtype),) for v in feed_vals))
        cached = program._replay_cache.get(key)
        if cached is None:
            replay = _ag.make_replay_fn(targets, placeholders + params)
            n_feed = len(placeholders)

            if opt is not None:
                loss_pos = next(i for i, t in enumerate(targets)
                                if t is program.loss)

                def step(feed_arrs, param_arrs):
                    def loss_of(ps):
                        outs = replay(*feed_arrs, *ps)
                        return outs[loss_pos], outs
                    grads, outs = jax.grad(loss_of, has_aux=True)(
                        param_arrs)
                    return outs, grads
                cached = jax.jit(step)
            else:
                cached = jax.jit(lambda feed_arrs, param_arrs:
                                 (replay(*feed_arrs, *param_arrs), None))
            program._replay_cache[key] = cached

        outs, grads = cached(feed_vals,
                             [p.data for p in params])
        if opt is not None and grads is not None:
            for p, g in zip(params, grads):
                p.grad = Tensor(g, stop_gradient=True)
            opt.step()
            opt.clear_grad()
        results = outs[: len(fetch_list)]
        if return_numpy:
            return [np.asarray(r) for r in results]
        return [Tensor(r) for r in results]

    def close(self):
        pass
