"""paddle.static.nn parity — the static-graph layer helpers recipe code
uses (reference: ``python/paddle/static/nn/common.py`` fc/embedding/
batch_norm). Each helper instantiates the dygraph layer once (creating
the parameters) and applies it to the placeholder, so the op lands on the
tape that Executor.run replays."""
from __future__ import annotations

from typing import Optional

__all__ = ["fc", "embedding", "batch_norm"]


def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
       bias_attr=None, activation: Optional[str] = None, name=None):
    """Reference: static/nn/common.py fc."""
    import paddle_tpu.nn as nn
    from paddle_tpu import ops
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= int(s)
    if tuple(x.shape[num_flatten_dims:]) != (in_features,):
        # -1 for the leading (batch) dim: the placeholder's dummy batch
        # size must not be baked into the replayed reshape
        x = ops.reshape(x, [-1] + list(x.shape[1:num_flatten_dims])
                        + [in_features])
    layer = nn.Linear(in_features, size, weight_attr=weight_attr,
                      bias_attr=bias_attr)
    out = layer(x)
    if activation is not None:
        import paddle_tpu.nn.functional as F
        out = getattr(F, activation)(out)
    out._static_layer = layer  # keep the params alive with the graph
    return out


def embedding(input, size, weight_attr=None, is_sparse: bool = False,
              padding_idx=None, name=None):
    """Reference: static/nn/common.py embedding."""
    import paddle_tpu.nn as nn
    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                         weight_attr=weight_attr, sparse=is_sparse)
    out = layer(input)
    out._static_layer = layer
    return out


def batch_norm(input, momentum: float = 0.9, epsilon: float = 1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test: bool = False, name=None):
    """Reference: static/nn/common.py batch_norm."""
    import paddle_tpu.nn as nn
    ch = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    layer = nn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr)
    if is_test:
        layer.eval()
    out = layer(input)
    out._static_layer = layer
    return out
