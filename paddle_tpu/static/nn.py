"""paddle.static.nn parity — the static-graph layer helpers recipe code
uses (reference: ``python/paddle/static/nn/common.py`` fc/embedding/
batch_norm). Each helper instantiates the dygraph layer once (creating
the parameters) and applies it to the placeholder, so the op lands on the
tape that Executor.run replays."""
from __future__ import annotations

from typing import Optional

__all__ = ["fc", "embedding", "batch_norm"]


def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
       bias_attr=None, activation: Optional[str] = None, name=None):
    """Reference: static/nn/common.py fc."""
    import paddle_tpu.nn as nn
    from paddle_tpu import ops
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= int(s)
    if tuple(x.shape[num_flatten_dims:]) != (in_features,):
        # -1 for the leading (batch) dim: the placeholder's dummy batch
        # size must not be baked into the replayed reshape
        x = ops.reshape(x, [-1] + list(x.shape[1:num_flatten_dims])
                        + [in_features])
    layer = nn.Linear(in_features, size, weight_attr=weight_attr,
                      bias_attr=bias_attr)
    out = layer(x)
    if activation is not None:
        import paddle_tpu.nn.functional as F
        out = getattr(F, activation)(out)
    out._static_layer = layer  # keep the params alive with the graph
    return out


def embedding(input, size, weight_attr=None, is_sparse: bool = False,
              padding_idx=None, name=None):
    """Reference: static/nn/common.py embedding."""
    import paddle_tpu.nn as nn
    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                         weight_attr=weight_attr, sparse=is_sparse)
    out = layer(input)
    out._static_layer = layer
    return out


def batch_norm(input, momentum: float = 0.9, epsilon: float = 1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test: bool = False, name=None):
    """Reference: static/nn/common.py batch_norm."""
    import paddle_tpu.nn as nn
    ch = int(input.shape[1] if data_layout == "NCHW" else input.shape[-1])
    layer = nn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr)
    if is_test:
        layer.eval()
    out = layer(input)
    out._static_layer = layer
    return out


# ======================= control flow ======================================
# Reference: python/paddle/static/nn/control_flow.py (cond:?, While/
# while_loop, case, switch_case). The reference lowers these to
# conditional_block / while ops in the ProgramDesc; here they lower to
# lax.cond / lax.while_loop — XLA's native control flow — recorded as ONE
# tape op so both the eager tape (Executor replay) and jit traces
# (to_static / TrainStep) capture data-dependent branching.

def _unwrap_tree(x):
    from paddle_tpu.core.tensor import Tensor
    import jax
    return jax.tree_util.tree_map(
        lambda v: v.data if isinstance(v, Tensor) else v, x,
        is_leaf=lambda v: isinstance(v, Tensor))


def _closure_requires_grad(fn) -> bool:
    """True if ``fn``'s closure (recursively, incl. helper callables and
    containers) captures a trainable tensor/layer — same collector the
    trainable ``bounded_while_loop`` uses, so the forward-only guard and
    the differentiable path agree on what "captured" means."""
    return bool(_closure_tensors(fn))


def cond(pred, true_fn=None, false_fn=None, name=None, operands=()):
    """Data-dependent branch (reference: control_flow.py ``cond``).

    Both branches are traced (XLA ``lax.cond`` executes one on device).
    With no ``operands`` the branch closures may capture surrounding
    tensors (paddle's calling convention); gradients then flow through the
    captured values only under an enclosing jit trace (to_static /
    TrainStep). Passing explicit ``operands`` tapes the whole branch as
    one op, so eager backward and Executor replay differentiate/replay it
    too — prefer it for training code.
    """
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.autograd import apply_op, no_grad
    from paddle_tpu.core.tensor import Tensor

    p = pred.data if isinstance(pred, Tensor) else pred

    if not isinstance(p, jax.core.Tracer) and not operands:
        # concrete pred, closure style: dygraph semantics — just run the
        # taken branch (ops record on the tape normally). A None branch is
        # a no-op (paddle parity).
        if bool(p):
            return true_fn()
        return None if false_fn is None else false_fn()

    if false_fn is None:
        raise ValueError(
            "cond under trace (or with operands) needs BOTH branches with "
            "matching output structures — XLA compiles both; pass a "
            "false_fn that returns the same structure as true_fn")

    # branch outputs may be any pytree: flatten inside the traced branch
    # (lax.cond checks leaf shapes but NOT our python structure — capture
    # each branch's treedef and require they match), unflatten after
    struct = {}

    def f(p_arr, *ops):
        def branch(fn, tag):
            def run(op_arrays):
                wrapped = [Tensor(a) for a in op_arrays]
                with no_grad():  # inner ops must not tape: the whole
                    out = fn(*wrapped)  # cond is ONE tape node
                leaves, treedef = jax.tree_util.tree_flatten(
                    _unwrap_tree(out))
                struct[tag] = treedef
                return tuple(leaves)
            return run
        return jax.lax.cond(jnp.reshape(p_arr, ()).astype(bool),
                            branch(true_fn, "t"), branch(false_fn, "f"),
                            list(ops))

    out = apply_op(f, pred, *operands, op_name="cond")
    if struct["t"] != struct["f"]:
        raise ValueError(
            f"cond branches returned different structures: true branch "
            f"{struct['t']}, false branch {struct['f']}")
    leaves = list(out) if isinstance(out, (tuple, list)) else [out]
    return jax.tree_util.tree_unflatten(struct["t"], leaves)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Data-dependent loop (reference: control_flow.py ``while_loop``).

    Lowers to ``lax.while_loop`` recorded as one tape op. FORWARD-ONLY:
    XLA cannot reverse-differentiate an unbounded while (the reference
    builds explicit backward blocks instead); if any loop var requires
    grad this raises — use ``lax.scan``-style bounded loops (e.g.
    ``lax.scan``-based RNN layers) for trainable recurrences.
    """
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.autograd import apply_op, no_grad, is_grad_enabled
    from paddle_tpu.core.tensor import Tensor

    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("loop_vars must be a non-empty list/tuple")
    tensors = [v for v in loop_vars if isinstance(v, Tensor)]
    if is_grad_enabled() and (
            any(not t.stop_gradient for t in tensors)
            or _closure_requires_grad(cond_fn)
            or _closure_requires_grad(body_fn)):
        raise ValueError(
            "static.nn.while_loop is forward-only (XLA while has no "
            "reverse-mode) and a loop var or a tensor/layer captured by "
            "cond_fn/body_fn requires grad — its gradient would silently "
            "be zero. Detach the inputs or wrap the call in no_grad(), "
            "or use static.nn.bounded_while_loop(cond, body, vars, "
            "max_iters) which IS differentiable")

    def f(*vars_):
        def c(vs):
            out = cond_fn(*[Tensor(v) for v in vs])
            out = out.data if isinstance(out, Tensor) else out
            return jnp.reshape(out, ()).astype(bool)

        def b(vs):
            with no_grad():
                out = body_fn(*[Tensor(v) for v in vs])
            if not isinstance(out, (list, tuple)):
                out = (out,)
            return [o.data if isinstance(o, Tensor) else jnp.asarray(o)
                    for o in out]
        return tuple(jax.lax.while_loop(c, b, list(vars_)))

    with no_grad():
        out = apply_op(f, *loop_vars, op_name="while_loop")
    return list(out) if isinstance(out, tuple) else [out]


def _closure_tensors(*fns):
    """Trainable tensors captured by ``fns``'s closures / bound self —
    parameters of captured Layers and bare Tensors. Ordered, deduped."""
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.nn.layer_base import Layer

    out, seen = [], set()

    def add(t):
        if isinstance(t, Tensor) and not t.stop_gradient \
                and id(t) not in seen:
            seen.add(id(t))
            out.append(t)

    visited = set()

    def scan(obj):
        if obj is None or id(obj) in visited:
            return
        visited.add(id(obj))
        if isinstance(obj, Layer):
            for p in obj.parameters():
                add(p)
        elif isinstance(obj, Tensor):
            add(obj)
        elif isinstance(obj, (list, tuple, set)):
            for item in obj:  # layers held in a plain container
                scan(item)
        elif isinstance(obj, dict):
            for item in obj.values():
                scan(item)
        elif callable(obj):
            # recurse into helper functions the closure captures (the
            # `body = lambda h: layer(h)` indirection) — their cells may
            # hold the trainable layer
            scan(getattr(obj, "__self__", None))
            for cell in getattr(obj, "__closure__", None) or ():
                try:
                    scan(cell.cell_contents)
                except ValueError:
                    pass

    for fn in fns:
        scan(fn)
    return out


def bounded_while_loop(cond_fn, body_fn, loop_vars, max_iters: int,
                       name=None):
    """TRAINABLE data-dependent loop with a static iteration bound.

    Runs ``body_fn`` while ``cond_fn`` holds, at most ``max_iters`` times;
    iterations after the condition first fails are masked no-ops (the loop
    vars pass through unchanged), so the whole loop is a fixed-length
    ``lax.scan`` and **gradients flow** — through the loop vars AND through
    parameters/tensors captured by the closures (threaded as taped
    operands, so eager ``backward`` differentiates them too). This is the
    TPU answer to the reference's differentiable while
    (``paddle/fluid/operators/controlflow/while_op.cc:349`` WhileGradOp +
    append_backward's block construction): XLA cannot reverse an unbounded
    ``while``, but a bounded masked scan reverses exactly, and dynamic-halt
    models (loop-until-converged, adaptive computation time) are bounded in
    practice.

    If the condition still holds after ``max_iters`` iterations the loop
    truncates there (the remaining iterations are simply not run) — pick
    the bound accordingly. ``static.nn.while_loop`` stays the
    forward-only unbounded alternative.
    """
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.autograd import apply_op, no_grad
    from paddle_tpu.core.tensor import Tensor

    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("loop_vars must be a non-empty list/tuple")
    if max_iters <= 0:
        return list(loop_vars)
    n_vars = len(loop_vars)
    captured = _closure_tensors(cond_fn, body_fn)

    def f(*arrays):
        var_arrays = arrays[:n_vars]
        cap_arrays = arrays[n_vars:]
        saved = [t._data for t in captured]
        for t, a in zip(captured, cap_arrays):
            t._data = a  # closures see the traced values -> grads flow
        try:
            def eval_cond(vs):
                with no_grad():
                    out = cond_fn(*[Tensor(v) for v in vs])
                out = out.data if isinstance(out, Tensor) else out
                return jnp.reshape(out, ()).astype(bool)

            def step(carry, _):
                vs, act = carry
                with no_grad():
                    new = body_fn(*[Tensor(v) for v in vs])
                if not isinstance(new, (list, tuple)):
                    new = (new,)
                if len(new) != n_vars:
                    raise ValueError(
                        f"body_fn returned {len(new)} values for "
                        f"{n_vars} loop vars")
                new_arrays = [o.data if isinstance(o, Tensor)
                              else jnp.asarray(o) for o in new]
                vs_next = tuple(
                    jnp.where(act, nv, v)
                    for nv, v in zip(new_arrays, vs))
                return (vs_next, act & eval_cond(vs_next)), None

            (final, _), _ = jax.lax.scan(
                step, (tuple(var_arrays), eval_cond(var_arrays)), None,
                length=int(max_iters))
            return final
        finally:
            for t, a in zip(captured, saved):
                t._data = a

    out = apply_op(f, *loop_vars, *captured,
                   op_name="bounded_while_loop")
    return list(out) if isinstance(out, tuple) else [out]


def _switch_over(fns, pos_of, operand_tensors, op_name):
    """Shared ``lax.switch`` lowering: trace every branch ONCE (flat — a
    50-branch switch compiles one switch, not 50 nested conds), verify the
    branches return the same python structure, dispatch on the traced
    position computed by ``pos_of`` from the operand arrays."""
    import jax
    from paddle_tpu.core.autograd import apply_op, no_grad

    struct = {}

    def f(*arrays):
        def mk(fn, tag):
            def run(_):
                with no_grad():  # one tape node for the whole switch
                    out = fn()
                leaves, treedef = jax.tree_util.tree_flatten(
                    _unwrap_tree(out))
                struct[tag] = treedef
                return tuple(leaves)
            return run

        pos = pos_of(arrays)
        return jax.lax.switch(pos, [mk(fn, j) for j, fn in enumerate(fns)],
                              None)

    out = apply_op(f, *operand_tensors, op_name=op_name)
    first = struct[0]
    for tag, td in struct.items():
        if td != first:
            raise ValueError(
                f"{op_name} branches returned different structures: "
                f"branch 0 {first}, branch {tag} {td}")
    leaves = list(out) if isinstance(out, (tuple, list)) else [out]
    return jax.tree_util.tree_unflatten(first, leaves)


def case(pred_fn_pairs, default=None, name=None):
    """Reference: control_flow.py ``case`` — first true pred wins; with no
    ``default`` the last fn runs when nothing matches. Lowers to ONE
    ``lax.switch`` over argmax(preds + [True]) (argmax returns the FIRST
    maximum, i.e. the first true pred)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Tensor

    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    preds = [p for p, _ in pred_fn_pairs]
    fns = [fn for _, fn in pred_fn_pairs]
    pred_arrays = [p.data if isinstance(p, Tensor) else p for p in preds]
    if not any(isinstance(p, jax.core.Tracer) for p in pred_arrays):
        # concrete preds: dygraph semantics — run the taken branch on tape
        for p, fn in zip(pred_arrays, fns):
            if bool(jnp.reshape(p, ())):
                return fn()
        return (default or fns[-1])()

    # no default: the last fn doubles as the fallback WITHOUT being traced
    # twice — the no-match position simply points at it
    fns_all = fns + ([default] if default is not None else [])
    fallback = len(fns_all) - 1

    def pos_of(arrays):
        flags = jnp.stack([jnp.reshape(a, ()).astype(bool)
                           for a in arrays])
        return jnp.where(jnp.any(flags), jnp.argmax(flags),
                         fallback).astype(jnp.int32)

    return _switch_over(fns_all, pos_of, preds, "case")


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Reference: control_flow.py ``switch_case`` — keyed dispatch; with no
    ``default`` the MAX-index branch catches unmatched indices. ONE flat
    ``lax.switch``."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core.tensor import Tensor

    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = [(i, fn) if not isinstance(fn, (tuple, list)) else tuple(fn)
                 for i, fn in enumerate(branch_fns)]
        pairs = sorted(pairs)
    keys = [int(k) for k, _ in pairs]
    fns = [fn for _, fn in pairs]
    idx_arr = branch_index.data if isinstance(branch_index, Tensor) \
        else branch_index
    if not isinstance(idx_arr, jax.core.Tracer):
        i = int(jnp.reshape(idx_arr, ()))
        fn = dict(zip(keys, fns)).get(i)
        if fn is None:
            fn = default or fns[-1]  # max key (sorted) is the fallback
        return fn()

    fns_all = fns + ([default] if default is not None else [])
    fallback = len(fns_all) - 1  # explicit default, or the max-key branch
    karr = jnp.asarray(keys, jnp.int32)

    def pos_of(arrays):
        i = jnp.reshape(arrays[0], ()).astype(jnp.int32)
        match = i == karr
        return jnp.where(jnp.any(match), jnp.argmax(match),
                         fallback).astype(jnp.int32)

    return _switch_over(fns_all, pos_of, [branch_index], "switch_case")


__all__ += ["cond", "while_loop", "bounded_while_loop", "case",
            "switch_case"]
