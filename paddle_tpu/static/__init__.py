"""paddle.static parity surface.

The reference's static graph stack (Program/Block IR + executors, SURVEY.md
§2.3) collapses into trace-based capture here (SURVEY.md §7: the CINN seam →
XLA): ``paddle_tpu.jit.to_static`` is the Program builder, XLA the executor.
This module keeps the pieces user code actually touches: ``InputSpec`` and
the inference-model save/load entry points.
"""
from __future__ import annotations

from typing import Optional, Sequence

from paddle_tpu.core.dtype import convert_dtype
from .graph import (  # noqa: F401
    Executor, Program, data, default_main_program,
    default_startup_program, global_scope, program_guard,
)
from . import nn  # noqa: F401

__all__ = ["InputSpec", "save_inference_model", "load_inference_model",
           "Executor", "Program", "data", "default_main_program",
           "default_startup_program", "global_scope", "program_guard",
           "nn"]


class InputSpec:
    """Reference: ``python/paddle/static/input.py`` InputSpec."""

    def __init__(self, shape: Sequence[Optional[int]], dtype="float32",
                 name: Optional[str] = None, stop_gradient: bool = True):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def to_shape_dtype_struct(self, batch: int = 1):
        import jax
        shape = tuple(batch if s == -1 else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype.np_dtype)

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype.name, name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, "
                f"dtype={self.dtype.name}, name={self.name})")


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    raise NotImplementedError(
        "program-based save_inference_model has no analog; use "
        "paddle_tpu.jit.save(layer, path, input_spec=[...]) which exports "
        "a compiled StableHLO artifact")


def load_inference_model(path_prefix, executor=None, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.load(path) to load a jit.save artifact")
