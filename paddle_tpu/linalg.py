"""paddle.linalg namespace (reference: ``python/paddle/linalg.py`` — a
re-export surface over tensor/linalg ops). The implementations live in
:mod:`paddle_tpu.ops.linalg` (jnp.linalg delegates on the tape)."""
from paddle_tpu.ops.linalg import (  # noqa: F401
    bincount, bmm, cdist, cholesky, cholesky_solve, corrcoef, cov, cross,
    det, dist, dot, eig, eigh, eigvals, eigvalsh, histogram, inner, inverse,
    lstsq, lu, matmul, matrix_power, matrix_rank, multi_dot, mv, norm,
    outer, pinv, qr, slogdet, solve, svd, triangular_solve,
)

__all__ = [
    "bincount", "bmm", "cdist", "cholesky", "cholesky_solve", "corrcoef",
    "cov", "cross", "det", "dist", "dot", "eig", "eigh", "eigvals",
    "eigvalsh", "histogram", "inner", "inverse", "lstsq", "lu", "matmul",
    "matrix_power", "matrix_rank", "multi_dot", "mv", "norm", "outer",
    "pinv", "qr", "slogdet", "solve", "svd", "triangular_solve",
]
