"""paddle.geometric parity (reference: ``python/paddle/geometric/`` —
segment reductions in ``math.py`` and graph message passing in
``message_passing/send_recv.py``).

TPU-native: all reductions lower to ``jax.ops.segment_*`` (one sorted
scatter per call — XLA's segment reduce), differentiable on the tape.
``out_size`` must be static under jit; eagerly it defaults to
``max(ids)+1`` like the reference.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.autograd import apply_op
from paddle_tpu.core.tensor import Tensor

__all__ = ["segment_sum", "segment_mean", "segment_min", "segment_max",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _n_segments(segment_ids, out_size):
    if out_size is not None:
        return int(out_size)
    ids = segment_ids.data if isinstance(segment_ids, Tensor) \
        else jnp.asarray(segment_ids)
    return int(jnp.max(ids)) + 1 if ids.size else 0


def _segment(reduce: str, name: str):
    jfn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
           "max": jax.ops.segment_max}.get(reduce)

    def f(data, segment_ids, name_arg=None):
        n = _n_segments(segment_ids, None)

        def body(d, ids):
            ids_ = ids.astype(jnp.int32)
            if reduce == "mean":
                s = jax.ops.segment_sum(d, ids_, num_segments=n)
                cnt = jax.ops.segment_sum(jnp.ones_like(ids_, d.dtype),
                                          ids_, num_segments=n)
                shape = (n,) + (1,) * (d.ndim - 1)
                return s / jnp.maximum(cnt.reshape(shape), 1)
            out = jfn(d, ids_, num_segments=n)
            if reduce in ("min", "max"):
                # empty segments: paddle fills 0, jax fills +-inf
                touched = jax.ops.segment_sum(
                    jnp.ones_like(ids_, jnp.float32), ids_, num_segments=n)
                shape = (n,) + (1,) * (d.ndim - 1)
                return jnp.where(touched.reshape(shape) > 0, out, 0)
            return out
        return apply_op(body, data, segment_ids, op_name=name)
    f.__name__ = name
    f.__doc__ = (f"paddle.geometric.{name} (reference: geometric/math.py; "
                 "empty segments produce 0).")
    return f


segment_sum = _segment("sum", "segment_sum")
segment_mean = _segment("mean", "segment_mean")
segment_min = _segment("min", "segment_min")
segment_max = _segment("max", "segment_max")


def _reduce_to_dst(msg, dst, pool_type, out_size):
    n = out_size
    dst_ = dst.astype(jnp.int32)
    if pool_type == "sum":
        return jax.ops.segment_sum(msg, dst_, num_segments=n)
    if pool_type == "mean":
        s = jax.ops.segment_sum(msg, dst_, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(dst_, msg.dtype), dst_,
                                  num_segments=n)
        shape = (n,) + (1,) * (msg.ndim - 1)
        return s / jnp.maximum(cnt.reshape(shape), 1)
    jfn = jax.ops.segment_min if pool_type == "min" else jax.ops.segment_max
    out = jfn(msg, dst_, num_segments=n)
    touched = jax.ops.segment_sum(jnp.ones_like(dst_, jnp.float32), dst_,
                                  num_segments=n)
    shape = (n,) + (1,) * (msg.ndim - 1)
    return jnp.where(touched.reshape(shape) > 0, out, 0)


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size: Optional[int] = None, name=None):
    """Gather source rows, scatter-reduce to destinations (reference:
    send_recv.py:35). out = reduce_{e: dst[e]=i} x[src[e]]."""
    n = out_size if out_size is not None else x.shape[0]

    def f(xa, src, dst):
        msg = xa[src.astype(jnp.int32)]
        return _reduce_to_dst(msg, dst, reduce_op, int(n))
    return apply_op(f, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size: Optional[int] = None,
                 name=None):
    """Combine source features with edge features, then scatter-reduce
    (reference: send_recv.py:178)."""
    n = out_size if out_size is not None else x.shape[0]
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def f(xa, ya, src, dst):
        msg = combine(xa[src.astype(jnp.int32)], ya)
        return _reduce_to_dst(msg, dst, reduce_op, int(n))
    return apply_op(f, x, y, src_index, dst_index, op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op: str = "add", name=None):
    """Per-edge message from both endpoints (reference:
    send_recv.py:375): out[e] = op(x[src[e]], y[dst[e]])."""
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def f(xa, ya, src, dst):
        return combine(xa[src.astype(jnp.int32)],
                       ya[dst.astype(jnp.int32)])
    return apply_op(f, x, y, src_index, dst_index, op_name="send_uv")
