"""Async sharded checkpoint writer with atomic commit.

Save path (``CheckpointManager.save`` drives this):

1. **Snapshot** (caller's thread, blocking): :func:`snapshot` walks the
   state tree and pulls every array to host numpy (`flatten_state`) —
   after it returns, the training step may mutate parameters freely; the
   checkpoint is isolated. This is the only part an async save charges to
   the step loop.
2. **Write** (background thread for async saves): shards stream into
   ``step_N.tmp/`` as fsynced raw-bytes shard files, each rank writing only
   shards it owns (round-robin over the flat shard index); rank 0 merges
   the per-rank shard lists into ``index.json``, writes the ``COMMITTED``
   marker, and **renames the directory** — the rename is the atomic
   publish. A crash at any earlier point leaves only ``step_N.tmp``,
   which no reader accepts.
3. Non-zero ranks block until the committed directory appears (cheap
   filesystem barrier — shared-fs semantics, like the reference's
   distributed save helpers).

Telemetry (``ckpt_*`` families through ``observability.metrics``, see
docs/CHECKPOINT.md): save/blocking durations, bytes, in-flight gauge,
last-committed-step gauge, failure counters.
"""
from __future__ import annotations

import os
import pickle
import queue
import threading
import time
import warnings
from typing import Callable, Dict, Optional

import numpy as np

from . import layout
from .layout import (AUX_FILE, COMMIT_MARKER, FORMAT_VERSION, TMP_SUFFIX,
                     CheckpointError, crc32_of, flatten_state, iter_shards,
                     plan_grid, poll_until, step_dir_name, write_index)

__all__ = ["Snapshot", "snapshot", "SaveFuture", "write_step",
           "AsyncCheckpointWriter", "ckpt_metrics"]


def ckpt_metrics(registry=None) -> dict:
    """The ``ckpt_*`` metric families (created on first use)."""
    from paddle_tpu.observability.metrics import get_registry
    r = registry or get_registry()
    return {
        "save_seconds": r.histogram(
            "ckpt_save_seconds",
            "snapshot->commit wall time per save, by mode"),
        "blocking_seconds": r.histogram(
            "ckpt_blocking_seconds",
            "time save() blocked its caller (the step-loop stall), by mode"),
        "restore_seconds": r.histogram(
            "ckpt_restore_seconds", "restore wall time"),
        "bytes": r.counter(
            "ckpt_bytes_total", "checkpoint bytes, by direction"),
        "in_flight": r.gauge(
            "ckpt_in_flight", "async saves snapshotted but not committed"),
        "last_step": r.gauge(
            "ckpt_last_committed_step", "most recently committed step"),
        "failures": r.counter(
            "ckpt_failures_total", "failed saves / integrity errors, by kind"),
        "gc_removed": r.counter(
            "ckpt_gc_removed_total", "step dirs removed by retention GC"),
    }


class Snapshot:
    """Host-side copy of one state tree, decoupled from device storage."""

    def __init__(self, skeleton_bytes: bytes, tensors: Dict[str, tuple],
                 nbytes: int, seconds: float):
        self.skeleton_bytes = skeleton_bytes
        self.tensors = tensors  # key -> (np array, _TensorRef)
        self.nbytes = nbytes
        self.seconds = seconds


def snapshot(state) -> Snapshot:
    """Device→host snapshot of ``state`` (see module docstring, phase 1).
    Every leaf becomes an OWNED host copy — buffer donation in the
    compiled train step forbids holding live jax references across the
    async write (see ``flatten_state``). On a real multi-host mesh the
    full-array copy per rank is the known cost; pulling only each rank's
    addressable shards is the TPU follow-up."""
    t0 = time.perf_counter()
    skeleton, tensors = flatten_state(state)
    nbytes = sum(int(a.nbytes) for a, _ in tensors.values())
    skel = pickle.dumps(skeleton, protocol=4)
    return Snapshot(skel, tensors, nbytes + len(skel),
                    time.perf_counter() - t0)


class SaveFuture:
    """Handle for one save; ``wait()`` blocks until commit (or re-raises
    the writer's failure)."""

    def __init__(self, step: int):
        self.step = step
        self._ev = threading.Event()
        self._exc: Optional[BaseException] = None
        self._result: Optional[str] = None

    def _finish(self, result: Optional[str], exc=None):
        self._result = result
        self._exc = exc
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def exception(self) -> Optional[BaseException]:
        return self._exc

    def wait(self, timeout: Optional[float] = None) -> str:
        """Block until this save committed; returns the step directory."""
        if not self._ev.wait(timeout):
            raise TimeoutError(
                f"checkpoint save of step {self.step} not finished "
                f"in {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._result


def _fsync_file(path: str, data: bytes):
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def _fsync_dir(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _rank_shards_file(rank: int) -> str:
    return f"shards.rank{rank}.json"


def write_step(root: str, step: int, snap: Snapshot, *,
               topology: Optional[dict] = None,
               metadata: Optional[dict] = None,
               process_index: Optional[int] = None,
               process_count: Optional[int] = None,
               fault_hook: Optional[Callable[[str], None]] = None,
               overwrite: bool = False,
               registry=None) -> str:
    """Write + atomically commit one step. Returns the final step dir.

    ``fault_hook(phase)`` is the crash-injection seam (tests): it runs at
    ``"after_shards"`` (shard files durable, no manifest yet) and
    ``"before_commit"`` (manifest written, marker/rename pending); raising
    from it aborts the save exactly as a process kill at that point would,
    leaving only the ``.tmp`` directory.
    """
    import json as _json

    if process_index is None or process_count is None:
        try:
            import jax
            process_index = jax.process_index()
            process_count = jax.process_count()
        except Exception:
            process_index, process_count = 0, 1
    topology = dict(topology or {})
    nshards = 1
    for v in topology.values():
        nshards *= int(v)
    nshards = max(nshards, process_count, 1)

    final_dir = os.path.join(root, step_dir_name(step))
    tmp_dir = final_dir + TMP_SUFFIX
    if os.path.isdir(final_dir) and not overwrite:
        raise CheckpointError(
            f"step {step} already committed at {final_dir!r}")
    os.makedirs(tmp_dir, exist_ok=True)

    # -- shards owned by this rank -------------------------------------------
    my_entries: Dict[str, dict] = {}
    written = 0
    for key in sorted(snap.tensors):
        arr, ref = snap.tensors[key]
        grid = plan_grid(arr.shape, nshards)
        entry = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                 "grid": grid, "kind": ref.kind, "shards": []}
        for flat_pos, offset, shard_shape, slices in iter_shards(
                arr.shape, grid):
            owner = flat_pos % process_count
            fname = f"{key}_s{flat_pos:03d}.bin"
            shard_rec = {"file": fname, "offset": offset,
                         "shape": shard_shape, "owner": owner}
            if owner == process_index:
                # raw C-order bytes, dtype/shape from the manifest — .npy
                # would silently degrade extension dtypes (bfloat16→|V2)
                data = np.asarray(arr[slices]).tobytes()
                shard_rec["crc32"] = crc32_of(data)
                shard_rec["nbytes"] = len(data)
                _fsync_file(os.path.join(tmp_dir, fname), data)
                written += len(data)
            entry["shards"].append(shard_rec)
        my_entries[key] = entry

    if process_index == 0:
        aux_crc = crc32_of(snap.skeleton_bytes)
        _fsync_file(os.path.join(tmp_dir, AUX_FILE), snap.skeleton_bytes)
        written += len(snap.skeleton_bytes)
    _fsync_dir(tmp_dir)

    if fault_hook is not None:
        fault_hook("after_shards")

    m = ckpt_metrics(registry)
    m["bytes"].inc(written, direction="write")

    # identity of any PRE-EXISTING commit of this step id (overwrite
    # re-runs): captured before this rank publishes its shard records —
    # rank 0 cannot commit until every rank has published, so this stat
    # is guaranteed pre-commit and the barrier below can distinguish the
    # stale dir from rank 0's fresh publish
    def _commit_token():
        try:
            st = os.stat(os.path.join(final_dir, layout.INDEX_FILE))
            return (st.st_ino, st.st_mtime_ns)
        except OSError:
            return None
    stale_token = _commit_token()

    if process_count > 1:
        # publish this rank's shard records ATOMICALLY (tmp + rename) so
        # rank 0's existence poll can never read a half-written file.
        # Known limitation: a crashed multi-host attempt's residue in a
        # reused step_N.tmp is not cleared (no rank may rmtree a dir the
        # others are writing into) — a stale records file from the same
        # step id could satisfy rank 0 early; multi-host re-saves of a
        # crashed step id should use a fresh step id
        rf = os.path.join(tmp_dir, _rank_shards_file(process_index))
        _fsync_file(rf + ".tmp", _json.dumps(my_entries).encode())
        os.replace(rf + ".tmp", rf)

    if process_index != 0:
        # wait for rank 0's FRESH commit (marker inside the renamed dir,
        # manifest identity differing from any stale same-id commit)
        poll_until(lambda: layout.is_committed(final_dir) and
                   _commit_token() != stale_token,
                   what=f"rank 0's commit of step {step} "
                        f"(rank {process_index} barrier)")
        return final_dir

    # -- rank 0: merge ranks' crc records, write manifest, commit ------------
    entries = my_entries
    if process_count > 1:
        for r in range(1, process_count):
            path = os.path.join(tmp_dir, _rank_shards_file(r))
            poll_until(lambda: os.path.exists(path),
                       what=f"rank {r}'s shard records for step {step}")
            with open(path) as f:
                theirs = _json.load(f)
            for key, entry in theirs.items():
                mine = entries[key]["shards"]
                for pos, rec in enumerate(entry["shards"]):
                    if rec.get("owner") == r:
                        mine[pos] = rec
            os.unlink(path)

    doc = {"format_version": FORMAT_VERSION, "step": int(step),
           "world_size": process_count, "topology": topology,
           "tensors": entries,
           "aux": {"file": AUX_FILE, "crc32": aux_crc,
                   "nbytes": len(snap.skeleton_bytes)},
           "metadata": dict(metadata or {})}
    write_index(tmp_dir, doc)
    _fsync_dir(tmp_dir)

    if fault_hook is not None:
        fault_hook("before_commit")

    # marker first, then the rename: the rename is the atomic publish, and
    # the marker is already inside when the new name appears
    _fsync_file(os.path.join(tmp_dir, COMMIT_MARKER), b"1\n")
    _fsync_dir(tmp_dir)
    aside = None
    if overwrite and os.path.isdir(final_dir):
        # replacing an existing step (a re-run writing the same step id):
        # rename the old commit ASIDE first — at no instant is committed
        # history deleted while the replacement is still unpublished (a
        # crash here leaves step_N.old, which readers ignore and the
        # committer below removes on success)
        import shutil
        aside = final_dir + ".old"
        if os.path.isdir(aside):
            shutil.rmtree(aside)  # residue of a previously crashed swap
        os.rename(final_dir, aside)
    os.rename(tmp_dir, final_dir)
    if aside is not None:
        import shutil
        shutil.rmtree(aside, ignore_errors=True)
    _fsync_dir(root)
    m["last_step"].set(int(step))
    from paddle_tpu.observability import flight_recorder
    now = time.perf_counter_ns()
    flight_recorder.record(
        flight_recorder.KIND_CKPT, f"commit:step_{int(step)}", now, now,
        aux=int(step), args={"step": int(step), "bytes": written})
    return final_dir


class AsyncCheckpointWriter:
    """Single background thread draining a FIFO save queue.

    One worker (not a pool) on purpose: saves commit in submission order,
    so ``latest_step()`` can never observe step N+1 without step N when
    both were submitted (the async ``wait()``-ordering contract)."""

    def __init__(self, registry=None):
        self._q: "queue.Queue" = queue.Queue()
        self._registry = registry
        self._m = ckpt_metrics(registry)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closed = False

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="pt-ckpt-writer", daemon=True)
                self._thread.start()

    def submit(self, fn: Callable[[], str], step: int) -> SaveFuture:
        if self._closed:
            raise CheckpointError("writer is closed")
        fut = SaveFuture(step)
        self._m["in_flight"].inc()
        self._q.put((fn, fut))
        self._ensure_thread()
        return fut

    def _run(self):
        while True:
            try:
                fn, fut = self._q.get(timeout=0.2)
            except queue.Empty:
                with self._lock:
                    # exit when drained (no idle polling thread per
                    # manager); the empty-check under the submit lock
                    # makes the handoff race-free — a concurrent submit
                    # either sees this thread alive or restarts one
                    if self._closed or self._q.empty():
                        self._thread = None
                        return
                continue
            try:
                fut._finish(fn())
            except BaseException as e:  # noqa: BLE001 — future carries it
                self._m["failures"].inc(kind="save")
                warnings.warn(
                    f"background checkpoint save of step {fut.step} "
                    f"failed: {type(e).__name__}: {e} (sync callers "
                    f"re-raise from wait())", RuntimeWarning)
                fut._finish(None, e)
            finally:
                self._m["in_flight"].dec()
                self._q.task_done()

    def wait_all(self, timeout: Optional[float] = None):
        """Block until every submitted save finished (committed or failed)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._q.unfinished_tasks:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("checkpoint writer queue not drained")
            time.sleep(0.005)

    def close(self, timeout: Optional[float] = None):
        self.wait_all(timeout)
        self._closed = True
