"""CheckpointManager — the subsystem's front door.

Owns one checkpoint root directory full of ``step_N`` dirs and provides:

* ``save(step, state)`` — async by default: the caller pays only the
  device→host snapshot; a background writer streams shards and commits
  atomically (``writer.write_step``). Returns a :class:`SaveFuture`.
* ``restore(step=None)`` — loads the latest (or given) committed step,
  crc-verifying every shard; on corruption it warns LOUDLY, bumps
  ``ckpt_failures_total{kind="integrity"}`` and falls back to the previous
  committed step, so a torn/bit-rotted step never silently restores.
* ``latest_step()`` / ``all_steps()`` — committed steps only.
* keep-last-k retention GC (also sweeps stale ``.tmp`` dirs of crashed
  saves), run after every commit.

Integration seams: hapi's ``ModelCheckpoint`` callback,
``incubate.checkpoint.TrainEpochRange`` and
``serving.ServingEngine.load_weights`` all route through this class;
``paddle.load`` dir-dispatches here (``load_state_dir``).
"""
from __future__ import annotations

import os
import shutil
import time
import warnings
from typing import Callable, List, Optional

from .layout import (INDEX_FILE, TMP_SUFFIX, CheckpointError,
                     CheckpointIntegrityError, is_committed,
                     list_committed_steps, parse_step_dir, read_index,
                     step_dir_name)
from .reshard import mesh_topology, read_state
from .writer import (AsyncCheckpointWriter, SaveFuture, ckpt_metrics,
                     snapshot, write_step)

__all__ = ["CheckpointManager", "load_state_dir"]


class CheckpointManager:
    """Orbax-flavored manager over one checkpoint directory.

    ``topology``: axis-name -> size dict recorded in the manifest and used
    to pick shard grids; defaults to the current ``distributed.get_mesh()``
    (falling back to one shard per tensor off-mesh). ``fault_hook`` is the
    crash-injection seam forwarded to :func:`writer.write_step` — tests
    use it to kill a save between shard write and commit.
    """

    def __init__(self, root: str, keep_last_k: Optional[int] = None,
                 async_: bool = True, topology: Optional[dict] = None,
                 registry=None,
                 fault_hook: Optional[Callable[[str], None]] = None):
        self.root = str(root)
        self.keep_last_k = keep_last_k
        self.async_ = bool(async_)
        self.registry = registry
        self.fault_hook = fault_hook
        self._topology = topology
        self._writer = AsyncCheckpointWriter(registry)
        self._m = ckpt_metrics(registry)
        self.last_restored_step: Optional[int] = None
        os.makedirs(self.root, exist_ok=True)

    # -- save ----------------------------------------------------------------
    def topology(self) -> dict:
        if self._topology is not None:
            return dict(self._topology)
        try:
            from paddle_tpu.distributed import get_mesh
            return mesh_topology(get_mesh())
        except Exception:
            return {}

    def save(self, step: int, state, async_: Optional[bool] = None,
             metadata: Optional[dict] = None,
             overwrite: bool = False) -> SaveFuture:
        """Snapshot ``state`` and persist it as ``step``. Async saves
        return immediately after the snapshot; ``fut.wait()`` blocks until
        the atomic commit. Sync saves commit before returning.
        ``overwrite`` lets a re-run replace an already-committed step id
        (default: raise — silently clobbering history is a bug)."""
        use_async = self.async_ if async_ is None else bool(async_)
        mode = "async" if use_async else "sync"
        t0 = time.perf_counter()
        snap = snapshot(state)
        topo = self.topology()

        def write() -> str:
            t1 = time.perf_counter()
            path = write_step(self.root, step, snap, topology=topo,
                              metadata=metadata, fault_hook=self.fault_hook,
                              overwrite=overwrite,
                              registry=self.registry)
            self._m["save_seconds"].observe(
                snap.seconds + (time.perf_counter() - t1), mode=mode)
            self._gc()
            return path

        # both modes go through the single writer thread — saves (and the
        # GC after each commit) are strictly serialized, so a sync save
        # can never race an in-flight async one
        fut = self._writer.submit(write, step)
        if use_async:
            self._m["blocking_seconds"].observe(
                time.perf_counter() - t0, mode=mode)
            return fut
        try:
            fut.wait()  # re-raises a failed sync save in the caller
        finally:
            self._m["blocking_seconds"].observe(
                time.perf_counter() - t0, mode=mode)
        return fut

    def wait_all(self, timeout: Optional[float] = None):
        """Drain every in-flight async save."""
        self._writer.wait_all(timeout)

    def close(self, timeout: Optional[float] = None):
        self._writer.close(timeout)

    # -- discovery -----------------------------------------------------------
    def all_steps(self) -> List[int]:
        return list_committed_steps(self.root)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def step_dir(self, step: int) -> str:
        return os.path.join(self.root, step_dir_name(step))

    def metadata(self, step: int) -> dict:
        return read_index(self.step_dir(step)).get("metadata", {})

    # -- restore -------------------------------------------------------------
    def restore(self, step: Optional[int] = None, mesh=None,
                verify: bool = True, strict: bool = False):
        """Load a committed step (default: latest) back into a state tree.

        Corrupt steps (checksum mismatch, missing shards, unreadable
        manifest) are skipped with a loud warning and the previous
        committed step is tried — unless ``strict`` or an explicit
        ``step`` was requested, in which case the integrity error raises.
        """
        steps = self.all_steps()
        if step is not None:
            if step not in steps:
                raise FileNotFoundError(
                    f"step {step} has no committed checkpoint in "
                    f"{self.root!r} (committed: {steps})")
            candidates = [step]
        else:
            candidates = list(reversed(steps))
        if not candidates:
            raise FileNotFoundError(
                f"no committed checkpoint under {self.root!r}")
        last_err: Optional[CheckpointError] = None
        for s in candidates:
            try:
                state = read_state(self.step_dir(s), verify=verify,
                                   mesh=mesh, registry=self.registry)
                self.last_restored_step = s
                from paddle_tpu.observability import flight_recorder
                now = time.perf_counter_ns()
                flight_recorder.record(
                    flight_recorder.KIND_CKPT, f"restore:step_{s}", now,
                    now, aux=int(s), args={"step": int(s)})
                return state
            except CheckpointIntegrityError as e:
                self._m["failures"].inc(kind="integrity")
                will_fall_back = not (strict or step is not None)
                warnings.warn(
                    f"checkpoint step {s} in {self.root!r} is CORRUPT "
                    f"({e}); " +
                    ("falling back to the previous committed step"
                     if will_fall_back else
                     "raising (explicitly requested step / strict mode)"),
                    RuntimeWarning, stacklevel=2)
                last_err = e
                if not will_fall_back:
                    raise
        raise CheckpointIntegrityError(
            f"every committed step under {self.root!r} failed integrity "
            f"verification") from last_err

    # -- retention -----------------------------------------------------------
    def _gc(self):
        """Keep the newest ``keep_last_k`` committed steps; sweep stale
        ``.tmp`` dirs (aborted saves) regardless of retention policy."""
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return
        committed = sorted(s for s in (parse_step_dir(n) for n in names)
                           if s is not None
                           if is_committed(os.path.join(
                               self.root, step_dir_name(s))))
        doomed = []
        if self.keep_last_k is not None and self.keep_last_k > 0:
            # retention by commit RECENCY (manifest mtime; id breaks
            # ties), not by step id: a restarted run re-numbering from
            # epoch 0 over higher-id steps of a previous run must not
            # have its fresh commits collected as "oldest"
            def commit_time(s):
                try:
                    return (os.path.getmtime(os.path.join(
                        self.root, step_dir_name(s), INDEX_FILE)), s)
                except OSError:
                    return (0.0, s)
            by_recency = sorted(committed, key=commit_time)
            doomed = [os.path.join(self.root, step_dir_name(s))
                      for s in by_recency[:-self.keep_last_k]]
        try:
            import jax
            single_process = jax.process_count() == 1
        except Exception:
            single_process = True
        for name in names:
            if single_process and name.startswith("step_") and \
                    name.endswith(TMP_SUFFIX):
                # sweep only .tmp dirs STRICTLY OLDER than the newest
                # committed step (saves commit in step order within this
                # process's serialized writer, so such a dir can only be
                # an aborted save's residue), and only in single-process
                # runs — on a shared fs another rank's live in-flight
                # save is indistinguishable from residue, so multi-host
                # crash residue is left for operator cleanup
                try:
                    s = int(name[len("step_"):-len(TMP_SUFFIX)])
                except ValueError:
                    continue
                if committed and s < committed[-1]:
                    doomed.append(os.path.join(self.root, name))
            elif name.startswith("step_") and name.endswith(".old"):
                # overwrite-swap residue: superseded once the same-id
                # final dir is committed again; if the final dir is
                # MISSING, the .old holds the only copy of that step
                # (crash between aside and publish) — keep it
                if is_committed(os.path.join(self.root, name[:-4])):
                    doomed.append(os.path.join(self.root, name))
        for path in doomed:
            shutil.rmtree(path, ignore_errors=True)
            if not path.endswith(TMP_SUFFIX):
                self._m["gc_removed"].inc()


def load_state_dir(path: str, step: Optional[int] = None, mesh=None,
                   verify: bool = True):
    """``paddle.load`` dir-dispatch target: ``path`` may be a manager root
    (latest committed step, with corruption fallback) or a single
    ``step_N`` directory."""
    if os.path.isfile(os.path.join(path, INDEX_FILE)):
        return read_state(path, verify=verify, mesh=mesh)
    return CheckpointManager(path).restore(step=step, mesh=mesh,
                                           verify=verify)
