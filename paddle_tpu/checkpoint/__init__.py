"""paddle_tpu.checkpoint — distributed checkpointing subsystem.

Async sharded save / verified restore with atomic commit and cross-mesh
reshard (see docs/CHECKPOINT.md):

- **layout** — step-directory format: per-tensor shard raw-bytes shard files +
  ``index.json`` manifest (global shape, dtype, shard grid, per-shard
  crc32) + pickled state skeleton; commit = ``COMMITTED`` marker +
  ``.tmp`` → final directory rename.
- **writer** — device→host snapshot off the critical path, background
  shard streaming, fsync + atomic publish; ``ckpt_*`` metric families.
- **reshard** — mesh-independent shard assembly and re-layout onto the
  *current* mesh (``NamedSharding`` placement), so a run saved under one
  dp/mp topology resumes under another.
- **manager** — ``CheckpointManager``: ``save``/``restore``,
  ``latest_step``/``all_steps``, keep-last-k GC, loud corruption fallback.
"""
from . import layout, manager, reshard, writer  # noqa: F401
from .layout import (  # noqa: F401
    CheckpointError, CheckpointIntegrityError, is_checkpoint_dir,
    list_committed_steps,
)
from .manager import CheckpointManager, load_state_dir  # noqa: F401
from .reshard import place_on_mesh, read_state  # noqa: F401
from .writer import SaveFuture, snapshot  # noqa: F401

__all__ = ["CheckpointManager", "load_state_dir", "read_state",
           "place_on_mesh", "snapshot", "SaveFuture", "CheckpointError",
           "CheckpointIntegrityError", "is_checkpoint_dir",
           "list_committed_steps", "layout", "writer", "manager",
           "reshard"]
