"""Checkpoint on-disk layout: step directories, shard planning, manifest.

Orbax/TensorStore-flavored format (PAPERS.md "Fine-Tuning and Serving
Gemma ... on Cloud TPU" names sharded async checkpointing as the substrate
for preemption-tolerant training):

```
<root>/
  step_12.tmp/          # in-flight save — never loadable
  step_12/              # committed step
    COMMITTED           # commit marker (written BEFORE the dir rename)
    index.json          # manifest: name -> shape/dtype/grid/per-shard crc32
    aux.pkl             # pickled state skeleton (non-array leaves +
                        # _TensorRef placeholders; preserves namedtuples)
    t0000_s000.bin ...  # one raw-bytes file per shard
```

A step is **committed** iff its directory does not end in ``.tmp`` AND the
``COMMITTED`` marker exists. The writer renames ``step_N.tmp`` →
``step_N`` as the last act, so a crash at any earlier point leaves only a
``.tmp`` directory, which readers ignore and GC removes — a torn
checkpoint is never loadable.

Shards are rectangular blocks of the global array: the manifest records
each shard's ``offset`` (start index per dim) and ``shape``, so assembly
is mesh-independent — any reader pastes shards into a full array and
re-lays it onto *its* mesh (reference auto_parallel Converter semantics:
merge under the old dist attrs, re-slice under the new).
"""
from __future__ import annotations

import itertools
import json
import os
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "FORMAT_VERSION", "INDEX_FILE", "COMMIT_MARKER", "AUX_FILE",
    "TMP_SUFFIX", "STEP_PREFIX", "CheckpointError",
    "CheckpointIntegrityError", "step_dir_name", "parse_step_dir",
    "is_committed", "list_committed_steps", "plan_grid", "iter_shards",
    "crc32_of", "flatten_state", "unflatten_state", "write_index",
    "read_index", "is_checkpoint_dir", "poll_until",
]

FORMAT_VERSION = 1
INDEX_FILE = "index.json"
COMMIT_MARKER = "COMMITTED"
AUX_FILE = "aux.pkl"
TMP_SUFFIX = ".tmp"
STEP_PREFIX = "step_"


class CheckpointError(RuntimeError):
    """Malformed/unusable checkpoint directory."""


def poll_until(predicate: Callable[[], bool], what: str,
               timeout: Optional[float] = None, interval: float = 0.005):
    """The shared filesystem-barrier wait (commit markers, rank shard
    lists, flat-save sidecars): poll ``predicate`` until true or until
    ``timeout`` seconds elapsed (default from
    ``PADDLE_TPU_CKPT_BARRIER_TIMEOUT``, 600 s), then raise
    ``TimeoutError`` naming ``what`` never happened."""
    if timeout is None:
        timeout = float(os.environ.get("PADDLE_TPU_CKPT_BARRIER_TIMEOUT",
                                       "600"))
    deadline = time.monotonic() + timeout
    while not predicate():
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"timed out after {timeout}s waiting for {what}; "
                f"no commit observed")
        time.sleep(interval)


class CheckpointIntegrityError(CheckpointError):
    """Checksum mismatch or missing shard — the step is corrupt."""


def step_dir_name(step: int) -> str:
    return f"{STEP_PREFIX}{int(step)}"


def parse_step_dir(name: str) -> Optional[int]:
    """``step_12`` -> 12; anything else (incl. ``step_12.tmp``) -> None."""
    if not name.startswith(STEP_PREFIX) or name.endswith(TMP_SUFFIX):
        return None
    try:
        return int(name[len(STEP_PREFIX):])
    except ValueError:
        return None


def is_committed(step_dir: str) -> bool:
    return (not step_dir.rstrip(os.sep).endswith(TMP_SUFFIX)
            and os.path.isfile(os.path.join(step_dir, COMMIT_MARKER))
            and os.path.isfile(os.path.join(step_dir, INDEX_FILE)))


def list_committed_steps(root: str) -> List[int]:
    """Ascending committed step numbers under ``root``."""
    if not os.path.isdir(root):
        return []
    steps = []
    for name in os.listdir(root):
        s = parse_step_dir(name)
        if s is not None and is_committed(os.path.join(root, name)):
            steps.append(s)
    return sorted(steps)


def is_checkpoint_dir(path: str) -> bool:
    """True for a manager root (has committed steps) or a single step dir."""
    if not os.path.isdir(path):
        return False
    return bool(list_committed_steps(path)) or \
        os.path.isfile(os.path.join(path, INDEX_FILE))


# ---------------------------- shard planning --------------------------------

def plan_grid(shape: Sequence[int], nshards: int) -> List[int]:
    """Partition grid (parts per dim) for a tensor of ``shape`` across up
    to ``nshards`` writers: shard the largest dim that divides evenly by
    the largest feasible part count. Scalars / indivisible shapes get a
    single shard — correctness never depends on shardability."""
    grid = [1] * len(shape)
    if nshards <= 1 or not shape:
        return grid
    for parts in range(min(nshards, max(shape) if shape else 1), 1, -1):
        divisible = [(size, dim) for dim, size in enumerate(shape)
                     if size % parts == 0 and size >= parts]
        if divisible:
            _, dim = max(divisible)
            grid[dim] = parts
            return grid
    return grid


def iter_shards(shape: Sequence[int], grid: Sequence[int]):
    """Yield ``(flat_pos, offset, shard_shape, slices)`` for every shard
    of the grid, in row-major grid order."""
    shape = list(shape)
    grid = list(grid)
    steps = [s // g for s, g in zip(shape, grid)] or []
    for flat_pos, index in enumerate(itertools.product(
            *[range(g) for g in grid])):
        offset = [i * st for i, st in zip(index, steps)]
        shard_shape = list(steps)
        slices = tuple(slice(o, o + sh)
                       for o, sh in zip(offset, shard_shape))
        yield flat_pos, offset, shard_shape, slices


def crc32_of(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


# ------------------------- state tree flattening ----------------------------

class _TensorRef:
    """Placeholder pickled into aux.pkl where an array leaf sat.

    ``kind``: ``"tensor"`` (paddle Tensor — restored as Tensor with its
    ``stop_gradient``/``name``), ``"jax"`` (bare jax array — restored as
    Tensor, matching ``framework.io`` parity), ``"ndarray"`` (numpy —
    restored as numpy)."""

    __slots__ = ("key", "kind", "stop_gradient", "name")

    def __init__(self, key: str, kind: str, stop_gradient: bool = True,
                 name: str = ""):
        self.key = key
        self.kind = kind
        self.stop_gradient = stop_gradient
        self.name = name

    # __slots__ classes need explicit pickle support
    def __getstate__(self):
        return (self.key, self.kind, self.stop_gradient, self.name)

    def __setstate__(self, st):
        self.key, self.kind, self.stop_gradient, self.name = st


def flatten_state(state) -> Tuple[object, Dict[str, Tuple[np.ndarray,
                                                          "_TensorRef"]]]:
    """Split a nested state into (skeleton, tensors).

    The skeleton mirrors ``state``'s container structure (dicts, lists,
    tuples, **namedtuples preserved**) with every array leaf replaced by a
    :class:`_TensorRef`; ``tensors`` maps ref key -> (host numpy copy,
    ref). The copy here IS the device→host snapshot: it must be an OWNED
    host buffer, not a reference — the compiled train step DONATES old
    param/moment buffers to XLA (a held jax array reference turns into
    'Array has been deleted' on the writer thread) and numpy leaves may
    be mutated in place by the caller."""
    from paddle_tpu.core.tensor import Tensor

    tensors: Dict[str, Tuple[np.ndarray, _TensorRef]] = {}
    counter = itertools.count()

    def ref_for(value, kind, stop_gradient=True, name=""):
        key = f"t{next(counter):04d}"
        ref = _TensorRef(key, kind, stop_gradient, name)
        tensors[key] = (np.array(value, copy=True), ref)
        return ref

    def walk(obj, path):
        if isinstance(obj, Tensor):
            return ref_for(obj.data, "tensor", obj.stop_gradient, obj.name)
        if isinstance(obj, np.ndarray):
            return ref_for(obj, "ndarray")
        if isinstance(obj, np.generic):
            return obj  # numpy scalars pickle fine in the skeleton
        if hasattr(obj, "dtype") and hasattr(obj, "shape") and \
                not isinstance(obj, (int, float, complex)):
            return ref_for(obj, "jax")  # bare jax arrays
        if isinstance(obj, dict):
            return {k: walk(v, f"{path}/{k}") for k, v in obj.items()}
        if isinstance(obj, tuple) and hasattr(obj, "_fields"):
            return type(obj)(*[walk(v, f"{path}/{i}")
                               for i, v in enumerate(obj)])
        if isinstance(obj, (list, tuple)):
            seq = [walk(v, f"{path}/{i}") for i, v in enumerate(obj)]
            return seq if isinstance(obj, list) else tuple(seq)
        return obj

    return walk(state, ""), tensors


def unflatten_state(skeleton, arrays: Dict[str, np.ndarray]):
    """Inverse of :func:`flatten_state`: rebuild the nested state from the
    pickled skeleton plus assembled arrays (keyed by ref key)."""
    from paddle_tpu.core.tensor import Tensor

    def walk(obj):
        if isinstance(obj, _TensorRef):
            arr = arrays[obj.key]
            if obj.kind == "ndarray":
                return arr
            return Tensor(arr, stop_gradient=obj.stop_gradient,
                          name=obj.name)
        if isinstance(obj, dict):
            return {k: walk(v) for k, v in obj.items()}
        if isinstance(obj, tuple) and hasattr(obj, "_fields"):
            return type(obj)(*[walk(v) for v in obj])
        if isinstance(obj, (list, tuple)):
            seq = [walk(v) for v in obj]
            return seq if isinstance(obj, list) else tuple(seq)
        return obj

    return walk(skeleton)


# ------------------------------- manifest -----------------------------------

def write_index(step_dir: str, doc: dict):
    """fsynced atomic write of the manifest into ``step_dir``."""
    path = os.path.join(step_dir, INDEX_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_index(step_dir: str) -> dict:
    path = os.path.join(step_dir, INDEX_FILE)
    if not os.path.isfile(path):
        raise CheckpointError(f"no {INDEX_FILE} in {step_dir!r}")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (json.JSONDecodeError, OSError) as e:
        raise CheckpointIntegrityError(
            f"unreadable manifest in {step_dir!r}: {e}") from e
    if doc.get("format_version") != FORMAT_VERSION:
        raise CheckpointError(
            f"unsupported checkpoint format_version "
            f"{doc.get('format_version')!r} in {step_dir!r}")
    return doc
