"""Restore: shard assembly, integrity verification, cross-mesh re-layout.

Assembly is mesh-independent by construction — the manifest records every
shard's global ``offset``/``shape``, so a reader pastes shards into a full
logical array regardless of which dp/mp topology wrote them (the
reference's ``auto_parallel/converter.py`` merge step). Re-layout onto the
*current* mesh is then just placement: :func:`place_on_mesh` computes a
``NamedSharding`` per tensor (largest divisible dim over the largest
usable mesh-axis subset) and ``jax.device_put``s the assembled array, so a
checkpoint written under ``{"dp": 8}`` restores onto ``{"dp": 2, "mp": 4}``
— elastic resume.

Every shard (and the pickled skeleton) is crc32-verified before use;
mismatches raise :class:`CheckpointIntegrityError`, which the manager
turns into a loud fallback to the previous committed step.
"""
from __future__ import annotations

import os
import pickle
import time
from typing import Dict, Optional

import numpy as np

from .layout import (AUX_FILE, CheckpointError, CheckpointIntegrityError,
                     crc32_of, is_committed, read_index, unflatten_state)

__all__ = ["assemble_tensor", "assemble_from", "read_state",
           "place_on_mesh", "mesh_topology"]


def mesh_topology(mesh) -> dict:
    """axis-name -> size dict for a ``jax.sharding.Mesh`` (what the save
    side records as the writing topology)."""
    if mesh is None:
        return {}
    return {str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)}


def _read_verified(path: str, crc: Optional[int], what: str) -> bytes:
    if not os.path.isfile(path):
        raise CheckpointIntegrityError(f"missing {what}: {path!r}")
    with open(path, "rb") as f:
        data = f.read()
    if crc is not None and crc32_of(data) != crc:
        raise CheckpointIntegrityError(
            f"checksum mismatch on {what}: {path!r}")
    return data


def assemble_from(entry: dict, fetch, verify: bool = True) -> np.ndarray:
    """Paste a tensor's shards back into the full logical array, pulling
    each shard's raw C-order bytes through ``fetch(rec) -> bytes``.

    The transport is pluggable — file reads (:func:`assemble_tensor`) and
    the elastic resize's in-memory TCPStore exchange share this exact
    offset-pasting loop, so the live-reshard path is bit-identical to the
    checkpoint-file path *by construction*, not by parallel maintenance.
    ``verify`` crc32-checks each fetched payload against the manifest.
    """
    try:
        dt = np.dtype(entry["dtype"])
    except TypeError as e:
        raise CheckpointError(
            f"unknown dtype {entry['dtype']!r} in manifest") from e
    full = np.empty(entry["shape"], dtype=dt)
    for rec in entry["shards"]:
        data = fetch(rec)
        what = rec.get("file") or f"offset {rec['offset']}"
        if verify and rec.get("crc32") is not None \
                and crc32_of(data) != rec["crc32"]:
            raise CheckpointIntegrityError(
                f"checksum mismatch on shard {what!r} "
                f"(owner rank {rec.get('owner', 0)})")
        expected = int(np.prod(rec["shape"])) * dt.itemsize
        if len(data) != expected:
            raise CheckpointIntegrityError(
                f"shard {what!r} holds {len(data)} bytes, manifest "
                f"shape {rec['shape']} x {dt} needs {expected}")
        shard = np.frombuffer(data, dtype=dt).reshape(rec["shape"])
        slices = tuple(slice(o, o + s)
                       for o, s in zip(rec["offset"], rec["shape"]))
        full[slices] = shard
    return full


def assemble_tensor(entry: dict, step_dir: str,
                    verify: bool = True) -> np.ndarray:
    """Paste a tensor's shards back into the full logical array. Shard
    files are raw C-order bytes; dtype and shape come from the manifest
    (extension dtypes like bfloat16 resolve once jax/ml_dtypes is
    imported, which ``import paddle_tpu`` guarantees)."""

    def fetch(rec):
        # crc verification happens in assemble_from against the manifest;
        # _read_verified only guards the read itself (missing file).
        return _read_verified(
            os.path.join(step_dir, rec["file"]), None,
            f"shard (owner rank {rec.get('owner', 0)})")

    return assemble_from(entry, fetch, verify=verify)


def _partition_spec(shape, mesh):
    """PartitionSpec sharding the largest divisible dim across as many
    mesh axes as divide it (axes taken in mesh order); None when nothing
    divides (fully replicated)."""
    from jax.sharding import PartitionSpec as P

    axes = list(mesh.axis_names)
    sizes = dict(mesh_topology(mesh))
    best = None  # (covered_devices, -dim) -> axis subset
    for dim, size in sorted(enumerate(shape), key=lambda t: -t[1]):
        covered, subset = 1, []
        for ax in axes:
            if size % (covered * sizes[ax]) == 0:
                covered *= sizes[ax]
                subset.append(ax)
        if len(subset) > 0 and covered > 1:
            cand = (covered, -dim, subset)
            if best is None or cand[:2] > best[:2]:
                best = cand
    if best is None:
        return P()
    covered, negdim, subset = best
    dim = -negdim
    spec = [None] * len(shape)
    spec[dim] = tuple(subset) if len(subset) > 1 else subset[0]
    return P(*spec)


def place_on_mesh(arr: np.ndarray, mesh):
    """Lay a full logical array onto the current mesh (NamedSharding)."""
    import jax
    from jax.sharding import NamedSharding
    spec = _partition_spec(arr.shape, mesh)
    return jax.device_put(arr, NamedSharding(mesh, spec))


def read_state(step_dir: str, verify: bool = True, mesh=None,
               registry=None):
    """Load one committed step directory back into a nested state tree.

    With ``mesh`` given, every restored array is placed onto it (sharded
    where divisible) before being wrapped — this is the reshard-on-load
    path; without it, arrays come back host-committed and placement
    happens in ``set_state_dict`` (framework.io parity).
    """
    from .writer import ckpt_metrics

    t0 = time.perf_counter()
    if not is_committed(step_dir):
        raise CheckpointError(
            f"{step_dir!r} is not a committed checkpoint step")
    doc = read_index(step_dir)
    aux = doc["aux"]
    skel_bytes = _read_verified(
        os.path.join(step_dir, aux["file"]),
        aux.get("crc32") if verify else None, "state skeleton")
    skeleton = pickle.loads(skel_bytes)

    arrays: Dict[str, np.ndarray] = {}
    nbytes = len(skel_bytes)
    for key, entry in doc["tensors"].items():
        full = assemble_tensor(entry, step_dir, verify=verify)
        nbytes += full.nbytes
        # kind "ndarray" leaves are contractually restored as (mutable)
        # numpy — never device_put them, even on the reshard path
        if mesh is not None and entry.get("kind") != "ndarray":
            full = place_on_mesh(full, mesh)
        arrays[key] = full

    state = unflatten_state(skeleton, arrays)
    m = ckpt_metrics(registry)
    m["restore_seconds"].observe(time.perf_counter() - t0)
    m["bytes"].inc(nbytes, direction="read")
    return state
