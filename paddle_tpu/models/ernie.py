"""ERNIE family (BASELINE.md "ERNIE pretraining MFU" config).

BERT-shaped bidirectional encoder with ERNIE's task heads; parity target is
the paddle ecosystem's ErnieModel surface (the reference repo's NLP zoo lives
in PaddleNLP; its in-tree seam is the transformer layer set,
``python/paddle/nn/layer/transformer.py``). Built on paddle_tpu's own
TransformerEncoder so attention rides the same flash/XLA path as Llama.
"""
from __future__ import annotations

from dataclasses import dataclass

from paddle_tpu import ops
from paddle_tpu import nn
from paddle_tpu.nn import functional as F

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "ErnieForPretraining"]


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    layer_norm_eps: float = 1e-12

    @staticmethod
    def tiny(**kw) -> "ErnieConfig":
        base = dict(vocab_size=128, hidden_size=32,
                    num_hidden_layers=2, num_attention_heads=2,
                    intermediate_size=64,
                    max_position_embeddings=64, type_vocab_size=2)
        base.update(kw)
        return ErnieConfig(**base)


class ErnieEmbeddings(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        S = input_ids.shape[1]
        pos = ops.arange(0, S, dtype="int64")
        x = self.word_embeddings(input_ids)
        x = ops.add(x, self.position_embeddings(pos))
        if token_type_ids is None:
            token_type_ids = ops.zeros_like(input_ids)
        x = ops.add(x, self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class ErnieModel(nn.Layer):
    """Returns (sequence_output [B,S,H], pooled_output [B,H])."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            act_dropout=0.0, normalize_before=False)
        self.encoder = nn.TransformerEncoder(enc_layer, cfg.num_hidden_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        x = self.encoder(x, src_mask=attention_mask)
        pooled = ops.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, cfg: ErnieConfig, num_classes: int = 2,
                 dropout: float = None):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob
                                  if dropout is None else dropout)
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return logits, F.cross_entropy(logits, labels)


class ErnieForPretraining(nn.Layer):
    """MLM + sentence-order heads (ERNIE pretraining objective shape)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_norm = nn.LayerNorm(cfg.hidden_size,
                                     epsilon=cfg.layer_norm_eps)
        self.sop_classifier = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, masked_lm_labels=None,
                sop_labels=None):
        seq, pooled = self.ernie(input_ids, token_type_ids)
        h = self.mlm_norm(F.gelu(self.mlm_transform(seq)))
        # decode against the (tied) word embedding matrix
        w = self.ernie.embeddings.word_embeddings.weight
        mlm_logits = ops.matmul(h, ops.transpose(w, [1, 0]))
        sop_logits = self.sop_classifier(pooled)
        if masked_lm_labels is None:
            return mlm_logits, sop_logits
        loss = F.cross_entropy(
            ops.reshape(mlm_logits, [-1, mlm_logits.shape[-1]]),
            ops.reshape(masked_lm_labels, [-1]), ignore_index=-100)
        if sop_labels is not None:
            loss = ops.add(loss, F.cross_entropy(sop_logits, sop_labels))
        return mlm_logits, sop_logits, loss
