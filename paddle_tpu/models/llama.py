"""Llama-3 family (BASELINE.md north-star model).

Capability parity target: the PaddleNLP Llama recipe the reference runs for
its headline numbers (the reference repo itself carries no LLM zoo; its
fused-attention seam is ``paddle/phi/kernels/gpu/flash_attn_kernel.cu``).

TPU-first design decisions:
  * attention goes through ``nn.functional.flash_attention`` → the Pallas
    flash kernel on TPU;
  * GQA (num_key_value_heads < num_attention_heads) is a reshape +
    broadcast, no repeat_interleave materialization;
  * with ``tensor_parallel=True`` the projections are mpu Column/Row
    parallel layers and the embedding is vocab-parallel — GSPMD places the
    collectives (SURVEY.md §7 principle 3);
  * rotary embedding is a single fused tape node (one jnp body), cached
    per (seq, dim, dtype).
"""
from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.autograd import apply_op
from paddle_tpu import ops
from paddle_tpu import nn
from paddle_tpu.nn import functional as F
from paddle_tpu.observability import numerics
from paddle_tpu.ops.paged_attention import (PagedLayerCache,
                                            RaggedLayerCache)

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM"]


@dataclass
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    tensor_parallel: bool = False
    recompute: bool = False

    @staticmethod
    def llama3_8b(**kw) -> "LlamaConfig":
        return LlamaConfig(**kw)

    @staticmethod
    def llama3_70b(**kw) -> "LlamaConfig":
        base = dict(hidden_size=8192, intermediate_size=28672,
                    num_hidden_layers=80, num_attention_heads=64,
                    num_key_value_heads=8)
        base.update(kw)
        return LlamaConfig(**base)

    @staticmethod
    def tiny(**kw) -> "LlamaConfig":
        """Test-size config: runs forward+backward in <1s on CPU."""
        base = dict(vocab_size=256, hidden_size=64,
                    intermediate_size=128, num_hidden_layers=2,
                    num_attention_heads=4, num_key_value_heads=2,
                    max_position_embeddings=128)
        base.update(kw)
        return LlamaConfig(**base)


@functools.lru_cache(maxsize=32)
def _rope_cache(seq_len: int, dim: int, theta: float, dtype_name: str):
    # numpy on purpose: this cache is shared across traces, so it must
    # never hold jax tracers (a traced entry would leak into later traces
    # as an UnexpectedTracerError); the arrays become XLA constants at use
    inv = 1.0 / (theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    t = np.arange(seq_len, dtype=np.float64)
    freqs = np.outer(t, inv)  # [S, dim/2]
    to = jnp.dtype(dtype_name)
    return (np.cos(freqs).astype(to), np.sin(freqs).astype(to))


def _rot_interleaved(t, cos, sin):
    """THE rotation convention (even/odd lane pairs, re-interleaved) —
    the single definition every path (eager, static-cache, paged
    serving) must share so their numerics can never desynchronize.
    ``cos``/``sin`` broadcast against ``t`` [..., S, H, D/2]."""
    t1, t2 = t[..., 0::2], t[..., 1::2]
    return jnp.stack([t1 * cos - t2 * sin, t2 * cos + t1 * sin],
                     axis=-1).reshape(t.shape)


def _gather_rope(pidx, dim, theta, dtype_name, table_len):
    """cos/sin [B, S, 1, dim/2] at PER-ROW absolute positions ``pidx``
    [B, S] (already clipped to the table) from the cached table."""
    cos_np, sin_np = _rope_cache(table_len, dim, theta, dtype_name)
    return (jnp.asarray(cos_np)[pidx][:, :, None, :],
            jnp.asarray(sin_np)[pidx][:, :, None, :])


def apply_rotary(q, k, theta: float = 500000.0, pos_offset: int = 0,
                 table_len: int = 0):
    """Rotate q,k ([B,S,H,D]) by absolute position (``pos_offset`` shifts
    the position index — the KV-cached decode path's token lands at
    position P, not 0). ``table_len`` fixes the cached table size (pass
    max_position_embeddings so every decode step hits ONE lru entry
    instead of minting a new table per length). One tape node."""
    def f(qa, ka):
        s, d = qa.shape[1], qa.shape[-1]
        n = max(table_len, pos_offset + s)
        cos, sin = _rope_cache(n, d, theta, str(qa.dtype))
        cos = jnp.asarray(cos)[None, pos_offset:pos_offset + s, None, :]
        sin = jnp.asarray(sin)[None, pos_offset:pos_offset + s, None, :]
        return (_rot_interleaved(qa, cos, sin),
                _rot_interleaved(ka, cos, sin))
    return apply_op(f, q, k, op_name="rotary_embedding")


def apply_rotary_positions(q, k, position_ids, theta: float = 500000.0,
                           table_len: int = 0):
    """Rotate q,k ([B,S,H,D]) at PER-TOKEN positions ``position_ids``
    [B,S] — the packed-sequence form (docs/DATA.md): each document inside
    a packed row restarts at position 0, so RoPE must be gathered per
    token instead of sliced by row offset. Same table and rotation
    convention as :func:`apply_rotary` (one ``_rope_cache`` /
    ``_rot_interleaved`` pair for every path)."""
    def f(qa, ka, pidx):
        s, d = qa.shape[1], qa.shape[-1]
        n = max(table_len, s)
        pidx = jnp.clip(pidx.astype(jnp.int32), 0, n - 1)
        cos, sin = _gather_rope(pidx, d, theta, str(qa.dtype), n)
        return (_rot_interleaved(qa, cos, sin),
                _rot_interleaved(ka, cos, sin))
    return apply_op(f, q, k, position_ids, op_name="rotary_embedding")


def _linear_cls(cfg: LlamaConfig, kind: str):
    if not cfg.tensor_parallel:
        return None
    from paddle_tpu.distributed.fleet import (
        ColumnParallelLinear, RowParallelLinear)
    return ColumnParallelLinear if kind == "col" else RowParallelLinear


def _make_linear(cfg, d_in, d_out, kind):
    cls = _linear_cls(cfg, kind)
    if cls is None:
        return nn.Linear(d_in, d_out, bias_attr=False)
    if kind == "col":
        return cls(d_in, d_out, has_bias=False, gather_output=False)
    return cls(d_in, d_out, has_bias=False, input_is_parallel=True)


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.n_heads = cfg.num_attention_heads
        self.n_kv = cfg.num_key_value_heads
        self.q_proj = _make_linear(cfg, cfg.hidden_size,
                                   self.n_heads * self.head_dim, "col")
        self.k_proj = _make_linear(cfg, cfg.hidden_size,
                                   self.n_kv * self.head_dim, "col")
        self.v_proj = _make_linear(cfg, cfg.hidden_size,
                                   self.n_kv * self.head_dim, "col")
        self.o_proj = _make_linear(cfg, self.n_heads * self.head_dim,
                                   cfg.hidden_size, "row")

    def forward(self, x, cache=None, attention_mask=None, pos_offsets=None,
                position_ids=None):
        """``cache=(k, v)`` ([B, P, n_kv, hd] each, P may be 0) switches to
        the incremental-decode path: returns (out, (k', v')). A
        ``cache=(k_buf, v_buf, pos)`` triple ([B, L, n_kv, hd] preallocated
        buffers + scalar write position) takes the STATIC-shape path —
        every decode step has identical shapes, which is what lets the
        whole generate loop compile into one program
        (``generation.compiled_generate``). Without a cache, plain causal
        flash attention returns just ``out``.

        ``attention_mask`` (reference mask threading:
        ``python/paddle/nn/layer/transformer.py:84 _convert_attention_mask``
        + ``fused_attention_op.cc`` arbitrary masks):
          * cacheless path — [B, S] 1/0 padding mask routed into the flash
            kernel's segment-id path (pad tokens attend nothing real);
          * static-cache path — [B, L] KEY-liveness mask over the whole
            buffer (False = never attend: pads and unwritten slots ahead
            are excluded by it and by the causal bound).
        ``pos_offsets`` ([B] int32, static path) shifts RoPE positions per
        row — a LEFT-padded row with ``pad`` pads has its first real token
        at position 0, not ``pad`` (the ragged-serving shape).
        ``position_ids`` ([B, S] int32, cacheless path) sets PER-TOKEN
        RoPE positions — the packed-training shape (docs/DATA.md): with a
        packed batch, ``attention_mask`` carries the packer's SEGMENT IDS
        (1, 2, … per document, 0 = pad; the kernel attends only within
        equal ids, which is exactly the 1/0 padding form generalized) and
        ``position_ids`` restarts at 0 inside each document.

        A :class:`~paddle_tpu.ops.paged_attention.PagedLayerCache` takes
        the BLOCK-PAGED path (the continuous-batching serving engine's
        cache form): per-row positions from ``context_lens``, scatter into
        the shared block pools, gather-based attention over each row's
        block table. A
        :class:`~paddle_tpu.ops.paged_attention.RaggedLayerCache` is the
        TOKEN-PACKED form of the same pools (the engine's one unified
        prefill+decode step): ``x`` is ``[1, total_tokens, hidden]``,
        per-token RoPE positions come from the cache, and the read path
        is the Ragged-Paged-Attention Pallas kernel (or its gather
        fallback — ``ops/paged_attention.py``'s impl knob)."""
        if isinstance(cache, RaggedLayerCache):
            if attention_mask is not None or pos_offsets is not None \
                    or position_ids is not None:
                raise NotImplementedError(
                    "the ragged paged path derives per-token positions "
                    "and key liveness from the cache itself")
            return self._ragged_paged_forward(x, cache)
        if isinstance(cache, PagedLayerCache):
            if attention_mask is not None or pos_offsets is not None:
                raise NotImplementedError(
                    "the paged path derives per-row positions and key "
                    "liveness from the cache itself; attention_mask/"
                    "pos_offsets do not apply")
            return self._paged_forward(x, cache)
        if cache is not None and position_ids is not None:
            raise NotImplementedError(
                "position_ids is a cacheless (packed training) argument")
        if cache is not None and len(cache) == 3:
            return self._static_forward(x, cache, attention_mask,
                                        pos_offsets)
        if cache is not None and (attention_mask is not None
                                  or pos_offsets is not None):
            raise NotImplementedError(
                "attention_mask/pos_offsets are supported on the "
                "cacheless (training) and static-cache (compiled "
                "generation) paths; the eager growing-cache path has no "
                "ragged support — use generate_compiled(attention_mask=…)")
        B, S = x.shape[0], x.shape[1]
        q = ops.reshape(self.q_proj(x), [B, S, self.n_heads, self.head_dim])
        k = ops.reshape(self.k_proj(x), [B, S, self.n_kv, self.head_dim])
        v = ops.reshape(self.v_proj(x), [B, S, self.n_kv, self.head_dim])
        if cache is None:
            if position_ids is not None:
                q, k = apply_rotary_positions(
                    q, k, position_ids, self.cfg.rope_theta,
                    table_len=self.cfg.max_position_embeddings)
            else:
                q, k = apply_rotary(q, k, self.cfg.rope_theta)
            if attention_mask is not None:
                # padding -> segment ids (real tokens segment 1, pads 0):
                # the flash kernel's varlen form — pads never mix with
                # real tokens in either direction
                seg = ops.cast(attention_mask, "int32")
                out = F.flash_attention(q, k, v, causal=True,
                                        q_segment_ids=seg,
                                        kv_segment_ids=seg)
            else:
                # GQA served natively by the attention kernel: KV stay at
                # n_kv heads end-to-end (no replication in HBM)
                out = F.flash_attention(q, k, v, causal=True)
            return self.o_proj(ops.reshape(out, [B, S, -1]))
        past_k, past_v = cache
        P = 0 if past_k is None else past_k.shape[1]
        q, k = apply_rotary(q, k, self.cfg.rope_theta, pos_offset=P,
                            table_len=self.cfg.max_position_embeddings)
        if P:
            k_all = ops.concat([past_k, k], axis=1)
            v_all = ops.concat([past_v, v], axis=1)
        else:
            k_all, v_all = k, v
        # offset-causal over [S queries x P+S keys]: query j (absolute
        # position P+j) sees keys <= P+j — covers full prefill (P=0),
        # CHUNKED prefill (P>0, S>1), and decode (S=1: all keys) in one
        # mask (sdpa's tril offset is s_k - s_q = P); GQA heads stay at n_kv
        out = F.scaled_dot_product_attention(q, k_all, v_all, is_causal=True)
        return self.o_proj(ops.reshape(out, [B, S, -1])), (k_all, v_all)

    def _static_forward(self, x, cache, key_mask=None, pos_offsets=None):
        """Fixed-shape KV-cached attention: rotary at a TRACED position,
        dynamic_update_slice into the preallocated buffers, masked
        attention over the whole buffer (keys past ``pos+S`` masked out).
        One tape node; S_q is 1 in decode, the prompt length in prefill.

        Ragged batches: ``key_mask`` [B, L] marks attendable buffer slots
        (pads False), ``pos_offsets`` [B] shifts each row's RoPE positions
        so a left-padded row's first REAL token sits at position 0 —
        buffer INDEX space stays row-independent (every row writes at
        ``pos``..``pos+S``), only position space is per-row."""
        import jax
        import jax.numpy as jnp

        B, S = x.shape[0], x.shape[1]
        q = ops.reshape(self.q_proj(x), [B, S, self.n_heads, self.head_dim])
        k = ops.reshape(self.k_proj(x), [B, S, self.n_kv, self.head_dim])
        v = ops.reshape(self.v_proj(x), [B, S, self.n_kv, self.head_dim])
        k_buf, v_buf, pos = cache
        L = int(k_buf.shape[1])
        hd = self.head_dim
        grp = self.n_heads // self.n_kv
        theta = self.cfg.rope_theta
        scale = 1.0 / math.sqrt(hd)
        ragged = key_mask is not None or pos_offsets is not None
        if ragged:
            if pos_offsets is None:
                pos_offsets = ops.zeros([B], dtype="int32")
            if key_mask is None:
                key_mask = ops.ones([B, L], dtype="bool")

        def f(qa, ka, va, kb, vb, p, *extra):
            p = jnp.reshape(p, ()).astype(jnp.int32)
            if ragged:
                po, km = extra
                # per-row positions: row b, query j -> p + j - pad_b
                pidx = jnp.clip(p + jnp.arange(S)[None, :]
                                - po[:, None].astype(jnp.int32), 0, L - 1)
                cos, sin = _gather_rope(pidx, hd, theta, str(qa.dtype), L)
            else:
                cos_np, sin_np = _rope_cache(L, hd, theta, str(qa.dtype))
                cos = jax.lax.dynamic_slice_in_dim(
                    jnp.asarray(cos_np), p, S)[None, :, None, :]
                sin = jax.lax.dynamic_slice_in_dim(
                    jnp.asarray(sin_np), p, S)[None, :, None, :]

            qr = _rot_interleaved(qa, cos, sin)
            kr = _rot_interleaved(ka, cos, sin)
            kb = jax.lax.dynamic_update_slice(kb, kr, (0, p, 0, 0))
            vb = jax.lax.dynamic_update_slice(vb, va, (0, p, 0, 0))
            qg = qr.reshape(B, S, self.n_kv, grp, hd)
            s = jnp.einsum("bskgh,blkh->bskgl", qg.astype(jnp.float32),
                           kb.astype(jnp.float32)) * scale
            q_pos = p + jnp.arange(S)
            causal = jnp.arange(L)[None, :] <= q_pos[:, None]  # [S, L]
            if ragged:
                live = causal[None, :, :] & km[:, None, :]     # [B, S, L]
                s = jnp.where(live[:, :, None, None, :], s,
                              jnp.finfo(jnp.float32).min)
            else:
                s = jnp.where(causal[None, :, None, None, :], s,
                              jnp.finfo(jnp.float32).min)
            w = jax.nn.softmax(s, axis=-1).astype(va.dtype)
            out = jnp.einsum("bskgl,blkh->bskgh", w, vb)
            return out.reshape(B, S, self.n_heads * hd), kb, vb

        extra = (pos_offsets, key_mask) if ragged else ()
        out, kb2, vb2 = apply_op(f, q, k, v, k_buf, v_buf, pos, *extra,
                                 op_name="static_kv_attention")
        return self.o_proj(out), (kb2, vb2, pos + S)

    def _paged_forward(self, x, cache):
        """Block-paged KV attention (the ``serving.ServingEngine`` path):
        RoPE at per-row traced positions (``context_lens``), scatter the
        new K/V into the shared block pools, masked gather-attention over
        each row's block table (ops/paged_attention.py). Shapes are
        independent of any sequence's length, so one executable serves
        every mix of requests. Cache position is HOST-managed: the
        returned cache carries the same ``context_lens`` — the engine
        advances them after harvesting valid tokens."""
        import jax.numpy as jnp

        from paddle_tpu.ops import paged_attention as pa

        B, S = x.shape[0], x.shape[1]
        q = ops.reshape(self.q_proj(x), [B, S, self.n_heads, self.head_dim])
        k = ops.reshape(self.k_proj(x), [B, S, self.n_kv, self.head_dim])
        v = ops.reshape(self.v_proj(x), [B, S, self.n_kv, self.head_dim])
        hd = self.head_dim
        theta = self.cfg.rope_theta
        table_len = self.cfg.max_position_embeddings
        scale = 1.0 / math.sqrt(hd)

        def f(qa, ka, va, kp, vp, bt, ctx, nlen):
            pos = ctx[:, None].astype(jnp.int32) + \
                jnp.arange(S, dtype=jnp.int32)[None, :]
            cos, sin = _gather_rope(jnp.clip(pos, 0, table_len - 1), hd,
                                    theta, str(qa.dtype), table_len)
            return pa.paged_attention_step(
                _rot_interleaved(qa, cos, sin),
                _rot_interleaved(ka, cos, sin), va, kp, vp,
                bt, ctx, nlen, scale=scale)

        out, kp2, vp2 = apply_op(
            f, q, k, v, cache.k_pool, cache.v_pool, cache.block_tables,
            cache.context_lens, cache.new_lens, op_name="paged_kv_attention")
        return self.o_proj(out), pa.PagedLayerCache(
            kp2, vp2, cache.block_tables, cache.context_lens, cache.new_lens)

    def _ragged_paged_forward(self, x, cache):
        """Token-packed block-paged attention (the unified serving
        step): ``x`` [1, T, hidden] carries every scheduled sequence's
        new tokens back to back; RoPE at the cache's per-token absolute
        positions; scatter the new K/V into the shared pools; then the
        RPA Pallas kernel (or gather fallback) streams each sequence's
        real pages (ops/paged_attention.py dispatches on the impl knob
        at trace time)."""
        import jax.numpy as jnp

        from paddle_tpu.ops import paged_attention as pa

        T = x.shape[1]
        q = ops.reshape(self.q_proj(x), [T, self.n_heads, self.head_dim])
        k = ops.reshape(self.k_proj(x), [T, self.n_kv, self.head_dim])
        v = ops.reshape(self.v_proj(x), [T, self.n_kv, self.head_dim])
        hd = self.head_dim
        theta = self.cfg.rope_theta
        table_len = self.cfg.max_position_embeddings
        scale = 1.0 / math.sqrt(hd)

        if cache.k_scale is not None:
            # int8-KV pools (ISSUE 20): the step quantizes the fresh
            # K/V per (token, head) and threads the scale pools
            # alongside the value pools
            def fq(qa, ka, va, kp, vp, ksc, vsc, bt, cu, ctx, sid, pos,
                   ssq, sbk):
                pidx = jnp.clip(pos.astype(jnp.int32), 0, table_len - 1)
                cos, sin = _gather_rope(pidx[None, :], hd, theta,
                                        str(qa.dtype), table_len)
                cos, sin = cos[0], sin[0]
                return pa.ragged_paged_attention_step(
                    _rot_interleaved(qa, cos, sin),
                    _rot_interleaved(ka, cos, sin), va, kp, vp,
                    bt, cu, ctx, sid, pos, ssq, sbk, scale=scale,
                    k_scale=ksc, v_scale=vsc)

            out, kp2, vp2, ks2, vs2 = apply_op(
                fq, q, k, v, cache.k_pool, cache.v_pool, cache.k_scale,
                cache.v_scale, cache.block_tables, cache.cu_seqlens,
                cache.context_lens, cache.seq_ids, cache.positions,
                cache.step_seq, cache.step_blk,
                op_name="ragged_paged_kv_attention_int8")
            return self.o_proj(ops.reshape(out, [1, T, -1])), \
                pa.RaggedLayerCache(
                    kp2, vp2, cache.block_tables, cache.cu_seqlens,
                    cache.context_lens, cache.seq_ids, cache.positions,
                    cache.step_seq, cache.step_blk, ks2, vs2)

        def f(qa, ka, va, kp, vp, bt, cu, ctx, sid, pos, ssq, sbk):
            pidx = jnp.clip(pos.astype(jnp.int32), 0, table_len - 1)
            cos, sin = _gather_rope(pidx[None, :], hd, theta,
                                    str(qa.dtype), table_len)
            cos, sin = cos[0], sin[0]          # [T, 1, hd/2]
            return pa.ragged_paged_attention_step(
                _rot_interleaved(qa, cos, sin),
                _rot_interleaved(ka, cos, sin), va, kp, vp,
                bt, cu, ctx, sid, pos, ssq, sbk, scale=scale)

        out, kp2, vp2 = apply_op(
            f, q, k, v, cache.k_pool, cache.v_pool, cache.block_tables,
            cache.cu_seqlens, cache.context_lens, cache.seq_ids,
            cache.positions, cache.step_seq, cache.step_blk,
            op_name="ragged_paged_kv_attention")
        # back to [1, T, hidden] for the backbone's residual stream
        return self.o_proj(ops.reshape(out, [1, T, -1])), \
            pa.RaggedLayerCache(
                kp2, vp2, cache.block_tables, cache.cu_seqlens,
                cache.context_lens, cache.seq_ids, cache.positions,
                cache.step_seq, cache.step_blk)


class LlamaMLP(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.gate_proj = _make_linear(cfg, cfg.hidden_size,
                                      cfg.intermediate_size, "col")
        self.up_proj = _make_linear(cfg, cfg.hidden_size,
                                    cfg.intermediate_size, "col")
        self.down_proj = _make_linear(cfg, cfg.intermediate_size,
                                      cfg.hidden_size, "row")

    def forward(self, x):
        # numerics tap seam (docs/OBSERVABILITY.md#numerics): identity
        # unless an instrumented executable is being traced. The gated
        # activation is where Llama-family bf16 ranges blow up first.
        act = numerics.tap(
            "mlp_act",
            ops.multiply(F.silu(self.gate_proj(x)), self.up_proj(x)))
        return self.down_proj(act)


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size,
                                          epsilon=cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   epsilon=cfg.rms_norm_eps)
        self.mlp = LlamaMLP(cfg)

    def forward(self, x, cache=None, attention_mask=None, pos_offsets=None,
                position_ids=None):
        if cache is None:
            x = ops.add(x, numerics.tap(
                "attn", self.self_attn(self.input_layernorm(x),
                                       attention_mask=attention_mask,
                                       position_ids=position_ids)))
            x = ops.add(x, numerics.tap(
                "mlp", self.mlp(self.post_attention_layernorm(x))))
            return numerics.tap("resid", x)
        attn_out, new_cache = self.self_attn(self.input_layernorm(x),
                                             cache=cache,
                                             attention_mask=attention_mask,
                                             pos_offsets=pos_offsets)
        x = ops.add(x, numerics.tap("attn", attn_out))
        x = ops.add(x, numerics.tap(
            "mlp", self.mlp(self.post_attention_layernorm(x))))
        return numerics.tap("resid", x), new_cache


class LlamaModel(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.tensor_parallel:
            from paddle_tpu.distributed.fleet import VocabParallelEmbedding
            self.embed_tokens = VocabParallelEmbedding(cfg.vocab_size,
                                                       cfg.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(cfg)
                                    for _ in range(cfg.num_hidden_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, epsilon=cfg.rms_norm_eps)

    def forward(self, input_ids, caches=None, attention_mask=None,
                pos_offsets=None, position_ids=None):
        """``attention_mask``: [B, S] 1/0 padding mask — or packed
        SEGMENT IDS (docs/DATA.md) — on the cacheless path (flash
        segment ids), [B, L] buffer key-liveness mask on the static-cache
        path; ``pos_offsets``: [B] per-row RoPE shift for left-padded
        ragged batches (static path only); ``position_ids``: [B, S]
        per-token RoPE positions (cacheless packed path only). Reference
        mask threading: ``nn/layer/transformer.py:84``."""
        x = numerics.tap("embed", self.embed_tokens(input_ids))
        if caches is None:
            kw = {}
            if attention_mask is not None:
                kw["attention_mask"] = attention_mask
            if position_ids is not None:
                kw["position_ids"] = position_ids
            for i, layer in enumerate(self.layers):
                with numerics.scope(f"layers.{i}"):
                    if self.cfg.recompute and self.training:
                        from paddle_tpu.distributed.fleet import recompute
                        # taps inside a remat region would leak its
                        # tracers through the collector — suppress them
                        # and tap the region's output instead
                        with numerics.suppress():
                            x = recompute(layer, x, **kw) if kw \
                                else recompute(layer, x)
                        x = numerics.tap("resid", x)
                    else:
                        x = layer(x, **kw)
            return numerics.tap("final_norm", self.norm(x))
        if len(caches) != len(self.layers):
            raise ValueError(
                f"caches has {len(caches)} entries for "
                f"{len(self.layers)} layers")
        new_caches = []
        for i, (layer, c) in enumerate(zip(self.layers, caches)):
            with numerics.scope(f"layers.{i}"):
                x, nc = layer(x, cache=c, attention_mask=attention_mask,
                              pos_offsets=pos_offsets)
            new_caches.append(nc)
        return numerics.tap("final_norm", self.norm(x)), new_caches


class LlamaForCausalLM(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if cfg.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = _make_linear(cfg, cfg.hidden_size,
                                        cfg.vocab_size, "col")
        self._init_weights()

    def _init_weights(self):
        """Llama recipe init: every 2-D weight (embedding, projections)
        ~ N(0, initializer_range); norms stay at ones. Without this the
        tied logits head scales like sqrt(d) and the initial loss explodes
        (HF LlamaPreTrainedModel._init_weights semantics)."""
        from paddle_tpu.nn import initializer as I
        init = I.Normal(std=self.cfg.initializer_range)
        for _, p in self.named_parameters():
            if len(p.shape) == 2:
                p.set_value(init(p.shape))  # set_value casts to p's dtype

    # vocab size from which the fused chunked CE pays for itself (below
    # it, the [T, V] logits are small and the plain path keeps `logits`
    # available to callers)
    _FUSED_CE_MIN_VOCAB = 32768

    def forward(self, input_ids, labels=None, attention_mask=None,
                position_ids=None):
        """``attention_mask`` [B, S] (1 real / 0 pad) masks padded tokens
        out of attention (flash segment ids); set padded label positions
        to -100 so the loss ignores them too. A PACKED batch
        (``paddle_tpu.data`` pipeline, docs/DATA.md) passes segment ids
        as ``attention_mask`` and per-document ``position_ids`` — this
        signature matches the packer's batch keys, so
        ``Model.prepare(opt, loss=None)`` + ``fit(pipeline)`` feeds
        batches straight through as kwargs."""
        h = self.model(input_ids, attention_mask=attention_mask,
                       position_ids=position_ids)
        if labels is not None and labels.shape[1] < 2:
            raise ValueError(
                "causal-LM loss needs sequences of length >= 2 (the "
                "internal shift leaves nothing to predict for length 1)")
        if (labels is not None and self.lm_head is None
                and self.cfg.vocab_size >= self._FUSED_CE_MIN_VOCAB):
            # large tied vocab: fused chunked matmul-CE — the [T, V]
            # logits never materialize (ops/fused_ce.py). Returns
            # (None, loss): producing logits would rebuild the tensor the
            # fusion exists to avoid.
            from paddle_tpu.ops.fused_ce import causal_lm_loss
            w = self.model.embed_tokens.weight
            loss = apply_op(causal_lm_loss, h, w, labels,
                            op_name="fused_causal_ce")
            return None, loss
        logits = numerics.tap("logits", self._logits(h))
        if labels is None:
            return logits
        # HF-style contract: labels == input_ids; the shift happens HERE
        # (position t predicts token t+1) — do not pre-shift labels
        loss = F.cross_entropy(
            ops.reshape(logits[:, :-1], [-1, logits.shape[-1]]),
            ops.reshape(labels[:, 1:], [-1]))
        return logits, loss

    def _logits(self, h):
        if self.lm_head is not None:
            return self.lm_head(h)
        return ops.matmul(h, ops.transpose(
            self.model.embed_tokens.weight, [1, 0]))

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 1.0, eos_token_id=None):
        """KV-cached autoregressive decoding (greedy when
        ``temperature == 0``); see models/generation.py for the loop."""
        from .generation import generate_loop

        def prefill(ids):
            caches = [(None, None)] * self.cfg.num_hidden_layers
            h, caches = self.model(ids, caches=caches)
            return self._logits(h[:, -1:]), caches

        def decode(tok, caches):
            h, caches = self.model(tok, caches=caches)
            return self._logits(h), caches

        return generate_loop(prefill, decode, input_ids, max_new_tokens,
                             temperature, top_k, top_p, eos_token_id)

    def generate_compiled(self, input_ids, max_new_tokens: int = 32,
                          temperature: float = 0.0, top_k: int = 0,
                          top_p: float = 1.0, eos_token_id=None,
                          prefill_chunk: int = 0, attention_mask=None):
        """Whole-loop compiled generation: prefill + every decode step in
        ONE jitted program over static KV buffers (see
        ``generation.compiled_generate``). Greedy output is token-for-token
        equal to ``generate``; ``attention_mask`` serves a LEFT-padded
        batch of unequal prompts, each row equal to its solo run."""
        from .generation import compiled_generate
        return compiled_generate(self, input_ids, max_new_tokens,
                                 temperature, top_k, top_p, eos_token_id,
                                 prefill_chunk=prefill_chunk,
                                 attention_mask=attention_mask)

    @staticmethod
    def flops_per_token(cfg: LlamaConfig) -> float:
        """Analytic fwd FLOPs/token (2 MAC) — feeds MFU accounting."""
        d, f, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
        hd = d // cfg.num_attention_heads
        kv = cfg.num_key_value_heads * hd
        per_layer = 2 * d * (d + 2 * kv + d) + 2 * 3 * d * f
        return L * per_layer + 2 * d * cfg.vocab_size
