"""DiT — diffusion transformer (BASELINE.md "DiT/SD-3" config).

Standard DiT-style architecture: patchify → N adaLN-Zero transformer blocks
conditioned on (timestep, class) → unpatchify to the noise prediction. The
reference ecosystem runs this family through PaddleMIX; in-tree the relevant
capability seam is the fused attention stack (SURVEY.md §2.10 item 6), which
here is the same flash-attention path the LLM families use.

TPU notes: all shapes are static (patch grid fixed by config), timestep
embedding is a single fused tape node, and adaLN modulation is elementwise —
XLA fuses it into the surrounding matmuls.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax.numpy as jnp

from paddle_tpu.core.autograd import apply_op
from paddle_tpu import ops
from paddle_tpu import nn

__all__ = ["DiTConfig", "DiT"]


@dataclass
class DiTConfig:
    input_size: int = 32          # latent spatial size
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 1152
    depth: int = 28
    num_heads: int = 16
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    learn_sigma: bool = True

    @staticmethod
    def dit_xl_2(**kw) -> "DiTConfig":
        return DiTConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "DiTConfig":
        base = dict(input_size=8, patch_size=2, in_channels=4,
                         hidden_size=32, depth=2, num_heads=2,
                         num_classes=10)
        base.update(kw)
        return DiTConfig(**base)


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep embedding, [B] -> [B, dim]."""
    def f(ta):
        half = dim // 2
        freqs = jnp.exp(-math.log(max_period) *
                        jnp.arange(half, dtype=jnp.float32) / half)
        args = ta.astype(jnp.float32)[:, None] * freqs[None]
        return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    return apply_op(f, t, op_name="timestep_embedding")


def _modulate(x, shift, scl):
    # x: [B,N,H], shift/scl: [B,H]
    return ops.add(ops.multiply(x, ops.unsqueeze(ops.add(
        ops.ones_like(scl), scl), 1)), ops.unsqueeze(shift, 1))


class TimestepEmbedder(nn.Layer):
    def __init__(self, hidden_size, freq_dim=256):
        super().__init__()
        self.freq_dim = freq_dim
        self.mlp = nn.Sequential(nn.Linear(freq_dim, hidden_size), nn.Silu(),
                                 nn.Linear(hidden_size, hidden_size))

    def forward(self, t):
        return self.mlp(timestep_embedding(t, self.freq_dim))


class LabelEmbedder(nn.Layer):
    def __init__(self, num_classes, hidden_size):
        super().__init__()
        # +1 slot: the null/unconditional class for CFG dropout
        self.embedding_table = nn.Embedding(num_classes + 1, hidden_size)

    def forward(self, labels):
        return self.embedding_table(labels)


class DiTBlock(nn.Layer):
    """Transformer block with adaLN-Zero conditioning."""

    def __init__(self, hidden_size, num_heads, mlp_ratio):
        super().__init__()
        self.norm1 = nn.LayerNorm(hidden_size, weight_attr=False,
                                  bias_attr=False)
        self.attn = nn.MultiHeadAttention(hidden_size, num_heads)
        self.norm2 = nn.LayerNorm(hidden_size, weight_attr=False,
                                  bias_attr=False)
        mlp_dim = int(hidden_size * mlp_ratio)
        self.mlp = nn.Sequential(nn.Linear(hidden_size, mlp_dim), nn.GELU(),
                                 nn.Linear(mlp_dim, hidden_size))
        # adaLN-Zero: projection initialized to zero so each block starts
        # as identity
        self.adaLN_modulation = nn.Sequential(
            nn.Silu(), nn.Linear(hidden_size, 6 * hidden_size,
                                 weight_attr=nn.initializer.Constant(0.0),
                                 bias_attr=nn.initializer.Constant(0.0)))

    def forward(self, x, c):
        mods = ops.chunk(self.adaLN_modulation(c), 6, axis=-1)
        shift_msa, scale_msa, gate_msa, shift_mlp, scale_mlp, gate_mlp = mods
        h = _modulate(self.norm1(x), shift_msa, scale_msa)
        x = ops.add(x, ops.multiply(ops.unsqueeze(gate_msa, 1),
                                    self.attn(h)))
        h = _modulate(self.norm2(x), shift_mlp, scale_mlp)
        x = ops.add(x, ops.multiply(ops.unsqueeze(gate_mlp, 1), self.mlp(h)))
        return x


class FinalLayer(nn.Layer):
    def __init__(self, hidden_size, patch_size, out_channels):
        super().__init__()
        self.norm_final = nn.LayerNorm(hidden_size, weight_attr=False,
                                       bias_attr=False)
        self.linear = nn.Linear(hidden_size,
                                patch_size * patch_size * out_channels,
                                weight_attr=nn.initializer.Constant(0.0),
                                bias_attr=nn.initializer.Constant(0.0))
        self.adaLN_modulation = nn.Sequential(
            nn.Silu(), nn.Linear(hidden_size, 2 * hidden_size,
                                 weight_attr=nn.initializer.Constant(0.0),
                                 bias_attr=nn.initializer.Constant(0.0)))

    def forward(self, x, c):
        shift, scl = ops.chunk(self.adaLN_modulation(c), 2, axis=-1)
        return self.linear(_modulate(self.norm_final(x), shift, scl))


class DiT(nn.Layer):
    def __init__(self, cfg: DiTConfig):
        super().__init__()
        self.cfg = cfg
        self.out_channels = cfg.in_channels * (2 if cfg.learn_sigma else 1)
        self.x_embedder = nn.Conv2D(cfg.in_channels, cfg.hidden_size,
                                    kernel_size=cfg.patch_size,
                                    stride=cfg.patch_size)
        self.t_embedder = TimestepEmbedder(cfg.hidden_size)
        self.y_embedder = LabelEmbedder(cfg.num_classes, cfg.hidden_size)
        n_patches = (cfg.input_size // cfg.patch_size) ** 2
        self.pos_embed = self.create_parameter(
            shape=[1, n_patches, cfg.hidden_size],
            default_initializer=nn.initializer.Normal(std=0.02))
        self.blocks = nn.LayerList([
            DiTBlock(cfg.hidden_size, cfg.num_heads, cfg.mlp_ratio)
            for _ in range(cfg.depth)])
        self.final_layer = FinalLayer(cfg.hidden_size, cfg.patch_size,
                                      self.out_channels)

    def unpatchify(self, x):
        c, p = self.out_channels, self.cfg.patch_size
        hw = self.cfg.input_size // p
        x = ops.reshape(x, [x.shape[0], hw, hw, p, p, c])
        x = ops.transpose(x, [0, 5, 1, 3, 2, 4])  # [B,C,hw,p,hw,p]
        return ops.reshape(x, [x.shape[0], c, hw * p, hw * p])

    def forward(self, x, t, y):
        """x: [B,C,H,W] latents; t: [B] timesteps; y: [B] class ids."""
        x = self.x_embedder(x)                       # [B,H,h',w']
        B, H = x.shape[0], x.shape[1]
        x = ops.transpose(ops.reshape(x, [B, H, -1]), [0, 2, 1])  # [B,N,H]
        x = ops.add(x, self.pos_embed)
        c = ops.add(self.t_embedder(t), self.y_embedder(y))
        for block in self.blocks:
            x = block(x, c)
        x = self.final_layer(x, c)
        return self.unpatchify(x)
